"""bench.py orchestration semantics (the round's evidence pipeline).

The parent/child protocol must never lose completed segments, never let a
CPU number masquerade as a TPU regression, and always emit one parseable
JSON line — these tests pin the _Assembly state machine and the child's
per-segment streaming without touching any accelerator.
"""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_absorb_failed_tpu_segment_stays_pending(tmp_path, monkeypatch):
    """An error-only payload on the TPU attempt must NOT mark the segment
    done — the CPU fallback re-runs it (round-4 regression guard)."""
    b = _load_bench()
    monkeypatch.setattr(b, "PARTIAL_PATH", str(tmp_path / "partial.json"))
    asm = b._Assembly()
    asm.absorb({"segment": "init", "data": {"platform": "tpu", "n_dev": 1}}, False)
    seg = asm.absorb(
        {"segment": "gbdt", "data": {"gbdt_error": "relay flapped"}}, False
    )
    assert seg == ""  # caller keeps it in `remaining`
    assert "gbdt" not in asm.done
    assert asm.extra["gbdt_error"] == "relay flapped"
    # the CPU fallback then succeeds: stale error is dropped
    seg = asm.absorb(
        {"segment": "gbdt", "data": {"gbdt_trees_per_sec": 5.0}}, True
    )
    assert seg == "gbdt" and "gbdt" in asm.done
    assert "gbdt_error" not in asm.extra
    assert asm.segments_cpu == ["gbdt"]


def test_emit_forces_fallback_when_featurizer_missing(capsys, tmp_path, monkeypatch):
    """value=0.0 with fallback=false would read as a measured TPU
    regression; a missing featurizer number must force the fallback flag."""
    b = _load_bench()
    monkeypatch.setattr(b, "PARTIAL_PATH", str(tmp_path / "partial.json"))
    asm = b._Assembly()
    asm.absorb({"segment": "init", "data": {"platform": "tpu", "n_dev": 1}}, False)
    asm.absorb({"segment": "hist", "data": {"hist_gcells_per_sec": 1.5}}, False)
    asm.emit()
    line = capsys.readouterr().out.strip()
    d = json.loads(line)
    assert d["value"] == 0.0
    assert d["extra"]["fallback"] is True
    assert "featurizer" in d["extra"]["segments_missing"]
    assert d["extra"]["hist_gcells_per_sec"] == 1.5


def test_emit_idempotent(capsys):
    """Signal handler + normal path may both call emit: one line only."""
    b = _load_bench()
    asm = b._Assembly()
    asm.emit()
    asm.emit()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1


def test_child_streams_segment_lines():
    """The child emits init + one line per requested segment + done, each
    a self-contained JSON record (the incremental-harvest contract)."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("PYTHONPATH", "PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "XLA_FLAGS")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["MMLSPARK_BENCH_SEGMENTS"] = "serving"
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--child"],
        env=env, capture_output=True, text=True, timeout=240,
    )
    assert p.returncode == 0, p.stderr[-1500:]
    recs = [json.loads(ln) for ln in p.stdout.splitlines() if ln.startswith("{")]
    segs = [r["segment"] for r in recs]
    assert segs == ["starting", "init", "serving", "done"]
    serving = recs[2]["data"]
    assert "serving_p50_ms" in serving
    assert "serving_gateway_p50_ms" in serving  # the gateway-overhead budget

class _FakeProc:
    def __init__(self, running: bool):
        self._running = running

    def poll(self):
        return None if self._running else 0

    def wait(self, timeout=None):
        if self._running:
            raise subprocess.TimeoutExpired("fake", timeout)
        return 0


class _FakeChild:
    """Replays scripted records; None = watchdog timeout/EOF. ``running``
    is the proc state _harvest sees when deciding the engaged guard."""

    def __init__(self, records, running_at_end: bool):
        self._records = list(records)
        self.proc = _FakeProc(running_at_end)
        self.killed = False

    def next_record(self, timeout_s):
        if self._records:
            return self._records.pop(0)
        return None

    def kill(self):
        self.killed = True
        self.proc._running = False


def test_harvest_killed_midflight_reports_engaged(tmp_path, monkeypatch):
    """A child killed while running strands the chip claim -> _harvest
    returns True and main() skips the TPU retry — whether or not it got
    as far as emitting lines (a pre-init kill can orphan a queued
    claim)."""
    import time as _time

    b = _load_bench()
    monkeypatch.setattr(b, "PARTIAL_PATH", str(tmp_path / "p.json"))
    asm = b._Assembly()
    child = _FakeChild(
        [{"segment": "starting", "data": {}},
         {"segment": "init", "data": {"platform": "tpu", "n_dev": 1}}],
        running_at_end=True,  # hung mid-segment, parent kills it
    )
    remaining = list(b.TPU_ORDER)
    engaged = b._harvest(child, asm, remaining,
                         _time.monotonic() + 60, False, b.TPU_ORDER)
    assert engaged is True
    assert child.killed
    assert remaining == list(b.TPU_ORDER)  # nothing completed
    # the pre-line variant: hung before any output, killed -> still engaged
    silent = _FakeChild([], running_at_end=True)
    assert b._harvest(silent, asm, list(b.TPU_ORDER),
                      _time.monotonic() + 60, False, b.TPU_ORDER) is True


def test_harvest_clean_exit_keeps_retry(tmp_path, monkeypatch):
    """A child that ran to 'done' (with one failed segment) and exited on
    its own released the claim -> returns False, the TPU retry stays."""
    import time as _time

    b = _load_bench()
    monkeypatch.setattr(b, "PARTIAL_PATH", str(tmp_path / "p.json"))
    asm = b._Assembly()
    recs = [{"segment": "starting", "data": {}},
            {"segment": "init", "data": {"platform": "tpu", "n_dev": 1}}]
    for seg in b.TPU_ORDER:
        if seg == "gbdt":  # one transient failure: stays in remaining
            recs.append({"segment": seg, "data": {"gbdt_error": "flap"}})
        else:
            recs.append({"segment": seg, "data": {f"{seg}_x": 1.0}})
    recs.append({"segment": "done", "data": {}})
    child = _FakeChild(recs, running_at_end=False)  # exits cleanly
    remaining = list(b.TPU_ORDER)
    engaged = b._harvest(child, asm, remaining,
                         _time.monotonic() + 60, False, b.TPU_ORDER)
    assert engaged is False
    assert remaining == ["gbdt"]  # only the failed segment is left


def test_cpu_fallback_survives_one_stalled_segment(tmp_path, monkeypatch,
                                                   capsys):
    """A segment that hangs its watchdog on the CPU fallback must not
    discard everything queued after it: the parent records the stuck
    segment and runs the rest in a fresh child — the emitted line shows
    the stalled segment in segments_missing (and segments_stalled), with
    every other segment completed."""
    b = _load_bench()
    monkeypatch.setattr(b, "PARTIAL_PATH", str(tmp_path / "p.json"))
    monkeypatch.setattr(b, "TOTAL_TPU_BUDGET_S", 0)  # skip the TPU phase

    stall = b.CPU_ORDER[1]

    class _Scripted(_FakeChild):
        stderr_tail = ""

    def _recs(segs):
        recs = [{"segment": "init",
                 "data": {"platform": "cpu", "n_dev": 1}}]
        recs += [{"segment": s, "data": {f"{s}_x": 1.0}} for s in segs]
        return recs

    # child 1 completes the first segment, then hangs at `stall`
    # (next_record -> None = watchdog miss); child 2 gets the rest
    children = [
        _Scripted(_recs([b.CPU_ORDER[0]]), running_at_end=True),
        _Scripted(
            _recs([s for s in b.CPU_ORDER[2:]])
            + [{"segment": "done", "data": {}}],
            running_at_end=False,
        ),
    ]
    spawned = []

    def _fake_child(remaining, env):
        spawned.append(list(remaining))
        return children.pop(0)

    monkeypatch.setattr(b, "_Child", _fake_child)
    b.main()
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["extra"]["segments_missing"] == [stall]
    assert out["extra"]["segments_stalled"] == [stall]
    # every segment after the stalled one was re-offered to child 2
    assert spawned[1] == [s for s in b.CPU_ORDER[2:]]
    assert f"{b.CPU_ORDER[-1]}_x" in out["extra"]


def test_stalled_child_yields_stall_stacks_naming_the_wedge(tmp_path,
                                                            monkeypatch):
    """Stall forensics through the real parent/child pair: a child
    deliberately wedged inside a segment (MMLSPARK_BENCH_WEDGE_SEGMENT)
    is SIGUSR2'd by the harvest loop before the kill, and the collected
    dump lands in extra["stall_stacks"] naming _deliberate_wedge as the
    blocked frame."""
    import time as _time

    b = _load_bench()
    monkeypatch.setattr(b, "PARTIAL_PATH", str(tmp_path / "p.json"))
    monkeypatch.setattr(b, "SEGMENT_TIMEOUT_S", 4)
    monkeypatch.setattr(b, "SEGMENT_TIMEOUTS", {})
    monkeypatch.setenv("MMLSPARK_FLIGHTREC_DIR", str(tmp_path / "spool"))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("PYTHONPATH", "PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS",
                     "XLA_FLAGS")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["MMLSPARK_BENCH_WEDGE_SEGMENT"] = "serving"
    env["MMLSPARK_FLIGHTREC_DIR"] = str(tmp_path / "spool")
    asm = b._Assembly()
    child = b._Child(["serving"], env)
    remaining = ["serving"]
    try:
        engaged = b._harvest(child, asm, remaining,
                             _time.monotonic() + 60, True, ["serving"])
    finally:
        child.kill()
    assert engaged is True  # wedged child had to be killed
    assert remaining == ["serving"]
    stacks = asm.extra["stall_stacks"]["serving"]
    assert "_deliberate_wedge" in stacks["MainThread"]


def test_collect_stall_stacks_tolerates_pidless_child():
    """_FakeChild-style children (and already-dead ones) have no
    signalable pid: forensics returns None fast instead of raising —
    the fallback-survival path must stay untouched."""
    b = _load_bench()
    assert b._collect_stall_stacks(
        _FakeChild([], running_at_end=True)
    ) is None


def test_segment_orders_cover_all_segments():
    """TPU_ORDER and CPU_ORDER must each be a permutation of SEGMENTS —
    a segment missing from either order would silently never run on
    that attempt."""
    b = _load_bench()
    assert sorted(b.TPU_ORDER) == sorted(b.SEGMENTS)
    assert sorted(b.CPU_ORDER) == sorted(b.SEGMENTS)
    assert set(b.SEGMENTS) == set(b.SEGMENT_FNS)
