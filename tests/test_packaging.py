"""Wheel packaging (build.sbt:199-207 packagePython analogue): the wheel
must build and carry the packaged zoo checkpoint + native kernel sources.
"""

import glob
import subprocess
import sys
import zipfile

import pytest


def test_wheel_builds_with_data(tmp_path):
    pytest.importorskip("setuptools")
    proc = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-build-isolation",
         "--no-deps", "-w", str(tmp_path), "."],
        capture_output=True, text=True, timeout=600,
        cwd=__file__.rsplit("/tests/", 1)[0],
    )
    if proc.returncode != 0 and "No module named pip" in proc.stderr:
        pytest.skip("pip unavailable")
    assert proc.returncode == 0, proc.stderr[-2000:]
    wheels = glob.glob(str(tmp_path / "*.whl"))
    assert len(wheels) == 1
    names = zipfile.ZipFile(wheels[0]).namelist()
    assert any(n.endswith("downloader/builtin/ResNet8_Digits.msgpack") for n in names)
    assert any(n.endswith("downloader/builtin/ResNet8_Digits.schema.json") for n in names)
    assert any(n.endswith(".cc") for n in names)  # native sources ship
    assert any(n.endswith("version.py") for n in names)


def test_version_importable():
    import mmlspark_tpu

    assert mmlspark_tpu.__version__
