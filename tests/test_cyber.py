"""cyber/ tests: ALS factorization quality, scalers, complement sampling,
AccessAnomaly end-to-end separation of anomalous accesses."""

from __future__ import annotations

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.cyber import (
    AccessAnomaly,
    ComplementSampler,
    LinearScalarScaler,
    StandardScalarScaler,
    als_predict,
    als_train,
    complement_sample,
    synthetic_access_df,
)


class TestALS:
    def test_reconstructs_low_rank(self):
        rng = np.random.RandomState(0)
        true_u = rng.randn(20, 3).astype(np.float32)
        true_v = rng.randn(15, 3).astype(np.float32)
        r = true_u @ true_v.T
        uf, vf = als_train(r, mask=np.ones_like(r), rank=3, iters=15, reg=0.01)
        np.testing.assert_allclose(uf @ vf.T, r, atol=0.15)

    def test_masked_completion(self):
        rng = np.random.RandomState(1)
        true_u = rng.randn(25, 2).astype(np.float32)
        true_v = rng.randn(18, 2).astype(np.float32)
        r = true_u @ true_v.T
        mask = (rng.rand(25, 18) < 0.6).astype(np.float32)
        uf, vf = als_train(r * mask, mask=mask, rank=2, iters=25, reg=0.01)
        # held-out entries reconstructed from low-rank structure
        err = np.abs((uf @ vf.T) - r)[mask == 0]
        assert np.median(err) < 0.5

    def test_implicit_ranks_seen_higher(self):
        rng = np.random.RandomState(2)
        r = (rng.rand(30, 20) < 0.2).astype(np.float32)
        uf, vf = als_train(r, rank=5, iters=10, implicit=True, alpha=20.0)
        pred = uf @ vf.T
        assert pred[r > 0].mean() > pred[r == 0].mean() + 0.2

    def test_als_predict_pairs(self):
        uf = np.array([[1.0, 0.0], [0.0, 1.0]])
        vf = np.array([[2.0, 0.0], [0.0, 3.0]])
        out = als_predict(uf, vf, np.array([0, 1]), np.array([0, 1]))
        np.testing.assert_allclose(out, [2.0, 3.0])


class TestScalers:
    def test_standard_per_tenant(self):
        df = DataFrame.from_dict(
            {
                "tenant": np.array([0, 0, 0, 1, 1, 1]),
                "score": np.array([1.0, 2.0, 3.0, 10.0, 20.0, 30.0]),
            }
        )
        model = StandardScalarScaler(input_col="score", partition_key="tenant").fit(df)
        out = model.transform(df)["score_scaled"]
        for t in (0, 1):
            sel = df["tenant"] == t
            assert abs(out[sel].mean()) < 1e-9
            np.testing.assert_allclose(out[sel].std(), 1.0, atol=1e-9)

    def test_linear_range(self):
        df = DataFrame.from_dict({"v": np.array([5.0, 10.0, 15.0])})
        model = LinearScalarScaler(
            input_col="v", min_required_value=0.0, max_required_value=1.0
        ).fit(df)
        np.testing.assert_allclose(model.transform(df)["v_scaled"], [0.0, 0.5, 1.0])

    def test_save_load(self, tmp_path):
        df = DataFrame.from_dict({"v": np.array([1.0, 3.0])})
        model = StandardScalarScaler(input_col="v").fit(df)
        model.save(str(tmp_path / "s"))
        from mmlspark_tpu import load_stage

        m2 = load_stage(str(tmp_path / "s"))
        np.testing.assert_allclose(
            model.transform(df)["v_scaled"], m2.transform(df)["v_scaled"]
        )


class TestComplement:
    def test_samples_only_unseen(self):
        users = np.array([0, 0, 1], np.int64)
        items = np.array([0, 1, 0], np.int64)
        cu, ci = complement_sample(users, items, 2, 2, factor=10.0, seed=0)
        seen = set(zip(users.tolist(), items.tolist()))
        got = set(zip(cu.tolist(), ci.tolist()))
        assert got and not (got & seen)
        assert got <= {(1, 1)}  # only one unseen cell exists

    def test_transformer_appends_rows(self):
        df = DataFrame.from_dict(
            {
                "user_idx": np.array([0, 1, 2], np.int64),
                "res_idx": np.array([0, 1, 2], np.int64),
                "rating": np.array([1.0, 1.0, 1.0]),
            }
        )
        out = ComplementSampler(factor=2.0).transform(df)
        assert out.count() > 3
        added = out["rating"][3:]
        assert (added == 0.0).all()


class TestAccessAnomaly:
    @pytest.mark.parametrize("implicit", [False, True])
    def test_cross_department_scores_higher(self, implicit):
        df = synthetic_access_df(
            n_departments=3, users_per_dept=8, resources_per_dept=6,
            accesses_per_user=25, cross_dept_prob=0.0, seed=0,
        )
        model = AccessAnomaly(rank=6, max_iter=10, implicit=implicit, seed=1).fit(df)

        # in-department (normal) probes vs cross-department (anomalous) probes
        normal = DataFrame.from_dict(
            {
                "tenant": np.zeros(3, np.int64),
                "user": np.array(["t0_d0_u0", "t0_d1_u1", "t0_d2_u2"], dtype=object),
                "res": np.array(["t0_d0_r0", "t0_d1_r1", "t0_d2_r2"], dtype=object),
            }
        )
        anomalous = DataFrame.from_dict(
            {
                "tenant": np.zeros(3, np.int64),
                "user": np.array(["t0_d0_u0", "t0_d1_u1", "t0_d2_u2"], dtype=object),
                "res": np.array(["t0_d1_r0", "t0_d2_r1", "t0_d0_r2"], dtype=object),
            }
        )
        ns = model.transform(normal)["anomaly_score"]
        xs = model.transform(anomalous)["anomaly_score"]
        assert xs.mean() > ns.mean() + 0.5, (ns, xs)

    def test_unseen_entities_neutral(self):
        df = synthetic_access_df(users_per_dept=4, accesses_per_user=10)
        model = AccessAnomaly(rank=4, max_iter=5).fit(df)
        probe = DataFrame.from_dict(
            {
                "tenant": np.array([0, 99], np.int64),
                "user": np.array(["nobody", "t0_d0_u0"], dtype=object),
                "res": np.array(["t0_d0_r0", "t0_d0_r0"], dtype=object),
            }
        )
        scores = model.transform(probe)["anomaly_score"]
        assert (scores == 0.0).all()

    def test_save_load(self, tmp_path):
        df = synthetic_access_df(users_per_dept=4, accesses_per_user=10)
        model = AccessAnomaly(rank=4, max_iter=5).fit(df)
        model.save(str(tmp_path / "aa"))
        from mmlspark_tpu import load_stage

        m2 = load_stage(str(tmp_path / "aa"))
        probe = df  # score the training rows
        np.testing.assert_allclose(
            model.transform(probe)["anomaly_score"],
            m2.transform(probe)["anomaly_score"],
            atol=1e-6,
        )


def test_als_coo_matches_dense():
    """Sparse COO ALS == dense ALS on the same observations (explicit)."""
    import numpy as np

    from mmlspark_tpu.cyber.als import als_train, als_train_coo

    rng = np.random.default_rng(0)
    U, I = 12, 9
    mask = rng.random((U, I)) < 0.4
    r = np.where(mask, rng.integers(1, 5, size=(U, I)).astype(np.float32), 0.0)
    uf1, if1 = als_train(r, rank=4, iters=8, reg=0.1, seed=3)
    eu, ei = np.nonzero(mask)
    uf2, if2 = als_train_coo(eu, ei, r[eu, ei], U, I, rank=4, iters=8, reg=0.1, seed=3)
    np.testing.assert_allclose(uf1 @ if1.T, uf2 @ if2.T, rtol=1e-3, atol=1e-3)


def test_als_coo_implicit_matches_dense():
    import numpy as np

    from mmlspark_tpu.cyber.als import als_train, als_train_coo

    rng = np.random.default_rng(1)
    U, I = 10, 8
    mask = rng.random((U, I)) < 0.35
    r = np.where(mask, rng.integers(1, 4, size=(U, I)).astype(np.float32), 0.0)
    uf1, if1 = als_train(r, rank=3, iters=6, implicit=True, alpha=10.0, seed=5)
    eu, ei = np.nonzero(mask)
    uf2, if2 = als_train_coo(
        eu, ei, r[eu, ei], U, I, rank=3, iters=6, implicit=True, alpha=10.0, seed=5
    )
    np.testing.assert_allclose(uf1 @ if1.T, uf2 @ if2.T, rtol=1e-3, atol=1e-3)
