"""BiLSTM sequence tagger: recurrence via lax.scan under jit, padded
batches with masked loss/serving, and batched eval through XLAModel
(mirrors the reference's BiLSTM-through-CNTKModel sample)."""

import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.models.sequence import BiLSTMTagger, train_tagger


def _task(n=64, t=12, vocab=50, seed=0):
    """Synthetic entity task needing LEFT context: tokens >= 40 are tag 1;
    the token AFTER trigger token 5 is tag 2; else 0."""
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, vocab, (n, t))
    tags = np.where(tokens >= 40, 1, 0)
    trig = np.zeros_like(tokens)
    trig[:, 1:] = tokens[:, :-1] == 5
    tags = np.where(trig.astype(bool) & (tags == 0), 2, tags)
    lens = rng.integers(6, t + 1, (n,))
    return tokens, tags, lens


def test_tagger_learns_contextual_tags():
    tokens, tags, lens = _task()
    model, vs = train_tagger(
        tokens, tags, vocab_size=50, num_tags=3, seq_lengths=lens,
        num_steps=150,
    )
    out = model.apply(vs, jnp.asarray(tokens), jnp.asarray(lens))
    pred = np.asarray(out["logits"].argmax(-1))
    mask = np.arange(tokens.shape[1])[None, :] < lens[:, None]
    acc = (pred == tags)[mask].mean()
    assert acc > 0.9, acc
    assert set(out) == set(BiLSTMTagger.LAYER_NAMES)


def test_padding_does_not_leak_into_real_positions():
    """The same sequences padded to a longer T must tag real positions
    identically (scan + seq_lengths masking; the backward direction is
    the dangerous one)."""
    tokens, tags, lens = _task(n=16, t=10)
    model, vs = train_tagger(
        tokens, tags, vocab_size=50, num_tags=3, seq_lengths=lens,
        num_steps=40,
    )
    t_pad = 16
    tokens_p = np.zeros((16, t_pad), tokens.dtype)
    tokens_p[:, :10] = tokens
    out = model.apply(vs, jnp.asarray(tokens), jnp.asarray(lens))
    out_p = model.apply(vs, jnp.asarray(tokens_p), jnp.asarray(lens))
    lo = np.asarray(out["logits"])
    lp = np.asarray(out_p["logits"])[:, :10]
    mask = np.arange(10)[None, :] < lens[:, None]
    np.testing.assert_allclose(lp[mask], lo[mask], rtol=1e-5, atol=1e-5)
    # padded tail predicts tag 0 deterministically
    tail_pred = np.asarray(out_p["logits"].argmax(-1))[:, 10:]
    assert (tail_pred == 0).all()


def test_tagger_serves_through_xla_model():
    """Masked serving end-to-end: lengths packed as the trailing column
    ride XLAModel's single-input contract, so the pad mask holds on the
    serving path (not only through direct model.apply)."""
    from mmlspark_tpu import DataFrame
    from mmlspark_tpu.models import XLAModel
    from mmlspark_tpu.models.sequence import pack_lengths

    tokens, tags, lens = _task(n=32, t=12)
    model, vs = train_tagger(
        tokens, tags, vocab_size=50, num_tags=3, seq_lengths=lens,
        num_steps=60,
    )
    xm = XLAModel(
        input_col="packed", output_col="tag_logits", batch_size=16,
        input_dtype="int32",
    )
    xm.set(apply_fn=model.packed_apply_fn(), variables=vs)
    df = DataFrame.from_dict({"packed": pack_lengths(tokens, lens)})
    out = np.stack(xm.transform(df)["tag_logits"])
    assert out.shape == (32, 12, 3)
    ref = np.asarray(
        model.apply(vs, jnp.asarray(tokens), jnp.asarray(lens))["logits"]
    )
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
    # padded tail is deterministically tag 0 on the serving path too
    mask = np.arange(12)[None, :] < lens[:, None]
    assert (out.argmax(-1)[~mask] == 0).all()
