"""Execute every sample notebook end-to-end — the nbtest analogue.

The reference uploads all notebooks/samples/*.ipynb to a Databricks
cluster and runs each as a job, gating CI on success
(nbtest/NotebookTests.scala:16-51). Here the runner executes each
notebook's code cells in order in a fresh namespace, from the repo root
(notebooks resolve committed datasets relative to cwd). Notebooks carry
their own assertions, so a passing run is a verified capability demo.
"""

from __future__ import annotations

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SAMPLES = os.path.join(REPO, "notebooks", "samples")

NOTEBOOKS = sorted(f for f in os.listdir(SAMPLES) if f.endswith(".ipynb"))

# the heaviest demos (~40/22/18/18 s serial) run in the full tier only;
# tier-1 executes every other notebook — each of these four has direct
# non-notebook tier-1 coverage (automl tune, torch_import, vit,
# gbdt_objectives quantile)
_SLOW_NOTEBOOKS = {
    "HyperParameterTuning - Fighting Breast Cancer.ipynb",
    "DeepLearning - Importing Torch Checkpoints.ipynb",
    "DeepLearning - ViT with Sequence Parallelism.ipynb",
    "LightGBM - Quantile Regression for Drug Discovery.ipynb",
    # ~33 s between them; direct tier-1 coverage: the gbdt suite
    # (test_gbdt*, test_real_datasets) and the transfer path
    # (test_zoo_weights transfer tests, test_e2e image flow)
    "LightGBM - Overview.ipynb",
    "DeepLearning - Transfer Learning with ImageFeaturizer.ipynb",
}


def test_notebooks_exist():
    assert len(NOTEBOOKS) >= 8


@pytest.mark.parametrize(
    "name",
    [
        pytest.param(
            n, marks=[pytest.mark.slow] if n in _SLOW_NOTEBOOKS else []
        )
        for n in NOTEBOOKS
    ],
)
def test_notebook_runs(name, monkeypatch):
    monkeypatch.chdir(REPO)
    with open(os.path.join(SAMPLES, name)) as f:
        nb = json.load(f)
    ns: dict = {"__name__": "__main__"}
    for i, cell in enumerate(nb["cells"]):
        if cell["cell_type"] != "code":
            continue
        src = "".join(cell["source"])
        code = compile(src, f"{name}[cell {i}]", "exec")
        exec(code, ns)  # noqa: S102 — executing our own committed notebooks
