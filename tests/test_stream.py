"""StreamingDataFrame: out-of-core chunked sources (the capability of the
reference's portioned binary reads, io/binary/BinaryFileFormat.scala:112-149).
"""

import os

import numpy as np
import pytest

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.stream import StreamingDataFrame


def counting_stream(n_chunks=10, rows=20, produced=None):
    produced = produced if produced is not None else []

    def make_chunk(i):
        produced.append(i)
        return DataFrame.from_dict(
            {"x": np.full(rows, float(i)), "i": np.arange(rows, dtype=np.float64)}
        )

    return StreamingDataFrame.from_generator(make_chunk, num_chunks=n_chunks), produced


def test_count_and_materialize():
    s, _ = counting_stream(5, 10)
    assert s.count() == 50
    df = s.materialize()
    assert len(df) == 50
    assert df["x"][0] == 0.0 and df["x"][-1] == 4.0


def test_lazy_one_chunk_at_a_time():
    s, produced = counting_stream(10, 4)
    it = s.iter_chunks()
    next(it)
    assert produced == [0]  # chunk 1 not built until asked for
    next(it)
    assert produced == [0, 1]


def test_materialize_stops_early():
    s, produced = counting_stream(100, 10)
    df = s.materialize(max_rows=25)
    assert len(df) == 25
    assert len(produced) == 3  # 3 chunks cover 25 rows; 97 never built


def test_reiterable_source():
    s, produced = counting_stream(3, 5)
    assert s.count() == 15
    assert s.count() == 15  # second traversal re-invokes the factory
    assert produced == [0, 1, 2, 0, 1, 2]


def test_transform_streams_through_stage():
    from mmlspark_tpu.stages import Lambda

    s, produced = counting_stream(6, 8)
    doubler = Lambda.of(lambda df: df.with_column("y", df["x"] * 2))
    out = s.transform(doubler)
    assert produced == []  # still lazy
    total = out.foreach_chunk(lambda c: None)
    assert total == 48


def test_stream_csv_chunks(tmp_path):
    p = tmp_path / "big.csv"
    n = 1000
    with open(p, "w") as f:
        f.write("a,b\n")
        for i in range(n):
            f.write(f"{i},{i * 2}\n")
    s = StreamingDataFrame.from_csv(str(p), chunk_rows=128)
    chunks = list(s.iter_chunks())
    assert len(chunks) > 1  # actually chunked
    assert sum(len(c) for c in chunks) == n
    df = s.materialize()
    np.testing.assert_allclose(df["a"], np.arange(n))
    np.testing.assert_allclose(df["b"], 2 * np.arange(n))


def test_stream_csv_no_header(tmp_path):
    p = tmp_path / "nh.csv"
    with open(p, "w") as f:
        for i in range(50):
            f.write(f"{i},{i + 1}\n")
    s = StreamingDataFrame.from_csv(str(p), chunk_rows=16, header=False)
    df = s.materialize()
    assert len(df) == 50
    np.testing.assert_allclose(df[df.columns[0]], np.arange(50))


def test_stream_binary_files(tmp_path):
    for i in range(7):
        (tmp_path / f"f{i}.bin").write_bytes(bytes([i]) * 10)
    s = StreamingDataFrame.from_binary_files(str(tmp_path), files_per_chunk=3)
    chunks = list(s.iter_chunks())
    assert [len(c) for c in chunks] == [3, 3, 1]
    df = s.materialize()
    assert len(df) == 7
    assert all(len(b) == 10 for b in df["bytes"])


def test_write_csv_roundtrip(tmp_path):
    s, _ = counting_stream(4, 5)
    out = tmp_path / "out.csv"
    rows = s.write_csv(str(out))
    assert rows == 20
    from mmlspark_tpu.io.csv import read_csv

    df = read_csv(str(out))
    assert len(df) == 20 and set(df.columns) == {"x", "i"}


def test_northstar_config_launches():
    """The 1M-row north-star workload is LAUNCHABLE: same code path, tiny
    override (rows/size shrunk, trained zoo backbone)."""
    import tools.northstar_stream as ns

    res = ns.run(rows=96, chunk=32, size=32, model="ResNet8_Digits", batch=16)
    assert res["rows"] == 96
    assert res["images_per_sec"] > 0


def test_stream_csv_serial_consolidator_semantics(tmp_path, monkeypatch):
    """Consolidation holds under SERIAL partition execution too: exactly one
    output partition carries all rows."""
    from mmlspark_tpu.io.consolidator import PartitionConsolidator

    df = DataFrame.from_dict({"x": np.arange(12, dtype=np.float64)},
                             num_partitions=4)
    # force serial execution through the nested-pool path (dataframe._run
    # runs partitions serially inside an "mml-task"-named thread)
    import threading

    t = threading.current_thread()
    monkeypatch.setattr(t, "name", "mml-task-forced")
    out = PartitionConsolidator().transform(df)
    sizes = sorted((len(p["x"]) for p in out._parts), reverse=True)
    assert sizes[0] == 12 and sum(sizes) == 12
    assert sorted(out["x"]) == list(range(12))


def test_stream_csv_quoted_newlines(tmp_path):
    """Chunk boundaries must not split quoted fields containing newlines."""
    import csv as _csv

    p = tmp_path / "q.csv"
    with open(p, "w", newline="") as f:
        w = _csv.writer(f)
        w.writerow(["a", "b"])
        for i in range(200):
            w.writerow([i, f"line1\nline2-{i}"])
    s = StreamingDataFrame.from_csv(str(p), chunk_rows=16)
    df = s.materialize()
    assert len(df) == 200
    assert all("\n" in v for v in df["b"])
