"""Elastic self-healing distributed training (parallel/elastic.py).

Unit layer: partition assignment invariance, straggler policy, the
registry-stamped generation protocol, TCP allreduce + loss detection,
and the world-1 bit-identity anchor. Chaos layer (subprocess gangs over
a real registry): SIGKILL one training host mid-round — survivors
detect, re-shard, resume, and the final booster is bit-identical to a
fresh shrunk-world run from the same checkpoint; a supervisor-restarted
host grows back in at the next checkpoint boundary.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mmlspark_tpu.core.faults import FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child_env() -> dict:
    env = {
        k: v
        for k, v in os.environ.items()
        # scrub the axon sitecustomize: children must be plain CPU
        if k not in ("PYTHONPATH", "PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS",
                     "XLA_FLAGS")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(REPO, ".jax_cache")
    return env


# -- partition assignment -----------------------------------------------------


def test_partition_assignment_contiguous_and_world_invariant():
    """Members take contiguous partition runs in sorted order, so the
    concatenation of member rows is the global dataset in original order
    at EVERY world size — the bit-identity contract's foundation."""
    from mmlspark_tpu.parallel.elastic import (
        assign_partitions,
        member_row_slice,
        partition_bounds,
    )

    bounds = partition_bounds(1003, 8)
    assert bounds[0][0] == 0 and bounds[-1][1] == 1003
    assert all(b[0] == a[1] for a, b in zip(bounds, bounds[1:]))
    for members in (["a"], ["a", "b"], ["c", "a", "b"], list("abcdefgh")):
        asg = assign_partitions(8, members)
        flat = [p for m in sorted(members) for p in asg[m]]
        assert flat == list(range(8))  # every partition exactly once
        slices = [member_row_slice(1003, 8, members, m)
                  for m in sorted(members)]
        assert slices[0][0] == 0 and slices[-1][1] == 1003
        assert all(s[1] == t[0] for s, t in zip(slices, slices[1:]))


def test_straggler_tracker_flags_sustained_slow_only():
    from mmlspark_tpu.parallel.elastic import StragglerTracker

    t = StragglerTracker(factor=3.0, sustain=3)
    fast = {"a": 0.1, "b": 0.1, "c": 0.1}
    assert t.observe(fast) == []
    slow = {"a": 0.1, "b": 0.1, "c": 0.9}
    assert t.observe(slow) == []          # 1st slow observation
    assert t.observe(slow) == []          # 2nd
    assert t.observe(slow) == ["c"]       # sustained -> flagged
    assert t.observe(fast) == []          # recovered -> streak reset
    assert t.observe(slow) == []          # must re-sustain from scratch


# -- generation protocol over the registry ------------------------------------


@pytest.fixture()
def gang_registry():
    from mmlspark_tpu.serving import fleet

    reg = fleet.run_registry(host="127.0.0.1", port=0, ttl_s=1.0)
    yield reg
    reg.stop()


def test_generation_record_is_registry_stamped_latest_wins(gang_registry):
    from mmlspark_tpu.parallel.elastic import GangMember, Generation

    m = GangMember(gang_registry.url, "a", heartbeat_s=0.2)
    try:
        m.commit_generation(Generation(gen=1, members=["a", "b"]))
        m.commit_generation(Generation(
            gen=2, members=["a"], reason="lost", resume_round=6,
        ))
        g = m.read_generation()
        assert g.gen == 2 and g.members == ["a"] and g.reason == "lost"
        assert g.resume_round == 6 and g.committer == "a"
        assert g.stamp > 0  # the REGISTRY stamped it, not the member
    finally:
        m.close()


def test_gang_members_form_generation_and_detect_loss(gang_registry):
    """Two members rendezvous through the registry (lowest name commits
    generation 1); when one's heartbeats stop, the survivor's next round
    boundary raises HostLostError naming exactly the dead host."""
    from mmlspark_tpu.parallel.elastic import (
        GangContext,
        GangMember,
        HostLostError,
        WorldChangedError,
        Generation,
    )

    a = GangMember(gang_registry.url, "a", heartbeat_s=0.2)
    b = GangMember(gang_registry.url, "b", heartbeat_s=0.2)
    try:
        gens = {}

        def await_b():
            gens["b"] = b.await_generation(2, timeout_s=20.0)

        t = threading.Thread(target=await_b)
        t.start()
        gens["a"] = a.await_generation(2, timeout_s=20.0)
        t.join(20.0)
        assert gens["a"].gen == 1 and gens["a"].members == ["a", "b"]
        assert gens["b"].gen == 1
        ros = a.roster()
        assert set(ros) == {"a", "b"} and "ewma_ms" in ros["a"]
        # b dies (clean close deregisters; a crash would TTL out instead)
        b.close()
        deadline = time.monotonic() + 10.0
        while "b" in (a.roster() or {}) and time.monotonic() < deadline:
            time.sleep(0.1)
        gang = GangContext(a, gens["a"], n_rows=100, n_partitions=4)
        # inside the loss grace, absence is not yet death (debounces a
        # freshly-restarted registry's empty roster)
        gang.on_round(0)
        time.sleep(gang.loss_grace_s + 0.2)
        with pytest.raises(HostLostError) as ei:
            gang.on_round(1)
        assert ei.value.lost == ["b"]
        # a newer generation committed by someone else aborts too (all
        # of THIS gang's members alive, so loss detection stays quiet)
        gang2 = GangContext(
            a, Generation(gen=2, members=["a"]), n_rows=100, n_partitions=4
        )
        a.commit_generation(Generation(gen=5, members=["a"]))
        with pytest.raises(WorldChangedError):
            gang2.on_round(1)
    finally:
        a.close()
        b.close()


def test_forced_detect_and_reshard_commit_retries_through_fault(
    gang_registry, tmp_path
):
    """Fault point ``elastic.detect``: a payload declares a named member
    lost without killing anything; ``elastic.reshard``: an injected
    commit refusal is retried until the plan relents."""
    from mmlspark_tpu.models.gbdt.train import TrainConfig
    from mmlspark_tpu.parallel.elastic import (
        ElasticTrainer,
        GangContext,
        GangMember,
        Generation,
        HostLostError,
    )

    a = GangMember(gang_registry.url, "a", heartbeat_s=0.2)
    b = GangMember(gang_registry.url, "b", heartbeat_s=0.2)
    try:
        gen = Generation(gen=1, members=["a", "b"])
        a.adopt(gen)
        gang = GangContext(a, gen, n_rows=100, n_partitions=4)
        plan = FaultPlan().on("elastic.detect", payload="b", at=(0,))
        with plan.armed():
            with pytest.raises(HostLostError) as ei:
                gang.on_round(0)
        assert ei.value.lost == ["b"]
        # the reshard commit: first attempt refused, second lands
        x = np.zeros((100, 4), np.float32)
        trainer = ElasticTrainer(
            gang_registry.url, "a", x, np.zeros(100), TrainConfig(),
            str(tmp_path / "ck"), n_partitions=4, heartbeat_s=0.05,
        )
        plan2 = FaultPlan().on(
            "elastic.reshard", error=ConnectionError, max_fires=1
        )
        with plan2.armed():
            trainer._reshard(a, gen, ei.value)
        assert len(plan2.fires()) == 1  # refused once, then committed
        g2 = a.read_generation()
        assert g2.gen == 2 and g2.members == ["a"] and g2.reason == "lost"
        assert trainer.status["reshards"] == 1
    finally:
        a.close()
        b.close()


# -- the TCP allreduce --------------------------------------------------------


def test_tcp_reducer_allreduce_sums_and_detects_loss(gang_registry):
    from mmlspark_tpu.parallel.elastic import (
        GangMember,
        Generation,
        HostLostError,
        TcpReducer,
    )

    a = GangMember(gang_registry.url, "a", heartbeat_s=0.2)
    b = GangMember(gang_registry.url, "b", heartbeat_s=0.2)
    try:
        time.sleep(0.3)  # both registered
        gen = Generation(gen=1, members=["a", "b"])
        ra = TcpReducer(a, gen, timeout_s=20.0)
        rb = TcpReducer(b, gen, timeout_s=20.0)
        out = {}

        def side(red, arrs, key):
            got = [red.allreduce(x) for x in arrs]
            out[key] = got

        xa = [np.arange(6, dtype=np.float32).reshape(2, 3),
              np.ones(4, np.float64)]
        xb = [np.full((2, 3), 10.0, np.float32),
              np.full(4, 2.0, np.float64)]
        t = threading.Thread(target=side, args=(rb, xb, "b"))
        t.start()
        side(ra, xa, "a")
        t.join(20.0)
        for got_a, got_b, ea, eb in zip(out["a"], out["b"], xa, xb):
            np.testing.assert_array_equal(got_a, got_b)
            np.testing.assert_allclose(got_a, ea + eb)
            assert got_a.dtype == ea.dtype and got_a.shape == ea.shape
        # b vanishes: a's next allreduce fails naming it once the TTL
        # lapses, instead of hanging forever (the socket-allreduce fix)
        rb.close()
        b.close()
        with pytest.raises(HostLostError) as ei:
            ra.allreduce(np.ones(2))
        assert ei.value.lost == ["b"]
        ra.close()
    finally:
        a.close()
        b.close()


# -- world-1 anchor: the gang path IS the plain path --------------------------


def test_world1_elastic_training_bit_identical_to_plain_train(
    gang_registry, tmp_path
):
    """A single-member gang must train bit-identically to plain
    unsharded ``train()`` — the anchor that makes the shrunk-world
    comparison meaningful."""
    from mmlspark_tpu.models.gbdt.train import TrainConfig, train
    from mmlspark_tpu.parallel.elastic import (
        ElasticTrainer,
        load_training_data,
    )

    x, y = load_training_data("synth:400x6:7")
    cfg = TrainConfig(
        objective="binary", num_iterations=4, num_leaves=7,
        min_data_in_leaf=5, seed=3,
    )
    booster = ElasticTrainer(
        gang_registry.url, "solo", x, y, cfg, str(tmp_path / "ck"),
        n_partitions=4, world_size=1, heartbeat_s=0.2,
        status_file=str(tmp_path / "status.json"),
    ).run()
    ref = train(x, y, cfg, shard=False)
    assert booster.to_model_string() == ref.to_model_string()
    status = json.load(open(tmp_path / "status.json"))
    assert status["done"] and status["gen"] == 1


def test_snapshot_checkpoint_freezes_latest(tmp_path):
    from mmlspark_tpu.models.gbdt.booster import Booster
    from mmlspark_tpu.models.gbdt.checkpoint import (
        TrainCheckpoint,
        load_checkpoint,
        save_checkpoint,
    )
    from mmlspark_tpu.parallel.elastic import snapshot_checkpoint

    d = str(tmp_path)
    assert snapshot_checkpoint(d, 2) == (None, 0)  # nothing yet
    rng = np.random.default_rng(0)
    save_checkpoint(d, TrainCheckpoint(
        round=6, booster=Booster(), scores=np.zeros(4, np.float32),
        bag=None, rng_state=rng.bit_generator.state, fingerprint="fp",
    ))
    snap, rnd = snapshot_checkpoint(d, 2)
    assert rnd == 6 and os.path.isdir(snap)
    # later checkpoints do not disturb the frozen snapshot
    save_checkpoint(d, TrainCheckpoint(
        round=8, booster=Booster(), scores=np.ones(4, np.float32),
        bag=None, rng_state=rng.bit_generator.state, fingerprint="fp",
    ))
    loaded = load_checkpoint(snap)
    assert loaded.round == 6 and float(loaded.scores.sum()) == 0.0


def test_charge_from_train_args_builds_train_argv():
    from mmlspark_tpu.serving.supervisor import charge_from_train_args

    c = charge_from_train_args(
        "--name hostA --data synth:100x4:0 --ckpt-dir /tmp/ck",
        "http://reg:9090/", 0,
    )
    assert c.argv[1:5] == ["-m", "mmlspark_tpu.serving.fleet", "train",
                           "--registry"]
    assert "--name" in c.argv and "hostA" in c.argv
    assert c.health_url is None          # trainers have no HTTP ingress
    assert c.name == "train-0:hostA"


# -- chaos: the acceptance scenario -------------------------------------------


_TRAIN_ARGS = [
    "--data", "synth:600x8:5", "--partitions", "4",
    "--num-iterations", "12", "--num-leaves", "7",
    "--min-data-in-leaf", "5", "--seed", "3",
    "--checkpoint-every", "2", "--heartbeat-s", "0.25",
]


def _spawn_trainer(
    reg_url: str, name: str, ckpt: str, out_dir: str, world: int,
    extra: list = (), fault: str = None, train_args: list = None,
):
    argv = [sys.executable, "-m", "mmlspark_tpu.serving.fleet"]
    if fault:
        argv += ["--fault-plan", fault]
    argv += [
        "train", "--registry", reg_url, "--name", name,
        "--ckpt-dir", ckpt, "--world-size", str(world),
        "--out-model", os.path.join(out_dir, f"model-{name}.txt"),
        "--status-file", os.path.join(out_dir, f"status-{name}.json"),
        *(train_args if train_args is not None else _TRAIN_ARGS),
        *extra,
    ]
    return subprocess.Popen(
        argv, env=_child_env(), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True,
    )


def _status(out_dir: str, name: str) -> dict:
    try:
        with open(os.path.join(out_dir, f"status-{name}.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


@pytest.mark.chaos
@pytest.mark.xdist_group("latency")
def test_chaos_elastic_host_loss_mid_round_resumes_bit_identical(tmp_path):
    """The acceptance scenario: a 2-host gang trains over the TCP
    histogram allreduce; one host is SIGKILLed MID-ROUND (an injected
    ``gbdt.round`` stall parks it inside round 6 while the survivor
    blocks in the round's allreduce). The survivor must detect the loss
    (TTL expiry), abort the in-flight round (through an armed
    ``train.round_abort`` point), re-shard to world 1, resume from the
    snapshotted checkpoint, and finish — and its final booster must be
    BIT-IDENTICAL to a fresh world-1 run started from that same
    snapshot. Recovery timings land in the status file (the bench's
    ``elastic`` segment records the same numbers)."""
    from mmlspark_tpu.serving import fleet

    reg = fleet.run_registry(host="127.0.0.1", port=0, ttl_s=1.2)
    out = str(tmp_path)
    ck = os.path.join(out, "ck")
    try:
        # victim stalls ENTERING round 6 (a chunk boundary), so the
        # survivor is wedged inside round 6's first gang allreduce when
        # the SIGKILL lands — a genuine mid-round loss
        victim_fault = json.dumps({
            "rules": [{"point": "gbdt.round", "at": [6], "delay_s": 600}],
        })
        # the survivor's abort path runs through an armed
        # train.round_abort (delay: a slow abort must still recover)
        survivor_fault = json.dumps({
            "rules": [
                {"point": "train.round_abort", "delay_s": 0.1,
                 "max_fires": 1},
            ],
        })
        surv = _spawn_trainer(
            reg.url, "a", ck, out, world=2, extra=["--no-growback"],
            fault=survivor_fault,
        )
        vict = _spawn_trainer(
            reg.url, "b", ck, out, world=2, extra=["--no-growback"],
            fault=victim_fault,
        )
        # wait for the round-6 checkpoint to commit, then give the
        # survivor a beat to enter round 6's allreduce before the kill
        latest = os.path.join(ck, "LATEST")
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            try:
                with open(latest) as f:
                    if f.read().strip() == "round-0000006":
                        break
            except OSError:
                pass
            assert vict.poll() is None, vict.communicate()[1][-2000:]
            time.sleep(0.1)
        time.sleep(0.6)
        vict.kill()
        out_a, err_a = surv.communicate(timeout=180)
        assert surv.returncode == 0, err_a[-3000:]
        sa = _status(out, "a")
        assert sa["done"] and sa["reshards"] == 1
        assert sa["members"] == ["a"] and sa["gen"] == 2
        assert sa["reshard_reasons"] == ["lost"]
        assert sa["resume_round"] == 6
        assert sa["snapshot"] and os.path.isdir(sa["snapshot"])
        # recovery timings recorded (the bench reads these)
        assert sa["detect_latency_s"] > 0
        assert sa["reshard_to_first_round_s"] > 0
        # -- the hard contract: fresh world-1 run from the SAME snapshot
        fresh = _spawn_trainer(
            reg.url, "c", os.path.join(out, "ck-fresh"), out, world=1,
            extra=["--resume-from", sa["snapshot"]],
        )
        out_c, err_c = fresh.communicate(timeout=180)
        assert fresh.returncode == 0, err_c[-3000:]
        with open(os.path.join(out, "model-a.txt")) as f:
            survivor_model = f.read()
        with open(os.path.join(out, "model-c.txt")) as f:
            fresh_model = f.read()
        assert survivor_model == fresh_model, (
            "survivor's resumed booster != fresh shrunk-world run from "
            "the same checkpoint"
        )
    finally:
        reg.stop()


@pytest.mark.chaos
@pytest.mark.xdist_group("latency")
def test_chaos_elastic_per_host_ckpt_dirs_artifact_pull_growback(tmp_path):
    """The no-shared-filesystem acceptance gate (docs/artifacts.md):
    a 2-host gang where every host owns a PRIVATE checkpoint dir
    (``--artifact-dir`` mode — every member writes its own checkpoints,
    reshard snapshots replicate as content-addressed artifacts). One
    host is SIGKILLed mid-run under a live supervisor: the survivor
    re-shards from ITS OWN disk, the restarted victim is grown back at
    the next checkpoint boundary and must PULL the agreed resume
    snapshot over HTTP (hash-verified) because the generation record
    names a path on the survivor's disk, not its own. Both hosts finish
    with identical boosters — and that booster is byte-identical to a
    plain shared-dir/solo run of the same data+config, the invariance
    the whole artifact plane must preserve."""
    from mmlspark_tpu.serving import fleet
    from mmlspark_tpu.serving.supervisor import (
        FleetSupervisor,
        charge_from_train_args,
    )

    reg = fleet.run_registry(host="127.0.0.1", port=0, ttl_s=1.2)
    out = str(tmp_path)
    # slow every chunk so the run comfortably outlives the restart
    fault = json.dumps({"rules": [{"point": "gbdt.round", "delay_s": 0.35}]})
    env = _child_env()

    def spawn(argv):
        return subprocess.Popen(
            argv, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True,
        )

    def args(name):
        # PER-HOST dirs: ck-a vs ck-b, art-a vs art-b — nothing shared
        return (
            f"--name {name} --data synth:600x8:5 --partitions 4 "
            f"--world-size 2 --ckpt-dir {out}/ck-{name} "
            f"--artifact-dir {out}/art-{name} --num-iterations 40 "
            f"--num-leaves 7 --min-data-in-leaf 5 --seed 3 "
            f"--checkpoint-every 2 --heartbeat-s 0.25 "
            f"--out-model {out}/model-{name}.txt "
            f"--status-file {out}/status-{name}.json"
        )

    charges = [
        charge_from_train_args(args(n), reg.url, i)
        for i, n in enumerate("ab")
    ]
    for c in charges:  # arm the chunk-slowdown plan in every trainer
        c.argv = c.argv[:3] + ["--fault-plan", fault] + c.argv[3:]
    sup = FleetSupervisor(
        charges, registry_url=reg.url, probe_s=0.3, backoff_s=0.3,
        stable_s=30.0, spawn=spawn,
    ).start()
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if _status(out, "a").get("gen") == 1:
                break
            time.sleep(0.2)
        assert _status(out, "a").get("gen") == 1, "gang never formed"
        time.sleep(2.0)  # into the run, past the first checkpoints
        victim = charges[1]
        victim.proc.kill()
        deadline = time.monotonic() + 150.0
        while time.monotonic() < deadline:
            sa, sb = _status(out, "a"), _status(out, "b")
            if sa.get("done") and sb.get("done"):
                break
            time.sleep(0.4)
        sa, sb = _status(out, "a"), _status(out, "b")
        assert sa.get("done") and sb.get("done"), (sa, sb)
        assert victim.restarts >= 1, "supervisor never restarted the victim"
        # survivor shrank from its OWN dir, victim grew back
        assert sa["reshard_reasons"][:1] == ["lost"]
        assert sa["gen"] >= 3 and sorted(sa["members"]) == ["a", "b"]
        # the victim's resume point came over HTTP: the generation
        # record named a snapshot on the SURVIVOR's disk, so the victim
        # had to pull the content-addressed bytes from a peer
        assert sb.get("artifact_fetches", 0) >= 1, (
            "victim never pulled a checkpoint artifact", sb,
        )
        with open(os.path.join(out, "model-a.txt")) as f:
            ma = f.read()
        with open(os.path.join(out, "model-b.txt")) as f:
            mb = f.read()
        assert ma == mb, "grown-back gang disagreed on the final booster"
    finally:
        sup.stop()
        reg.stop()


@pytest.mark.chaos
@pytest.mark.xdist_group("latency")
def test_chaos_elastic_per_host_reshard_bit_identical_via_artifact(tmp_path):
    """Gate 1's hard bit-identity contract, with the shared filesystem
    removed: per-host checkpoint dirs, one host SIGKILLed mid-round —
    the survivor re-shards from ITS OWN disk and publishes the frozen
    resume snapshot as a content-addressed artifact. A fresh world-1
    trainer then warm-starts from ``--resume-from artifact:<name>@
    <digest>@<url>`` — the snapshot bytes travel over HTTP, hash-
    verified, from the survivor's (restart-surviving) store — and its
    final booster must equal the survivor's byte-for-byte. Same claim
    as the shared-dir gate, new transport."""
    from mmlspark_tpu.serving import fleet
    from mmlspark_tpu.serving.artifacts import ArtifactServer, ArtifactStore

    reg = fleet.run_registry(host="127.0.0.1", port=0, ttl_s=1.2)
    out = str(tmp_path)
    try:
        victim_fault = json.dumps({
            "rules": [{"point": "gbdt.round", "at": [6], "delay_s": 600}],
        })
        art = {n: os.path.join(out, f"art-{n}") for n in "abc"}
        surv = _spawn_trainer(
            reg.url, "a", os.path.join(out, "ck-a"), out, world=2,
            extra=["--no-growback", "--artifact-dir", art["a"]],
        )
        vict = _spawn_trainer(
            reg.url, "b", os.path.join(out, "ck-b"), out, world=2,
            extra=["--no-growback", "--artifact-dir", art["b"]],
            fault=victim_fault,
        )
        # per-host dirs: watch the SURVIVOR's own checkpoint stream
        latest = os.path.join(out, "ck-a", "LATEST")
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            try:
                with open(latest) as f:
                    if f.read().strip() == "round-0000006":
                        break
            except OSError:
                pass
            assert vict.poll() is None, vict.communicate()[1][-2000:]
            time.sleep(0.1)
        time.sleep(0.6)
        vict.kill()
        _, err_a = surv.communicate(timeout=180)
        assert surv.returncode == 0, err_a[-3000:]
        sa = _status(out, "a")
        assert sa["done"] and sa["reshards"] == 1 and sa["gen"] == 2
        assert sa["snapshot"].startswith(os.path.join(out, "ck-a"))
        # the survivor advertised the snapshot as an artifact; its store
        # survives the process (re-indexed from disk) — serve it
        store = ArtifactStore(art["a"])
        name = os.path.basename(sa["snapshot"])
        refs = [r for r in store.refs() if r.startswith(name + "@")]
        assert refs, (store.refs(), name)
        srv = ArtifactServer(store)
        try:
            fresh = _spawn_trainer(
                reg.url, "c", os.path.join(out, "ck-c"), out, world=1,
                extra=[
                    "--artifact-dir", art["c"],
                    "--resume-from", f"artifact:{refs[0]}@{srv.url}",
                ],
            )
            _, err_c = fresh.communicate(timeout=180)
            assert fresh.returncode == 0, err_c[-3000:]
        finally:
            srv.stop()
        sc = _status(out, "c")
        assert sc.get("artifact_fetches", 0) >= 1, sc
        with open(os.path.join(out, "model-a.txt")) as f:
            survivor_model = f.read()
        with open(os.path.join(out, "model-c.txt")) as f:
            fresh_model = f.read()
        assert survivor_model == fresh_model, (
            "survivor's resumed booster != fresh world-1 run from the "
            "artifact-pulled snapshot"
        )
    finally:
        reg.stop()


@pytest.mark.chaos
@pytest.mark.xdist_group("latency")
def test_chaos_elastic_supervisor_growback_at_checkpoint_boundary(tmp_path):
    """``fleet supervise`` training charges close the loop: a SIGKILLed
    trainer is restarted with its full argv, auto-resumes from the
    shared checkpoint dir, and is grown back into the gang at the next
    checkpoint boundary (generation reason ``grow``) — and both hosts
    finish with the identical booster."""
    from mmlspark_tpu.serving import fleet
    from mmlspark_tpu.serving.supervisor import (
        FleetSupervisor,
        charge_from_train_args,
    )

    reg = fleet.run_registry(host="127.0.0.1", port=0, ttl_s=1.2)
    out = str(tmp_path)
    ck = os.path.join(out, "ck")
    # slow every chunk so the run comfortably outlives the restart
    fault = json.dumps({"rules": [{"point": "gbdt.round", "delay_s": 0.35}]})
    env = _child_env()

    def spawn(argv):
        return subprocess.Popen(
            argv, env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True,
        )

    def args(name):
        return (
            f"--name {name} --data synth:600x8:5 --partitions 4 "
            f"--world-size 2 --ckpt-dir {ck} --num-iterations 40 "
            f"--num-leaves 7 --min-data-in-leaf 5 --seed 3 "
            f"--checkpoint-every 2 --heartbeat-s 0.25 "
            f"--out-model {out}/model-{name}.txt "
            f"--status-file {out}/status-{name}.json"
        )

    charges = [
        charge_from_train_args(args(n), reg.url, i)
        for i, n in enumerate("ab")
    ]
    for c in charges:  # arm the chunk-slowdown plan in every trainer
        c.argv = c.argv[:3] + ["--fault-plan", fault] + c.argv[3:]
    sup = FleetSupervisor(
        charges, registry_url=reg.url, probe_s=0.3, backoff_s=0.3,
        stable_s=30.0, spawn=spawn,
    ).start()
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if _status(out, "a").get("gen") == 1:
                break
            time.sleep(0.2)
        assert _status(out, "a").get("gen") == 1, "gang never formed"
        time.sleep(2.0)  # into the run
        victim = charges[1]
        victim.proc.kill()
        deadline = time.monotonic() + 150.0
        while time.monotonic() < deadline:
            sa, sb = _status(out, "a"), _status(out, "b")
            if sa.get("done") and sb.get("done"):
                break
            time.sleep(0.4)
        sa, sb = _status(out, "a"), _status(out, "b")
        assert sa.get("done") and sb.get("done"), (sa, sb)
        assert victim.restarts >= 1, "supervisor never restarted the victim"
        # the survivor shrank (lost), then the restarted host grew back:
        # the final generation includes both again
        assert sa["reshard_reasons"][:1] == ["lost"]
        assert sa["gen"] >= 3 and sorted(sa["members"]) == ["a", "b"]
        with open(os.path.join(out, "model-a.txt")) as f:
            ma = f.read()
        with open(os.path.join(out, "model-b.txt")) as f:
            mb = f.read()
        assert ma == mb, "grown-back gang disagreed on the final booster"
    finally:
        sup.stop()
        reg.stop()


# -- split brain: quorum CAS, fencing, parking --------------------------------


def test_declared_dead_pinned_to_monotonic_not_wall_clock(
    gang_registry, monkeypatch
):
    """An NTP step (wall clock jumps an hour) must neither mass-declare
    death nor mask a real one: sighting ages are time.monotonic()
    deltas, so only genuinely-stale sightings cross the grace."""
    from mmlspark_tpu.parallel.elastic import GangMember

    a = GangMember(gang_registry.url, "a", heartbeat_s=0.2)
    b = GangMember(gang_registry.url, "b", heartbeat_s=0.2)
    try:
        deadline = time.monotonic() + 10.0
        while (
            set(a.roster() or {}) != {"a", "b"}
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert set(a.roster()) == {"a", "b"}
        # b crashes (no clean deregister): silence its heartbeats and
        # let the TTL prune it from the roster
        b.registry_urls = []
        deadline = time.monotonic() + 10.0
        while "b" in (a.roster() or {}) and time.monotonic() < deadline:
            time.sleep(0.1)
        ros = a.roster()
        assert "b" not in ros
        real = time.time
        with monkeypatch.context() as mp:
            # the NTP step: wall clock leaps one hour forward. b's last
            # sighting is ~2s old on the monotonic clock — a 30s grace
            # must NOT declare it dead just because the wall moved
            mp.setattr(time, "time", lambda: real() + 3600.0)
            assert a.declared_dead(["b"], ros, grace_s=30.0) == []
        # and the real death is still detected once the (monotonic)
        # grace genuinely elapses
        time.sleep(0.6)
        assert a.declared_dead(["b"], ros, grace_s=0.5) == ["b"]
    finally:
        a.close()
        b.close()


def test_commit_generation_zero_acks_raises_not_false_success():
    """Regression: with every registry dead, commit_generation used to
    swallow every POST failure and report the commit as done. Now zero
    acks raises (QuorumLostError) and the ack count is visible."""
    from mmlspark_tpu.parallel.elastic import (
        GangMember,
        Generation,
        QuorumLostError,
    )

    m = GangMember(
        "http://127.0.0.1:9/,http://127.0.0.1:19/", "a", heartbeat_s=30.0
    )
    try:
        with pytest.raises(QuorumLostError):
            m.commit_generation(
                Generation(gen=1, members=["a"]), expected_gen=0
            )
        assert m.commit_acks == 0
        assert m.committed_gens == []
    finally:
        m.close()


def test_generation_cas_concurrent_commits_exactly_one_winner(gang_registry):
    """Two members race conflicting gen-2 commits from the same adopted
    gen 1: the registry's CAS admits exactly one; the loser gets a
    rejection carrying the winning record, not a silent last-write."""
    from mmlspark_tpu import obs
    from mmlspark_tpu.parallel.elastic import (
        GangMember,
        Generation,
        GenerationConflictError,
    )

    def stale_count():
        return obs.sum_samples(
            obs.parse_text(obs.render()),
            "mmlspark_registry_cas_commits_total", {"result": "stale"},
        )

    a = GangMember(gang_registry.url, "a", heartbeat_s=0.2)
    b = GangMember(gang_registry.url, "b", heartbeat_s=0.2)
    try:
        a.commit_generation(Generation(gen=1, members=["a", "b"]))
        gb = b.await_generation(2, timeout_s=10.0)
        assert gb.gen == 1
        before = stale_count()
        barrier = threading.Barrier(2)
        results: dict = {}

        def race(m):
            barrier.wait()
            try:
                results[m.name] = m.commit_generation(
                    Generation(gen=2, members=[m.name])
                )
            except GenerationConflictError as e:
                results[m.name] = e

        t = threading.Thread(target=race, args=(b,))
        t.start()
        race(a)
        t.join(10.0)
        winners = [
            n for n, r in results.items() if isinstance(r, Generation)
        ]
        losers = [
            n for n, r in results.items()
            if isinstance(r, GenerationConflictError)
        ]
        assert len(winners) == 1 and len(losers) == 1, results
        # the loser's rejection names the winning world
        loss = results[losers[0]]
        assert loss.current is not None
        assert loss.current.gen == 2
        assert loss.current.members == [winners[0]]
        # the registry counted the rejected commit
        assert stale_count() == before + 1
        # and the record IS the winner's, not the last writer's
        g = a.read_generation()
        assert g.gen == 2 and g.members == [winners[0]]
    finally:
        a.close()
        b.close()


def test_registry_restart_does_not_resurrect_superseded_generation():
    """HA: gen 2 wins a 2-of-3 majority while registry C is down. C
    restarts empty, a straggler re-posts the OLD gen-1 record to it, and
    anti-entropy must reconcile C to the HIGHEST committed generation —
    never resurrect the superseded world."""
    from mmlspark_tpu.parallel.elastic import (
        GangMember,
        Generation,
        GenerationConflictError,
    )
    from mmlspark_tpu.serving.registry import DriverRegistry

    reg_a = DriverRegistry(host="127.0.0.1", port=0, ttl_s=30.0)
    reg_b = DriverRegistry(host="127.0.0.1", port=0, ttl_s=30.0)
    reg_c = DriverRegistry(host="127.0.0.1", port=0, ttl_s=30.0)
    urls = f"{reg_a.url},{reg_b.url},{reg_c.url}"
    m = GangMember(urls, "a", heartbeat_s=30.0)
    regs = [reg_a, reg_b]
    try:
        g1 = m.commit_generation(
            Generation(gen=1, members=["a", "b"]), expected_gen=0
        )
        assert g1.gen == 1 and m.commit_acks == 3
        reg_c.stop()  # C misses the next commit
        g2 = m.commit_generation(Generation(gen=2, members=["a"]))
        assert g2.gen == 2 and m.commit_acks == 2  # majority of 3
        # C restarts EMPTY; a partitioned straggler's heartbeat re-post
        # lands the superseded gen-1 record on it first
        reg_c2 = DriverRegistry(host="127.0.0.1", port=0, ttl_s=30.0)
        regs.append(reg_c2)
        z = GangMember(reg_c2.url, "b", heartbeat_s=30.0)
        try:
            z.adopt(g1)
            z.heartbeat()  # re-posts the adopted gen-1 record
            # anti-entropy pulls from A: the gen record merges to the
            # HIGHEST gen, not the freshest timestamp
            reg_c2.peers = [reg_a.url]
            reg_c2.reconcile_now()
            got = z.read_generation()
            assert got.gen == 2 and got.members == ["a"]
            # and a CAS commit against the reconciled C from the stale
            # world is rejected, not adopted
            with pytest.raises(GenerationConflictError):
                z.commit_generation(
                    Generation(gen=2, members=["b"]), expected_gen=1
                )
        finally:
            z.close()
    finally:
        m.close()
        for r in regs:
            r.stop()


def test_registry_commit_cas_fault_point_refuses_then_relents(gang_registry):
    """Fault point ``registry.commit_cas``: an injected error refuses
    the commit server-side (503 — a missing ack), so a single-registry
    deployment loses its majority-of-1; the retry lands once the plan
    relents."""
    from mmlspark_tpu.parallel.elastic import (
        GangMember,
        Generation,
        QuorumLostError,
    )

    m = GangMember(gang_registry.url, "a", heartbeat_s=0.2)
    try:
        plan = FaultPlan().on(
            "registry.commit_cas", error=RuntimeError, max_fires=1
        )
        with plan.armed():
            with pytest.raises(QuorumLostError):
                m.commit_generation(
                    Generation(gen=1, members=["a"]), expected_gen=0
                )
            assert m.commit_acks == 0
            g = m.commit_generation(
                Generation(gen=1, members=["a"]), expected_gen=0
            )
        assert g.gen == 1 and m.commit_acks == 1
        assert len(plan.fires("registry.commit_cas")) == 1
    finally:
        m.close()


def test_fenced_out_only_on_registry_confirmed_exclusion(gang_registry):
    """The fencing token: a member whose adopted epoch is superseded by
    a committed generation that EXCLUDES it refuses to write; blindness
    or a newer world that still INCLUDES it never fences."""
    from mmlspark_tpu import obs
    from mmlspark_tpu.parallel.elastic import GangMember, Generation

    def fenced_count():
        return obs.sum_samples(
            obs.parse_text(obs.render()),
            "mmlspark_elastic_fenced_writes_total", {"plane": "checkpoint"},
        )

    a = GangMember(gang_registry.url, "a", heartbeat_s=0.2)
    z = GangMember(gang_registry.url, "z", heartbeat_s=0.2)
    try:
        g1 = a.commit_generation(
            Generation(gen=1, members=["a", "z"]), expected_gen=0
        )
        z.adopt(g1)
        assert not z.fenced_out("checkpoint")  # current world includes z
        a.commit_generation(Generation(gen=2, members=["a"]))
        before = fenced_count()
        assert z.fenced_out("checkpoint")      # superseded AND excluded
        assert fenced_count() == before + 1
        # a newer world that still includes the member does not fence
        a.commit_generation(Generation(gen=3, members=["a", "z"]))
        assert not z.fenced_out("checkpoint")
    finally:
        a.close()
        z.close()


@pytest.mark.chaos
@pytest.mark.xdist_group("latency")
def test_chaos_partition_drill_minority_parks_majority_wins_zombie_fenced(
    tmp_path,
):
    """The split-brain acceptance drill (docs/chaos.md): member b's
    registry link runs through a seeded chaos proxy; a conductor
    ``partition`` step blackholes it. The majority side (a, with the
    registry) declares b dead, CAS-commits gen 2 and trains on; the
    minority (b) loses its registry quorum and PARKS — stops training,
    commits nothing, keeps heartbeating. The survivor's booster is
    bit-identical to a fresh majority-only run from the same snapshot; a
    zombie's late generation commit and late (stale-epoch) publication
    are both rejected and counted; the generation-monotonicity and
    single-writer laws stay green through the whole soak; post-heal the
    parked member's heartbeats reach the registry again."""
    import urllib.parse

    from mmlspark_tpu import obs
    from mmlspark_tpu.chaos.conductor import ChaosConductor, Scenario
    from mmlspark_tpu.chaos.invariants import InvariantChecker
    from mmlspark_tpu.chaos.wire import ChaosProxy
    from mmlspark_tpu.parallel.elastic import (
        GangMember,
        Generation,
        GenerationConflictError,
    )
    from mmlspark_tpu.serving import fleet
    from mmlspark_tpu.serving.modelstore import ModelDispatcher, ModelStore
    from mmlspark_tpu.serving.server import WorkerServer

    def counter(name, match=None):
        return obs.sum_samples(obs.parse_text(obs.render()), name, match)

    reg = fleet.run_registry(host="127.0.0.1", port=0, ttl_s=1.2)
    out = str(tmp_path)
    ck = os.path.join(out, "ck")
    reg_port = urllib.parse.urlparse(reg.url).port
    proxy = ChaosProxy("127.0.0.1", reg_port, seed=13, name="reg-b").start()
    surv = vict = fresh = None
    try:
        # b's ONLY path to the registry is the proxy; the park fault
        # point fires (armed with a tiny delay) as b stops training
        park_fault = json.dumps({
            "rules": [{"point": "elastic.park", "delay_s": 0.05}],
        })
        surv = _spawn_trainer(
            reg.url, "a", ck, out, world=2, extra=["--no-growback"],
        )
        vict = _spawn_trainer(
            f"http://127.0.0.1:{proxy.port}/", "b", ck, out, world=2,
            extra=["--no-growback", "--gen-timeout-s", "240"],
            fault=park_fault,
        )
        # wait until the 2-member gang is genuinely training (a couple
        # of checkpoints committed) before cutting the wire
        latest = os.path.join(ck, "LATEST")
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            try:
                with open(latest) as f:
                    if f.read().strip() >= "round-0000004":
                        break
            except OSError:
                pass
            assert surv.poll() is None, surv.communicate()[1][-2000:]
            assert vict.poll() is None, vict.communicate()[1][-2000:]
            time.sleep(0.1)
        checker = InvariantChecker(
            registry_url=reg.url, service_name="train",
            status_files=[
                os.path.join(out, "status-a.json"),
                os.path.join(out, "status-b.json"),
                os.path.join(out, "status-c.json"),
            ],
        )
        cut = ChaosConductor(
            Scenario.from_spec({"seed": 13, "steps": [
                {"at_s": 0.0, "action": "partition", "links": ["reg-b"]},
                {"at_s": 0.0, "action": "mark", "note": "partition open"},
            ]}),
            proxies={"reg-b": proxy},
        )
        journal = cut.run()
        assert [e["action"] for e in journal] == ["partition", "mark"]
        assert journal[0]["links"] == ["reg-b"]
        # the soak: majority trains to completion while the invariant
        # laws are evaluated continuously
        soak_deadline = time.monotonic() + 180.0
        while surv.poll() is None and time.monotonic() < soak_deadline:
            assert checker.check(final=False) == []
            time.sleep(0.3)
        out_a, err_a = surv.communicate(timeout=30)
        assert surv.returncode == 0, err_a[-3000:]
        sa = _status(out, "a")
        assert sa["done"] and sa["reshards"] == 1
        assert sa["members"] == ["a"] and sa["gen"] == 2
        assert sa["committed_gens"] == [1, 2]  # a bootstrapped AND won
        # -- the minority parked: zero commits, training stopped
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            sb = _status(out, "b")
            if sb.get("parked"):
                break
            time.sleep(0.2)
        sb = _status(out, "b")
        assert sb.get("parked") is True, sb
        assert sb["parks"] >= 1
        assert sb["park_reasons"][0] in ("quorum", "conflict")
        assert sb["committed_gens"] == []
        assert not sb.get("done")
        assert vict.poll() is None, "parked member must keep running"
        # -- zombie generation commit: a SIGSTOP'd coordinator waking
        # after the reshard tries to move the world FORWARD from its
        # stale epoch; the CAS rejects (expected_gen 1 < committed 2)
        z = GangMember(reg.url, "z", heartbeat_s=0.5)
        try:
            z.adopt(Generation(gen=1, members=["a", "b"]))
            before = counter(
                "mmlspark_registry_cas_commits_total",
                {"result": "conflict"},
            )
            with pytest.raises(GenerationConflictError) as ei:
                z.commit_generation(
                    Generation(gen=3, members=["b", "z"]), expected_gen=1
                )
            assert ei.value.current is not None
            assert ei.value.current.gen == 2
            assert counter(
                "mmlspark_registry_cas_commits_total",
                {"result": "conflict"},
            ) == before + 1
        finally:
            z.close()
        # -- zombie publication: the committed gen rides load/swap as an
        # epoch; a worker that saw the winner's epoch 2 refuses epoch 1
        srv = WorkerServer()
        winfo = srv.start()
        ModelDispatcher(srv, ModelStore(), default_model="m").start()
        try:
            import http.client

            conn = http.client.HTTPConnection(
                "127.0.0.1", winfo.port, timeout=10
            )

            def publish(epoch):
                conn.request(
                    "POST", "/models/m/load",
                    body=json.dumps(
                        {"spec": "zoo:NoSuch", "epoch": epoch}
                    ),
                    headers={"Content-Type": "application/json"},
                )
                r = conn.getresponse()
                return r.status, json.loads(r.read() or b"{}")

            publish(2)  # the winner's epoch is now the highest seen
            before = counter(
                "mmlspark_elastic_fenced_publications_total",
                {"model": "m"},
            )
            fence_plan = FaultPlan().on("publish.fence", delay_s=0.01)
            with fence_plan.armed():
                code, body = publish(1)
            assert code == 409 and body["fenced"] is True
            assert body["highest_epoch"] == 2
            assert len(fence_plan.fires("publish.fence")) == 1
            assert counter(
                "mmlspark_elastic_fenced_publications_total",
                {"model": "m"},
            ) == before + 1
            conn.close()
        finally:
            srv.stop()
        # -- the hard contract: a fresh majority-only run from the same
        # snapshot produces the SAME booster bytes
        fresh = _spawn_trainer(
            reg.url, "c", os.path.join(out, "ck-fresh"), out, world=1,
            extra=["--resume-from", sa["snapshot"]],
        )
        out_c, err_c = fresh.communicate(timeout=180)
        assert fresh.returncode == 0, err_c[-3000:]
        with open(os.path.join(out, "model-a.txt")) as f:
            survivor_model = f.read()
        with open(os.path.join(out, "model-c.txt")) as f:
            fresh_model = f.read()
        assert survivor_model == fresh_model, (
            "survivor's booster != fresh majority-only run from the "
            "same snapshot"
        )
        # -- heal: the parked member's heartbeats reach the registry
        # again (it parked, it never died), and the final invariant
        # check — including generation monotonicity across the whole
        # drill — is green
        heal = ChaosConductor(
            Scenario.from_spec({"seed": 13, "steps": [
                {"at_s": 0.0, "action": "heal", "links": ["reg-b"]},
                {"at_s": 0.5, "action": "check", "final": True},
            ]}),
            proxies={"reg-b": proxy}, checker=checker,
        )
        heal.run()
        assert heal.violations == []
        deadline = time.monotonic() + 20.0
        back = False
        while time.monotonic() < deadline:
            entries = fleet.roster_entries_from_registry(
                reg.url, "train-gang"
            )
            if any(e.get("host") == "b" for e in entries):
                back = True
                break
            time.sleep(0.2)
        assert back, "parked member's heartbeats never resumed post-heal"
    finally:
        for p in (surv, vict, fresh):
            if p is not None and p.poll() is None:
                p.kill()
        proxy.stop()
        reg.stop()


@pytest.mark.chaos
@pytest.mark.xdist_group("latency")
def test_chaos_reshard_pull_blackholed_replica_fails_over(
    gang_registry, tmp_path,
):
    """One replica holder blackholed DURING the reshard pull: the
    grow-back member resolving the agreed resume snapshot by digest
    dials the advertising peers in the gang's deterministic sorted-name
    order — the first peer's ingress swallows every response byte
    (asymmetric partition, not a clean refusal) — and the fetch must
    burn one bounded timeout, fail over to the surviving holder, and
    land hash-verified bytes that unpack to the exact committed
    snapshot tree."""
    from mmlspark_tpu.chaos.wire import ChaosProxy, WireRule
    from mmlspark_tpu.parallel.elastic import GangMember, replicate_snapshot
    from mmlspark_tpu.serving.artifacts import (
        ArtifactStore,
        pack_dir,
        unpack_dir,
    )

    out = str(tmp_path)
    # the committer's frozen reshard snapshot, on its PRIVATE disk
    snap = os.path.join(out, "ck-a", "round-0000006")
    os.makedirs(snap)
    rng = np.random.default_rng(21)
    for fn in ("booster.json", "state.bin"):
        with open(os.path.join(snap, fn), "wb") as f:
            f.write(rng.bytes(40_000))
    stores = {
        n: ArtifactStore(os.path.join(out, f"art-{n}")) for n in "abc"
    }
    a = GangMember(
        gang_registry.url, "a", heartbeat_s=0.2, artifact_store=stores["a"],
    )
    b = GangMember(
        gang_registry.url, "b", heartbeat_s=0.2, artifact_store=stores["b"],
    )
    c = GangMember(
        gang_registry.url, "c", heartbeat_s=0.2, artifact_store=stores["c"],
    )
    # the committer's artifact ingress goes dark mid-pull: peers dial the
    # ADVERTISED port, so pointing it through a blackholing proxy is
    # exactly a host whose replies stopped arriving
    wire = ChaosProxy(
        "127.0.0.1", a.artifact_port, seed=7, name="reshard-blackhole",
        rules=[WireRule("blackhole", direction="s2c")],
    ).start()
    a.artifact_port = wire.port
    try:
        pack = os.path.join(out, "snap.pack")
        pack_dir(snap, pack)
        ref = stores["a"].put(pack, name="round-0000006")
        # replicate-before-commit pushed the snapshot to holder b (the
        # training plane's majority target for a world of 3 is 1)
        status: dict = {}
        assert replicate_snapshot(a, ref.digest, ["a", "b", "c"], status) == 1
        assert status["snapshot_replicas"] == 1
        assert stores["b"].has(ref.digest)
        # both advertisements must ride a heartbeat before c can resolve
        deadline = time.monotonic() + 15.0
        peers = c.artifact_peers(ref.digest)
        while time.monotonic() < deadline and len(peers) < 2:
            time.sleep(0.1)
            peers = c.artifact_peers(ref.digest)
        assert len(peers) == 2, peers
        assert str(wire.port) in peers[0], (
            "sorted-name failover order must dial the blackholed "
            "committer first", peers,
        )
        # per-connection timeout bounds the blackhole's cost: the dark
        # peer blocks the socket until exactly this budget expires
        t0 = time.monotonic()
        path = stores["c"].fetch(
            ref.digest, peers, name="round-0000006", timeout_s=8.0,
        )
        dt = time.monotonic() - t0
        assert dt < 25.0, f"failover burned {dt:.1f}s, not one timeout"
        local = os.path.join(out, "ck-c", f"pulled-{ref.digest[:16]}")
        unpack_dir(path, local)
        for fn in ("booster.json", "state.bin"):
            with open(os.path.join(snap, fn), "rb") as want, \
                    open(os.path.join(local, fn), "rb") as got:
                assert got.read() == want.read(), fn
        assert any(e.kind == "blackhole" for e in wire.journal()), (
            "the drill never actually exercised the blackhole"
        )
    finally:
        wire.stop()
        for m in (a, b, c):
            m.close()
