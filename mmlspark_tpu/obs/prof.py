"""Always-on wall-clock sampling profiler — "what is every thread doing".

Metrics say *that* something is slow and spans say *where one request*
spent its time; neither answers "what was this process standing in when
it wedged". A stdlib-only daemon thread samples ``sys._current_frames()``
at a configurable rate (default 19 Hz — deliberately co-prime with 1 Hz
and 10 Hz periodic work so the sampler never phase-locks onto a timer
loop) and aggregates per-thread **collapsed flame stacks** in a bounded
dict: ``thread;frame;frame;... count`` lines, directly feedable to any
flamegraph renderer.

Exposure:

- ``GET /profile`` on every instrumented ingress (WorkerServer — which
  is also the gateway's and the trainer's artifact ingress — and the
  driver registry) returns the collapsed-stack text and **starts the
  sampler on first scrape** if the process didn't already;
  ``fleet profile <role|url> [--seconds N]`` diffs two scrapes N seconds
  apart and merges the window across processes into one fleet view.
- ``GET /debug/threads`` returns an instant all-thread dump (JSON) —
  no sampler needed, one ``sys._current_frames()`` walk.
- :func:`collapsed_now` / :func:`threads_payload` are the in-process
  halves the hang watchdog (obs/watchdog.py) embeds into stall dumps.

Exported metrics (``tools/lint_metric_names.py`` family ``prof``):
``mmlspark_prof_samples_total`` (sampling passes taken),
``mmlspark_prof_drops_total{reason}`` (``overflow``: distinct stacks
beyond the per-thread bound collapse into an overflow bucket;
``behind``: sampler overslept more than one period and skipped ticks),
``mmlspark_prof_overhead_ratio`` (EWMA fraction of wall time spent
inside the sampling pass — the smoke test's sampler-overhead gate reads
this gauge).

Env knobs: ``MMLSPARK_PROF_HZ`` (default 19; ``0`` disables
:func:`ensure_started`), ``MMLSPARK_PROF_MAX_STACKS`` (distinct
collapsed stacks kept per thread, default 512).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from mmlspark_tpu.obs import tracing
from mmlspark_tpu.obs.registry import counter, gauge

_M_SAMPLES = counter(
    "mmlspark_prof_samples_total",
    "Sampling-profiler passes over sys._current_frames()",
)
_M_DROPS = counter(
    "mmlspark_prof_drops_total",
    "Profiler data dropped (overflow: stack dict at bound; behind: "
    "sampler overslept and skipped ticks)", labels=("reason",),
)
_M_OVERHEAD = gauge(
    "mmlspark_prof_overhead_ratio",
    "EWMA fraction of wall time the sampling pass consumes "
    "(the smoke probe's sampler-overhead bound reads this)",
)

DEFAULT_HZ = 19.0
_OVERFLOW_KEY = "<overflow>"


def _frame_key(frame: Any) -> str:
    """One collapsed-stack element: ``file:function``. No line numbers —
    a hot loop would otherwise mint one stack per line it was caught on
    and blow the bound with near-duplicates (the instant dump keeps
    lines; aggregation wants the function)."""
    co = frame.f_code
    return f"{os.path.basename(co.co_filename)}:{co.co_name}"


def _collapse(frame: Any, limit: int = 64) -> str:
    """Root-first semicolon-joined frames of one thread's stack."""
    parts: list = []
    depth = 0
    while frame is not None and depth < limit:
        parts.append(_frame_key(frame))
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts)


def _thread_names() -> Dict[int, str]:
    return {t.ident: t.name for t in threading.enumerate() if t.ident}


def threads_payload() -> dict:
    """Instant all-thread dump: every live thread's full stack with line
    numbers (``GET /debug/threads``; also embedded in watchdog stall
    dumps). Pure ``sys._current_frames()`` — works with the sampler off.
    """
    names = _thread_names()
    threads = []
    for ident, frame in sys._current_frames().items():
        stack: list = []
        f = frame
        depth = 0
        while f is not None and depth < 128:
            co = f.f_code
            stack.append(f"{co.co_filename}:{f.f_lineno} {co.co_name}")
            f = f.f_back
            depth += 1
        stack.reverse()
        threads.append({
            "ident": ident,
            "name": names.get(ident, f"thread-{ident}"),
            "stack": stack,
            "collapsed": _collapse(frame),
        })
    threads.sort(key=lambda t: t["name"])
    return {
        "process": tracing.process_label(),
        "ts": round(time.time(), 3),
        "threads": threads,
    }


def collapsed_now() -> str:
    """One instantaneous collapsed-stack line per live thread (count 1)
    — the zero-state fallback the watchdog embeds when a process wedges
    before its sampler accumulated anything."""
    payload = threads_payload()
    return "".join(
        f"{t['name']};{t['collapsed']} 1\n" for t in payload["threads"]
    )


class SamplingProfiler:
    """Daemon-thread wall-clock sampler with bounded per-thread stacks."""

    def __init__(
        self, hz: Optional[float] = None, max_stacks: Optional[int] = None
    ):
        env_hz = os.environ.get("MMLSPARK_PROF_HZ")
        self.hz = float(hz if hz is not None else (env_hz or DEFAULT_HZ))
        self.max_stacks = int(
            max_stacks
            if max_stacks is not None
            else os.environ.get("MMLSPARK_PROF_MAX_STACKS", "512")
        )
        self._lock = threading.Lock()
        # {thread_name: {collapsed_stack: count}} — thread NAME, not
        # ident: a respawned worker thread keeps aggregating into the
        # same flame rather than minting a dead twin per incarnation
        self._stacks: Dict[str, Dict[str, int]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.samples = 0
        self.started_at = 0.0
        self._overhead_ewma = 0.0

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "SamplingProfiler":
        with self._lock:
            if self.running or self.hz <= 0:
                return self
            self._stop.clear()
            self.started_at = time.monotonic()
            self._thread = threading.Thread(
                target=self._loop, name="mmlspark-prof-sampler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(2.0)
        self._thread = None

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self.samples = 0

    # -- sampling ------------------------------------------------------------

    def _loop(self) -> None:
        period = 1.0 / self.hz
        me = threading.get_ident()
        next_at = time.monotonic()
        while not self._stop.is_set():
            next_at += period
            now = time.monotonic()
            if now < next_at:
                if self._stop.wait(next_at - now):
                    return
            elif now - next_at > period:
                # overslept a whole period (GIL starvation, suspend):
                # skip the missed ticks rather than burst-sample —
                # bursts would over-weight whatever starved us
                missed = int((now - next_at) / period)
                next_at += missed * period
                if _M_DROPS._on:
                    _M_DROPS.labels(reason="behind").inc(missed)
            t0 = time.perf_counter()
            self._sample_once(me)
            cost = time.perf_counter() - t0
            # EWMA of (time sampling) / (period): the steady-state
            # fraction of one core this profiler burns
            self._overhead_ewma = (
                0.95 * self._overhead_ewma + 0.05 * (cost / period)
            )
            if _M_OVERHEAD._on:
                _M_OVERHEAD.set(round(self._overhead_ewma, 6))

    def _sample_once(self, skip_ident: int) -> None:
        names = _thread_names()
        frames = sys._current_frames()
        with self._lock:
            self.samples += 1
            for ident, frame in frames.items():
                if ident == skip_ident:
                    continue  # the sampler never profiles itself
                tname = names.get(ident, f"thread-{ident}")
                per = self._stacks.get(tname)
                if per is None:
                    per = self._stacks[tname] = {}
                key = _collapse(frame)
                if key in per or len(per) < self.max_stacks:
                    per[key] = per.get(key, 0) + 1
                else:
                    # bound hit: new distinct stacks fold into one
                    # overflow bucket instead of growing without limit
                    per[_OVERFLOW_KEY] = per.get(_OVERFLOW_KEY, 0) + 1
                    if _M_DROPS._on:
                        _M_DROPS.labels(reason="overflow").inc()
        if _M_SAMPLES._on:
            _M_SAMPLES.inc()

    # -- exposition ----------------------------------------------------------

    def collapsed(self) -> str:
        """Flamegraph-ready collapsed-stack text: one
        ``thread;frame;...;frame count`` line per (thread, stack)."""
        with self._lock:
            snap = {t: dict(per) for t, per in self._stacks.items()}
        lines = []
        for tname in sorted(snap):
            for stack, n in sorted(snap[tname].items()):
                lines.append(f"{tname};{stack} {n}\n")
        return "".join(lines)

    def profile_payload(self) -> str:
        """The ``GET /profile`` body: a comment header (process, rate,
        sample count, overhead — ``#``-prefixed, ignored by flamegraph
        tooling) followed by the collapsed stacks."""
        head = (
            f"# process: {tracing.process_label()}\n"
            f"# hz: {self.hz:g}\n"
            f"# samples: {self.samples}\n"
            f"# running: {str(self.running).lower()}\n"
            f"# overhead_ratio: {self._overhead_ewma:.6f}\n"
        )
        return head + self.collapsed()


# the process-wide sampler every /profile ingress serves from
PROFILER = SamplingProfiler()


def ensure_started() -> SamplingProfiler:
    """Start the process sampler if it isn't running (fleet roles call
    this at boot; ``GET /profile`` calls it on first scrape so even a
    process booted without it starts accumulating the moment someone
    looks). ``MMLSPARK_PROF_HZ=0`` disables."""
    if not PROFILER.running:
        PROFILER.start()
    return PROFILER


def parse_collapsed(text: str) -> Dict[str, int]:
    """Parse collapsed-stack text back to ``{stack_line: count}`` —
    ``fleet profile``'s scrape-side half (comment lines skipped)."""
    out: Dict[str, int] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        stack, _, n = line.rpartition(" ")
        if not stack:
            continue
        try:
            out[stack] = out.get(stack, 0) + int(n)
        except ValueError:
            continue
    return out


def merge_collapsed(per_process: Dict[str, Dict[str, int]]) -> str:
    """Merge per-process ``{stack: count}`` maps into one fleet-wide
    collapsed view, each stack prefixed with its process name so one
    flamegraph shows which process owns which flame."""
    lines = []
    for proc in sorted(per_process):
        for stack, n in sorted(per_process[proc].items()):
            lines.append(f"{proc};{stack} {n}\n")
    return "".join(lines)


__all__ = [
    "DEFAULT_HZ",
    "PROFILER",
    "SamplingProfiler",
    "collapsed_now",
    "ensure_started",
    "merge_collapsed",
    "parse_collapsed",
    "threads_payload",
]
