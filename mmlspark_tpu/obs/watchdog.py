"""Hang watchdog: named progress counters that auto-dump all-thread
stacks when they stop moving.

The flight recorder explains what the last N *requests* did; the
sampling profiler (obs/prof.py) explains where time goes while things
move. This module covers the third failure mode — nothing moves at all:
a gang round that never completes, a dispatcher batch wedged inside a
handler, a bench segment past its budget, an experiment trial whose rung
report never lands. Processes register **named progress counters**; a
counter that was armed (ticked at least once) and then goes silent past
its deadline triggers one **stall dump** per episode: all-thread stacks
(with the wedged frames), the sampler's collapsed flames, and the
flight-recorder tail, written to the same on-error spool flightrec uses
(``MMLSPARK_FLIGHTREC_DIR``, default ``<tmp>/mmlspark_flightrec``) as
``stalldump-*.json``, and counted in
``mmlspark_watchdog_stalls_total{source}``.

Call-site contract::

    from mmlspark_tpu.obs import watchdog
    watchdog.tick("elastic.round", deadline_s=300)   # auto-registers
    ...                                              # every round
    watchdog.disarm("elastic.round")                 # work finished

``tick`` re-arms a disarmed counter; ``disarm`` pauses monitoring (an
*idle* dispatcher is healthy — only silence while armed is a stall).
``watchdog.scope(name, deadline_s)`` arms around a block. One dump per
stall episode: a stalled counter dumps once, then waits for a tick
before it can fire again (a 10-minute wedge is one file, not twenty).

``SIGUSR2`` (opt-in via :func:`install_sigusr2`, installed by the fleet
CLI roles and the bench child) writes the same dump on demand —
``bench.py``'s harvest loop signals a stalled child and collects the
dump *before* killing it, so a stalled segment names its wedged frame in
the BENCH json instead of just going missing.

Fault point ``obs.watchdog_dump`` fires on every stall-dump attempt
(chaos can fail the spool write; the stall is still counted — losing
the dump must never lose the signal).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

from mmlspark_tpu.obs import tracing
from mmlspark_tpu.obs.registry import counter

_M_STALLS = counter(
    "mmlspark_watchdog_stalls_total",
    "Registered progress counters that went silent past their deadline, "
    "by counter name", labels=("source",),
)

DEFAULT_DEADLINE_S = 120.0
# how many flight-recorder records ride along in a stall dump
_FLIGHTREC_TAIL = 64


class _Progress:
    __slots__ = ("name", "deadline_s", "last_tick", "armed", "dumped",
                 "ticks")

    def __init__(self, name: str, deadline_s: float):
        self.name = name
        self.deadline_s = float(deadline_s)
        self.last_tick = time.monotonic()
        self.armed = True
        self.dumped = False
        self.ticks = 0


def dump_stacks(reason: str, source: Optional[str] = None,
                dump_dir: Optional[str] = None) -> Optional[str]:
    """Write one stall dump (all-thread stacks + collapsed flames +
    flight-recorder tail) into the flightrec spool. Returns the path, or
    None when the write failed — a broken disk must not take the caller
    down. Shared by the watchdog monitor, SIGUSR2, and tests."""
    from mmlspark_tpu.core import faults
    from mmlspark_tpu.obs import prof
    from mmlspark_tpu.obs.flightrec import FLIGHT

    # chaos hook: an injected error here simulates a failed spool write
    # (the caller counts the stall regardless)
    faults.inject(
        "obs.watchdog_dump", context={"reason": reason, "source": source}
    )
    payload = prof.threads_payload()
    payload["reason"] = reason
    payload["source"] = source
    payload["collapsed"] = prof.collapsed_now()
    if prof.PROFILER.samples:
        # the sampler's aggregate names the wedged frame with history
        # behind it, not just the instant of the dump
        payload["profile"] = prof.PROFILER.profile_payload()
    payload["flightrec_tail"] = FLIGHT.snapshot()[-_FLIGHTREC_TAIL:]
    out_dir = dump_dir or FLIGHT.dump_dir
    try:
        os.makedirs(out_dir, exist_ok=True)
        fname = (
            f"stalldump-{time.strftime('%Y%m%d-%H%M%S')}"
            f"-{os.getpid()}-{reason}.json"
        )
        final = os.path.join(out_dir, fname)
        tmp = final + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, final)  # a collector never sees a half dump
    except OSError:
        return None
    return final


class Watchdog:
    """Monitor thread over the process's registered progress counters."""

    def __init__(self, poll_s: float = 1.0):
        self.poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._counters: Dict[str, _Progress] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.stalls: Dict[str, int] = {}
        self.last_dump: Optional[str] = None

    # -- registration --------------------------------------------------------

    def tick(self, name: str, deadline_s: float = DEFAULT_DEADLINE_S) -> None:
        """Record progress on ``name`` (auto-registers and re-arms). The
        monitor starts lazily on the first tick of the process."""
        start = False
        with self._lock:
            p = self._counters.get(name)
            if p is None:
                p = self._counters[name] = _Progress(name, deadline_s)
            else:
                p.deadline_s = float(deadline_s)
            p.last_tick = time.monotonic()
            p.armed = True
            p.dumped = False
            p.ticks += 1
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._loop, name="mmlspark-watchdog", daemon=True
                )
                start = True
        if start:
            self._thread.start()

    def disarm(self, name: str) -> None:
        """Pause monitoring of ``name`` until its next tick — the work it
        tracked finished (or went legitimately idle)."""
        with self._lock:
            p = self._counters.get(name)
            if p is not None:
                p.armed = False

    def unregister(self, name: str) -> None:
        with self._lock:
            self._counters.pop(name, None)

    def scope(self, name: str, deadline_s: float = DEFAULT_DEADLINE_S):
        """``with watchdog.scope("modelstore.batch", 60):`` — armed for
        the block, disarmed on exit (even via exception)."""
        return _Scope(self, name, deadline_s)

    def counters(self) -> dict:
        """Registration table (debug/introspection)."""
        with self._lock:
            return {
                n: {
                    "deadline_s": p.deadline_s,
                    "armed": p.armed,
                    "ticks": p.ticks,
                    "silent_s": round(time.monotonic() - p.last_tick, 3),
                }
                for n, p in self._counters.items()
            }

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(2.0)
        self._thread = None

    def reset(self) -> None:
        """Drop every counter and stall tally (test isolation)."""
        with self._lock:
            self._counters.clear()
            self.stalls.clear()
            self.last_dump = None

    # -- monitoring ----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            now = time.monotonic()
            stalled: list = []
            with self._lock:
                for p in self._counters.values():
                    if (
                        p.armed
                        and not p.dumped
                        and now - p.last_tick > p.deadline_s
                    ):
                        p.dumped = True  # one dump per stall episode
                        stalled.append(p.name)
            for name in stalled:
                self._on_stall(name)

    def _on_stall(self, name: str) -> None:
        self.stalls[name] = self.stalls.get(name, 0) + 1
        _M_STALLS.labels(source=name).inc()
        try:
            self.last_dump = dump_stacks("watchdog_stall", source=name)
        except Exception:  # noqa: BLE001 — injected (or real) dump failure
            self.last_dump = None


class _Scope:
    def __init__(self, wd: Watchdog, name: str, deadline_s: float):
        self.wd, self.name, self.deadline_s = wd, name, deadline_s

    def __enter__(self) -> "_Scope":
        self.wd.tick(self.name, self.deadline_s)
        return self

    def __exit__(self, *exc: Any) -> None:
        self.wd.disarm(self.name)


# the process-wide watchdog every subsystem ticks
WATCHDOG = Watchdog()


def tick(name: str, deadline_s: float = DEFAULT_DEADLINE_S) -> None:
    WATCHDOG.tick(name, deadline_s)


def disarm(name: str) -> None:
    WATCHDOG.disarm(name)


def scope(name: str, deadline_s: float = DEFAULT_DEADLINE_S) -> Iterator:
    return WATCHDOG.scope(name, deadline_s)


def install_sigusr2() -> bool:
    """SIGUSR2 -> write a stall dump on demand (fleet CLI roles and the
    bench child call this; handlers only install from the main thread).
    Returns whether the handler was installed."""
    import signal

    def on_sig(signum: int, frame: Any) -> None:
        try:
            path = dump_stacks("sigusr2")
        except Exception:  # noqa: BLE001 — injected dump failure
            path = None
        print(f"watchdog: stack dump to {path}", flush=True)

    try:
        signal.signal(signal.SIGUSR2, on_sig)
        return True
    except (ValueError, OSError):  # non-main thread / unsupported platform
        return False


__all__ = [
    "DEFAULT_DEADLINE_S",
    "WATCHDOG",
    "Watchdog",
    "disarm",
    "dump_stacks",
    "install_sigusr2",
    "scope",
    "tick",
]
