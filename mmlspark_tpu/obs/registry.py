"""MetricsRegistry: counters, gauges, fixed-bucket histograms; Prometheus
text exposition v0.0.4 and its scrape-side parser.

Design constraints, in priority order:

1. Hot-path cost. Serving instruments fire per request; call sites
   pre-resolve label children once (``family.labels(server=name)``) so the
   per-event op is one enabled-check + one locked float add. A disabled
   registry short-circuits before the lock.
2. No dependencies. stdlib only; scraping/aggregation (serving/fleet.py
   ``top``) reuses :func:`parse_text` rather than a client library.
3. Prometheus-compatible output. ``GET /metrics`` on the worker, gateway
   and driver registry all emit :func:`render`'s text so any standard
   scraper ingests the fleet unchanged.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Iterable, Optional, Sequence

# latency-oriented default: 100 µs .. 10 s (fixed buckets per metric family
# keep scrape output bounded and make cross-worker aggregation exact)
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# size-oriented alternative (batch sizes, queue depths)
SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

_NAME_OK = set("abcdefghijklmnopqrstuvwxyz0123456789_")


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    return "{" + ",".join(
        f'{k}="{_escape(v)}"' for k, v in zip(names, values)
    ) + "}"


class _Family:
    """Shared family machinery: label-child management + one lock.

    An unlabeled family is its own single child; a labeled one lazily
    creates a child per label-value tuple. One lock per family serves both
    child creation and child value ops — serving-level contention on a
    CPython float add is negligible, and it keeps snapshot() consistent.
    """

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labels: Sequence[str]):
        self._reg = registry
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        self._lock = threading.Lock()
        self._children: dict[tuple, Any] = {}

    def labels(self, **kv: Any) -> Any:
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.label_names}, "
                f"got {tuple(sorted(kv))}"
            )
        key = tuple(str(kv[k]) for k in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child()
                    self._children[key] = child
        return child

    def _make_child(self) -> Any:
        raise NotImplementedError

    def remove(self, **kv: Any) -> None:
        """Drop one label child (series lifecycle: e.g. a gateway pruning
        the series of a permanently departed backend). No-op when absent;
        a later ``labels()`` recreates the child at zero (standard
        Prometheus counter-reset semantics, handled by ``rate()``)."""
        key = tuple(str(kv[k]) for k in self.label_names)
        with self._lock:
            self._children.pop(key, None)

    def _read(self) -> list:
        """[(label_values_tuple, payload)] materialized UNDER the family
        lock, so a scrape never sees a torn histogram (counts incremented
        but count not yet — cumulative buckets would exceed +Inf).
        Payload: float for counter/gauge, (counts, sum, count) copies for
        histograms."""
        with self._lock:
            items = (
                sorted(self._children.items()) if self.label_names
                else [((), self)]
            )
            out = []
            for values, child in items:
                if self.kind == "histogram":
                    out.append(
                        (values, (list(child.counts), child.sum, child.count))
                    )
                else:
                    out.append((values, child._value))
            return out

    def reset(self) -> None:
        with self._lock:
            targets = (
                list(self._children.values()) if self.label_names else [self]
            )
        for t in targets:
            t._zero()


class _CounterChild:
    __slots__ = ("_on", "_lock", "_value")

    def __init__(self, registry: "MetricsRegistry", lock: threading.Lock):
        # the enabled flag is CACHED on every child and family
        # (set_enabled walks the registry propagating it). Hot call sites
        # may branch on the pre-bound child's/family's ``_on`` directly to
        # skip a whole instrument bundle with ONE attribute load — that,
        # not per-op checks, is what keeps the serving path's disabled
        # per-request overhead under 1 µs (tests/test_obs.py)
        self._on = registry._enabled
        self._lock = lock
        self._value = 0.0

    def inc(self, v: float = 1.0) -> None:
        if not self._on:
            return
        with self._lock:
            self._value += v

    @property
    def value(self) -> float:
        return self._value

    def _zero(self) -> None:
        self._value = 0.0


class Counter(_Family, _CounterChild):
    """Monotone counter. ``.inc()`` on the family (unlabeled) or on
    ``.labels(...)`` children."""

    kind = "counter"

    def __init__(self, registry, name, help, labels):
        _Family.__init__(self, registry, name, help, labels)
        _CounterChild.__init__(self, registry, self._lock)

    def _make_child(self) -> _CounterChild:
        return _CounterChild(self._reg, self._lock)

    def inc(self, v: float = 1.0) -> None:
        if self.label_names:
            raise ValueError(f"metric {self.name!r} needs .labels(...)")
        _CounterChild.inc(self, v)


class _GaugeChild:
    __slots__ = ("_on", "_lock", "_value")

    def __init__(self, registry: "MetricsRegistry", lock: threading.Lock):
        self._on = registry._enabled
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self._on:
            return
        self._value = float(v)

    def inc(self, v: float = 1.0) -> None:
        if not self._on:
            return
        with self._lock:
            self._value += v

    def dec(self, v: float = 1.0) -> None:
        self.inc(-v)

    @property
    def value(self) -> float:
        return self._value

    def _zero(self) -> None:
        self._value = 0.0


class Gauge(_Family, _GaugeChild):
    kind = "gauge"

    def __init__(self, registry, name, help, labels):
        _Family.__init__(self, registry, name, help, labels)
        _GaugeChild.__init__(self, registry, self._lock)

    def _make_child(self) -> _GaugeChild:
        return _GaugeChild(self._reg, self._lock)


class _HistogramChild:
    __slots__ = ("_on", "_lock", "_bounds", "counts", "sum", "count",
                 "exemplars")

    def __init__(self, registry: "MetricsRegistry", lock: threading.Lock,
                 bounds: Sequence[float]):
        self._on = registry._enabled
        self._lock = lock
        self._bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self.sum = 0.0
        self.count = 0
        # per-bucket exemplar: (trace_id, value) of the LAST observation
        # that carried a trace id — the bucket -> real-trace jump table
        # (`fleet traces --slowest`). Lazily allocated: histograms whose
        # call sites never pass a trace id pay nothing.
        self.exemplars: Optional[list] = None

    def observe(self, v: float, trace_id: Optional[str] = None) -> None:
        if not self._on:
            return
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if trace_id is not None:
                if self.exemplars is None:
                    self.exemplars = [None] * (len(self._bounds) + 1)
                self.exemplars[i] = (trace_id, v)

    def _zero(self) -> None:
        self.counts = [0] * (len(self._bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.exemplars = None


class Histogram(_Family, _HistogramChild):
    """Fixed-bucket histogram (cumulative ``le`` buckets on render)."""

    kind = "histogram"

    def __init__(self, registry, name, help, labels,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        _Family.__init__(self, registry, name, help, labels)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        _HistogramChild.__init__(self, registry, self._lock, self.buckets)

    def _make_child(self) -> _HistogramChild:
        return _HistogramChild(self._reg, self._lock, self.buckets)

    def exemplar_samples(self) -> list:
        """[{labels, le, trace_id, value}] for every bucket exemplar this
        family holds (materialized under the family lock)."""
        out = []
        with self._lock:
            items = (
                sorted(self._children.items()) if self.label_names
                else [((), self)]
            )
            for values, child in items:
                ex = child.exemplars
                if not ex:
                    continue
                ld = dict(zip(self.label_names, values))
                bounds = list(self.buckets) + [math.inf]
                for b, slot in zip(bounds, ex):
                    if slot is None:
                        continue
                    out.append({
                        "labels": ld,
                        "le": "+Inf" if b == math.inf else _fmt(b),
                        "trace_id": slot[0],
                        "value": slot[1],
                    })
        return out


class MetricsRegistry:
    """Process-wide metric store. Families are get-or-create by name — a
    second registration with the same (type, labels, buckets) returns the
    SAME family, so modules can declare their metrics at import time
    without coordinating; a conflicting re-registration raises."""

    def __init__(self) -> None:
        self._enabled = True
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    @property
    def enabled(self) -> bool:
        return self._enabled

    @enabled.setter
    def enabled(self, on: bool) -> None:
        # propagate to every child's cached flag: the per-event check is
        # then a single attribute load (see _CounterChild)
        on = bool(on)
        self._enabled = on
        for fam in self.families():
            with fam._lock:
                fam._on = on
                for child in fam._children.values():
                    child._on = on

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Sequence[str], **kw: Any) -> Any:
        _validate_name(name)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if type(fam) is not cls or fam.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind} with labels {fam.label_names}"
                    )
                if kw.get("buckets") is not None and tuple(
                    sorted(float(b) for b in kw["buckets"])
                ) != fam.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"different buckets"
                    )
                return fam
            fam = (
                cls(self, name, help, labels, buckets=kw["buckets"])
                if kw.get("buckets") is not None
                else cls(self, name, help, labels)
            )
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def families(self) -> list:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def exemplars(self) -> dict:
        """{histogram name: [{labels, le, trace_id, value}]} across the
        registry — only histograms that recorded at least one trace-id
        exemplar appear."""
        out: dict = {}
        for fam in self.families():
            if fam.kind != "histogram":
                continue
            samples = fam.exemplar_samples()
            if samples:
                out[fam.name] = samples
        return out

    def reset(self) -> None:
        for fam in self.families():
            fam.reset()

    # -- snapshot / exposition ------------------------------------------------

    def snapshot(self) -> dict:
        """name -> {kind, help, samples: [(labels_dict, value_or_hist)]}.
        Histogram values are {buckets: [(le, cumulative)], sum, count}."""
        out: dict = {}
        for fam in self.families():
            samples = []
            for values, payload in fam._read():
                ld = dict(zip(fam.label_names, values))
                if fam.kind == "histogram":
                    counts, total, count = payload
                    cum, acc = [], 0
                    for b, c in zip(fam.buckets, counts):
                        acc += c
                        cum.append((b, acc))
                    samples.append((ld, {
                        "buckets": cum, "sum": total, "count": count,
                    }))
                else:
                    samples.append((ld, payload))
            out[fam.name] = {
                "kind": fam.kind, "help": fam.help, "samples": samples,
            }
        return out

    def render(self) -> str:
        """Prometheus text exposition format v0.0.4."""
        lines: list = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, payload in fam._read():
                ls = _label_str(fam.label_names, values)
                if fam.kind == "histogram":
                    counts, total, count = payload
                    acc = 0
                    for b, c in zip(fam.buckets, counts):
                        acc += c
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_le_labels(fam.label_names, values, _fmt(b))}"
                            f" {acc}"
                        )
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_le_labels(fam.label_names, values, '+Inf')}"
                        f" {count}"
                    )
                    lines.append(f"{fam.name}_sum{ls} {_fmt(total)}")
                    lines.append(f"{fam.name}_count{ls} {count}")
                else:
                    lines.append(f"{fam.name}{ls} {_fmt(payload)}")
        return "\n".join(lines) + "\n"


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _le_labels(names: Sequence[str], values: Sequence[str], le: str) -> str:
    return _label_str(tuple(names) + ("le",), tuple(values) + (le,))


def _validate_name(name: str) -> None:
    if not name or not set(name) <= _NAME_OK or name[0].isdigit():
        raise ValueError(f"invalid metric name {name!r}")


# -- process-wide default registry -------------------------------------------

REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, labels, buckets=buckets)


def render() -> str:
    return REGISTRY.render()


# -- scrape side --------------------------------------------------------------

def parse_text(text: str) -> dict:
    """Parse exposition text -> {(name, ((label, value), ...)): float}.

    The inverse of :func:`render` for the metrics the fleet aggregator
    needs (counters, gauges, histogram _sum/_count/_bucket samples all
    appear under their literal sample names). Label pairs are sorted for
    stable keys."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labels_part, value_part = rest.rsplit("}", 1)
                labels = []
                for pair in _split_labels(labels_part):
                    k, _, v = pair.partition("=")
                    labels.append((k.strip(), _unescape(v.strip().strip('"'))))
                value = float(value_part.strip())
                out[(name, tuple(sorted(labels)))] = value
            else:
                name, value_part = line.rsplit(None, 1)
                out[(name, ())] = float(value_part)
        except ValueError:
            continue  # scrape must survive a malformed line, not die on it
    return out


def _split_labels(s: str) -> Iterable[str]:
    """Split 'a="x",b="y,z"' on commas OUTSIDE quotes."""
    depth_quote = False
    cur = []
    prev = ""
    for ch in s:
        if ch == '"' and prev != "\\":
            depth_quote = not depth_quote
        if ch == "," and not depth_quote:
            yield "".join(cur)
            cur = []
        else:
            cur.append(ch)
        prev = ch
    if cur:
        yield "".join(cur)


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def sum_samples(parsed: dict, name: str,
                match: Optional[dict] = None) -> float:
    """Sum every sample of ``name`` whose labels include ``match``."""
    want = set((match or {}).items())
    total = 0.0
    for (n, labels), v in parsed.items():
        if n == name and want <= set(labels):
            total += v
    return total
