"""SLO engine: declarative targets evaluated into multi-window burn rates.

The SRE alerting shape for the serving fleet: an operator declares what
"good" means (availability %, p99 latency budget) per model/route; the
engine samples cumulative counters/histograms (the same families
``/metrics`` exposes), maintains a short history, and computes **burn
rates** over 5m/1h windows — the ratio of the observed bad fraction to
the error budget (``1 - availability``). Burn 1.0 = exactly spending the
budget; 14.4 on the 5m window = the classic page-now threshold (budget
gone in ~50 minutes).

The SLI is unified: a request is *bad* when it errored OR (with a
``p99_ms`` budget set) finished over the latency budget — the
over-budget count comes straight from the cumulative histogram buckets,
so no extra instrumentation rides the request path.

Exported per target (``/metrics`` on whatever process runs the engine):

- ``mmlspark_slo_burn_rate_ratio{slo, window}``
- ``mmlspark_slo_error_budget_remaining_ratio{slo}`` (lifetime)
- ``mmlspark_slo_bad_fraction_ratio{slo}`` (lifetime bad/total)
- ``mmlspark_slo_p99_latency_seconds{slo}`` (bucket estimate, lifetime)
- ``mmlspark_slo_status_count{slo}`` — 0 green / 1 yellow / 2 red
- ``mmlspark_slo_evaluations_total``

Fleet wiring: workers and the gateway run an engine thread over their
own registry (``fleet worker/gateway --slo-targets ...``; sensible
defaults otherwise), ``fleet top`` renders the scraped status gauges as
a red/yellow/green column, and the deploy smoke fails on a red target.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from mmlspark_tpu.obs.registry import (
    REGISTRY,
    counter,
    gauge,
    parse_text,
    sum_samples,
)

# evaluation windows: (label, seconds). Multi-window per SRE practice —
# the short window catches fast burns, the long one filters blips.
WINDOWS = (("5m", 300.0), ("1h", 3600.0))

# burn-rate thresholds for the status column: red pages, yellow warns
RED_BURN = 14.4
YELLOW_BURN = 1.0

GREEN, YELLOW, RED = 0, 1, 2
STATUS_NAMES = {GREEN: "green", YELLOW: "yellow", RED: "red"}

_M_BURN = gauge(
    "mmlspark_slo_burn_rate_ratio",
    "Error-budget burn rate per target and window (1.0 = spending the "
    "budget exactly; >= 14.4 on 5m is page-now)",
    labels=("slo", "window"),
)
_M_BUDGET = gauge(
    "mmlspark_slo_error_budget_remaining_ratio",
    "Fraction of the lifetime error budget still unspent, per target",
    labels=("slo",),
)
_M_BAD = gauge(
    "mmlspark_slo_bad_fraction_ratio",
    "Lifetime bad-request fraction (errors + over-latency-budget), per "
    "target", labels=("slo",),
)
_M_P99 = gauge(
    "mmlspark_slo_p99_latency_seconds",
    "Bucket-estimated p99 of the target's latency histogram",
    labels=("slo",),
)
_M_STATUS = gauge(
    "mmlspark_slo_status_count",
    "Target status: 0 green, 1 yellow, 2 red", labels=("slo",),
)
_M_EVALS = counter(
    "mmlspark_slo_evaluations_total", "SLO engine evaluation ticks",
)


@dataclass
class SLOTarget:
    """One declarative objective over a metric family selection.

    ``match`` narrows by labels (e.g. ``{"server": "serving"}`` or
    ``{"model": "resnet"}``) — the per-model/route knob. When the three
    families carry DIFFERENT label sets (the gateway: its request count
    rides the labeled serving family but its failure counter and latency
    histogram are process-global), the per-metric overrides
    ``total_match`` / ``error_match`` / ``latency_match`` replace
    ``match`` for that family alone — a match selecting zero series
    would silently evaluate to a permanently-green target."""

    name: str
    availability: float = 0.999
    p99_ms: Optional[float] = None
    total_metric: str = "mmlspark_serving_requests_total"
    error_metric: str = "mmlspark_serving_handler_errors_total"
    latency_metric: str = "mmlspark_serving_request_latency_seconds"
    match: Dict[str, str] = field(default_factory=dict)
    total_match: Optional[Dict[str, str]] = None
    error_match: Optional[Dict[str, str]] = None
    latency_match: Optional[Dict[str, str]] = None

    def __post_init__(self) -> None:
        if not 0.0 < self.availability < 1.0:
            raise ValueError(
                f"slo {self.name!r}: availability must be in (0, 1), "
                f"got {self.availability}"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.availability

    def _match_for(self, which: str) -> Dict[str, str]:
        override = getattr(self, f"{which}_match")
        return self.match if override is None else override

    @staticmethod
    def from_spec(spec: Any) -> "SLOTarget":
        """Dict / JSON string -> target. Unknown keys raise (a typo'd
        field silently ignored is an alert that never fires)."""
        if isinstance(spec, str):
            spec = json.loads(spec)
        if not isinstance(spec, dict):
            raise ValueError("SLO target spec must be a JSON object")
        known = {
            "name", "availability", "p99_ms", "total_metric",
            "error_metric", "latency_metric", "match",
            "total_match", "error_match", "latency_match",
        }
        unknown = set(spec) - known
        if unknown:
            raise ValueError(
                f"unknown SLO target field(s) {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        if "name" not in spec:
            raise ValueError('SLO target needs a "name"')
        return SLOTarget(**spec)


def load_targets(spec: Any) -> list:
    """``--slo-targets`` grammar: a JSON list of target objects, inline
    or a path to a file holding one."""
    if isinstance(spec, str):
        s = spec.strip()
        if not s.startswith("["):
            with open(s, encoding="utf-8") as f:
                s = f.read()
        spec = json.loads(s)
    if not isinstance(spec, list):
        raise ValueError("SLO targets spec must be a JSON list")
    return [SLOTarget.from_spec(t) for t in spec]


def default_targets(
    service_name: str = "serving",
    availability: float = 0.999,
    p99_ms: Optional[float] = 250.0,
    gateway: bool = False,
) -> list:
    """The out-of-the-box objectives a fleet role evaluates when no
    ``--slo-targets`` was given: one availability+latency target over the
    role's own serving family."""
    if gateway:
        return [SLOTarget(
            name=f"{service_name}-gateway",
            availability=availability,
            p99_ms=p99_ms,
            total_metric="mmlspark_serving_requests_total",
            error_metric="mmlspark_gateway_failures_total",
            latency_metric="mmlspark_gateway_request_latency_seconds",
            # the gateway's ingress count rides the labeled serving
            # family, but its failure counter (labels: reason) and
            # latency histogram (unlabeled) are process-global — a
            # server-label match there would select ZERO series and the
            # target could never leave green
            match={"server": f"{service_name}-gateway"},
            error_match={},
            latency_match={},
        )]
    return [SLOTarget(
        name=service_name,
        availability=availability,
        p99_ms=p99_ms,
        match={"server": service_name},
    )]


def freshness_target(
    name: str = "online-freshness",
    budget_ms: float = 5000.0,
    availability: float = 0.99,
) -> SLOTarget:
    """The continuous-learning freshness objective as a first-class SLO
    target: a publication is *bad* when it failed outright OR its
    example-ingested -> model-servable time exceeded ``budget_ms`` (read
    from the ``mmlspark_online_freshness_seconds`` buckets, so no extra
    instrumentation rides the training loop). Burn rates, windows and
    red/yellow thresholds are the standard engine semantics — a
    feedback stream outrunning the publish path pages exactly like a
    latency SLO would (docs/online-learning.md)."""
    return SLOTarget(
        name=name,
        availability=availability,
        p99_ms=budget_ms,
        total_metric="mmlspark_online_publish_attempts_total",
        error_metric="mmlspark_online_publish_failures_total",
        latency_metric="mmlspark_online_freshness_seconds",
    )


def _buckets_of(parsed: dict, name: str, match: dict) -> dict:
    """{le_bound: cumulative_count} summed across matching series."""
    want = set(match.items())
    out: dict = {}
    for (n, labels), v in parsed.items():
        if n != f"{name}_bucket":
            continue
        ld = dict(labels)
        le = ld.pop("le", None)
        if le is None or not want <= set(ld.items()):
            continue
        bound = math.inf if le == "+Inf" else float(le)
        out[bound] = out.get(bound, 0.0) + v
    return out


def _quantile_from_buckets(buckets: dict, q: float) -> float:
    """Smallest bucket bound whose cumulative count reaches the q-th
    observation (the standard scrape-side estimate; inf collapses to the
    largest finite bound)."""
    if not buckets:
        return 0.0
    total = buckets.get(math.inf, max(buckets.values()))
    if total <= 0:
        return 0.0
    rank = q * total
    finite = sorted(b for b in buckets if b != math.inf)
    for b in finite:
        if buckets[b] >= rank:
            return b
    return finite[-1] if finite else 0.0


def _over_budget(buckets: dict, budget_s: float) -> float:
    """Observations strictly over the latency budget: total minus the
    cumulative count at the smallest bound >= budget (conservative when
    the budget falls between bounds)."""
    if not buckets:
        return 0.0
    total = buckets.get(math.inf, max(buckets.values()))
    at_or_under = 0.0
    best = None
    for b in sorted(b for b in buckets if b != math.inf):
        if b >= budget_s:
            best = b
            break
    if best is not None:
        at_or_under = buckets[best]
    else:
        at_or_under = total  # budget beyond the largest bound: all pass
    return max(0.0, total - at_or_under)


@dataclass
class _Sample:
    t: float
    total: float
    bad: float


class SLOEngine:
    """Ticks over a metrics source, maintains per-target sample history,
    exports burn-rate gauges.

    ``source``: a callable returning parsed exposition samples (the
    :func:`parse_text` dict shape). Default: render+parse the process
    registry — the in-process fleet-role deployment. ``fleet top`` feeds
    scraped text instead via :meth:`tick(parsed=...)`."""

    def __init__(
        self,
        targets: list,
        interval_s: float = 15.0,
        source: Optional[Callable[[], dict]] = None,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self.targets = list(targets)
        self.interval_s = float(interval_s)
        self._source = source or (lambda: parse_text(REGISTRY.render()))
        self._now = time_fn
        # history long enough to anchor the largest window at the tick
        # interval (plus slack for jittered ticks)
        depth = max(64, int(WINDOWS[-1][1] / max(self.interval_s, 1.0)) + 8)
        self._hist: dict = {t.name: deque(maxlen=depth) for t in self.targets}
        self._report: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "SLOEngine":
        self.tick()  # gauges exist from the first scrape onward
        self._thread = threading.Thread(
            target=self._loop, name="slo-engine", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the engine must outlive a tick
                pass

    # -- evaluation ------------------------------------------------------------

    def tick(self, parsed: Optional[dict] = None,
             now: Optional[float] = None) -> dict:
        """One evaluation pass. Returns the per-target report dict (also
        readable later via :meth:`report`)."""
        parsed = self._source() if parsed is None else parsed
        now = self._now() if now is None else now
        out: dict = {}
        for t in self.targets:
            total = sum_samples(parsed, t.total_metric, t._match_for("total"))
            bad = sum_samples(parsed, t.error_metric, t._match_for("error"))
            buckets = _buckets_of(
                parsed, t.latency_metric, t._match_for("latency")
            )
            if t.p99_ms is not None:
                bad += _over_budget(buckets, t.p99_ms / 1e3)
            bad = min(bad, total) if total > 0 else bad
            hist = self._hist[t.name]
            hist.append(_Sample(now, total, bad))
            burns = {
                w: self._burn(hist, seconds, t.budget, now)
                for w, seconds in WINDOWS
            }
            bad_frac = (bad / total) if total > 0 else 0.0
            budget_left = (
                max(0.0, 1.0 - bad_frac / t.budget) if t.budget > 0 else 0.0
            )
            p99 = _quantile_from_buckets(buckets, 0.99)
            status = self._status(burns)
            out[t.name] = {
                "burn": burns,
                "bad_fraction": bad_frac,
                "budget_remaining": budget_left,
                "p99_s": p99,
                "status": STATUS_NAMES[status],
                "total": total,
                "bad": bad,
            }
            if REGISTRY._enabled:
                for w, b in burns.items():
                    if b is not None:
                        _M_BURN.labels(slo=t.name, window=w).set(b)
                _M_BUDGET.labels(slo=t.name).set(budget_left)
                _M_BAD.labels(slo=t.name).set(bad_frac)
                _M_P99.labels(slo=t.name).set(p99)
                _M_STATUS.labels(slo=t.name).set(status)
        _M_EVALS.inc()
        with self._lock:
            self._report = out
        return out

    @staticmethod
    def _burn(hist: deque, window_s: float, budget: float,
              now: float) -> Optional[float]:
        """Bad-fraction over the window divided by the error budget.
        Anchored at the oldest sample inside the window (or the oldest
        held, for young engines); None until two samples exist or while
        the window saw no traffic."""
        if len(hist) < 2 or budget <= 0:
            return None
        floor = now - window_s
        anchor = hist[0]
        for s in hist:
            if s.t >= floor:
                anchor = s
                break
        cur = hist[-1]
        d_total = cur.total - anchor.total
        if d_total <= 0:
            return None
        d_bad = max(0.0, cur.bad - anchor.bad)
        return (d_bad / d_total) / budget

    @staticmethod
    def _status(burns: dict) -> int:
        vals = [b for b in burns.values() if b is not None]
        if not vals:
            return GREEN
        if burns.get(WINDOWS[0][0]) is not None and (
            burns[WINDOWS[0][0]] >= RED_BURN
        ):
            return RED
        if max(vals) >= YELLOW_BURN:
            return YELLOW
        return GREEN

    def report(self) -> dict:
        with self._lock:
            return dict(self._report)

    def status(self, name: str) -> Optional[str]:
        return self.report().get(name, {}).get("status")


def status_from_scrape(parsed: dict) -> Optional[int]:
    """Worst ``mmlspark_slo_status_count`` in a scrape (the fleet-top
    column source); None when the endpoint exports no SLO gauges (a
    pre-SLO worker — the column degrades to '-')."""
    worst = None
    for (n, _labels), v in parsed.items():
        if n == "mmlspark_slo_status_count":
            worst = v if worst is None else max(worst, v)
    return int(worst) if worst is not None else None


__all__ = [
    "GREEN", "RED", "RED_BURN", "SLOEngine", "SLOTarget", "STATUS_NAMES",
    "WINDOWS", "YELLOW", "YELLOW_BURN", "default_targets",
    "freshness_target", "load_targets", "status_from_scrape",
]
