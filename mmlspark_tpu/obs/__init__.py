"""Runtime telemetry: metrics registry, span tracing, Prometheus exposition.

The reference ships per-stage StopWatch timers and VW TrainingStats
DataFrames; production visibility there came from Spark's own metrics
system. This package is the TPU rebuild's equivalent substrate — a
dependency-free (stdlib-only; jax is touched lazily and optionally)
telemetry layer every subsystem reports into:

- :class:`MetricsRegistry` — process-wide counters, gauges and
  fixed-bucket histograms with labels; thread-safe; snapshot +
  Prometheus text exposition v0.0.4 (:func:`render`); scrape-side
  :func:`parse_text` for the fleet aggregator.
- :func:`span` / :func:`record_span` — host-side tracing with trace-id
  propagation (the gateway stamps :data:`TRACE_HEADER` into forwarded
  requests; workers continue the trace). Spans export both to the
  registry (``mmlspark_trace_span_seconds`` latency histograms) and to
  ``jax.profiler.TraceAnnotation`` so host spans nest into device traces.

Metric names follow ``mmlspark_<subsystem>_<name>_<unit>`` — enforced by
``tools/lint_metric_names.py``. Catalogue: docs/observability.md.

Hot-path contract: every instrument op on a disabled registry
(:func:`set_enabled`\\ (False)) returns after one attribute read — the
serving path's full per-request instrumentation costs < 1 µs
(asserted in tests/test_obs.py).
"""

from mmlspark_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    counter,
    gauge,
    histogram,
    parse_text,
    render,
    sum_samples,
)
from mmlspark_tpu.obs.tracing import (
    BUFFER,
    PARENT_HEADER,
    Span,
    SpanBuffer,
    TRACE_HEADER,
    clear_recent_spans,
    current_trace_id,
    new_span_id,
    new_trace_id,
    process_label,
    recent_spans,
    record_span,
    render_traces,
    set_process_label,
    span,
    traces_payload,
)


def set_enabled(on: bool) -> None:
    """Enable/disable the process-wide default registry (and with it span
    recording). Disabled instruments are ~free (< 1 µs for a whole
    request's worth of calls)."""
    REGISTRY.enabled = bool(on)


def enabled() -> bool:
    return REGISTRY.enabled


def reset() -> None:
    """Zero every metric in the default registry IN PLACE (children stay
    bound — call sites pre-resolve label children for hot-path speed) and
    drop recorded spans, flight records, profiler aggregates and watchdog
    counters. Test isolation helper."""
    import sys as _sys

    from mmlspark_tpu.obs import flightrec

    REGISTRY.reset()
    clear_recent_spans()
    flightrec.FLIGHT.clear()
    # prof/watchdog state only if those modules were actually imported —
    # reset() must not drag them (and core.faults) into every test
    prof_mod = _sys.modules.get("mmlspark_tpu.obs.prof")
    if prof_mod is not None:
        prof_mod.PROFILER.reset()
    wd_mod = _sys.modules.get("mmlspark_tpu.obs.watchdog")
    if wd_mod is not None:
        wd_mod.WATCHDOG.reset()


__all__ = [
    "BUFFER",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "PARENT_HEADER",
    "REGISTRY",
    "Span",
    "SpanBuffer",
    "TRACE_HEADER",
    "clear_recent_spans",
    "counter",
    "current_trace_id",
    "enabled",
    "gauge",
    "histogram",
    "new_span_id",
    "new_trace_id",
    "parse_text",
    "process_label",
    "recent_spans",
    "record_span",
    "render",
    "render_traces",
    "reset",
    "set_enabled",
    "set_process_label",
    "span",
    "sum_samples",
    "traces_payload",
]
