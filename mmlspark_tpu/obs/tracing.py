"""Span tracing with trace-id propagation, a scrape-able span buffer,
and device-trace nesting.

A :class:`Span` is a named host-side interval tied to a trace id. The
gateway mints a trace id per ingress request and stamps it into the
forwarded request's :data:`TRACE_HEADER`; the worker reads the header and
records its own spans under the same id — one logical request is one
trace across processes, with zero infrastructure (ids ride the existing
HTTP hop). :data:`PARENT_HEADER` carries the sender's span id the same
way, so a worker's spans parent under the gateway's forward span and the
trace collector (obs/traces.py) can assemble a true cross-process tree.

Spans land in three places:

- the default metrics registry, as the ``mmlspark_trace_span_seconds``
  histogram labeled by span name — so every span family gets a latency
  distribution for free on ``/metrics``;
- the process :class:`SpanBuffer` (:data:`BUFFER`) — a bounded ring of
  finished spans, with attrs, served as JSON on ``GET /traces`` by every
  instrumented server; the trace collector scrapes and joins these;
- ``jax.profiler.TraceAnnotation`` (lazily imported, optional) — inside a
  ``jax.profiler.trace`` capture the host span nests into the device
  timeline, which is how "queue wait vs. TPU dispatch" becomes visible in
  one Perfetto view.

:func:`recent_spans` is the test/debug view of the same buffer.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Optional

from mmlspark_tpu.obs.registry import REGISTRY, histogram

# the headers the gateway stamps and workers read (lowercased: the
# WorkerServer parser lowercases header names on ingress)
TRACE_HEADER = "x-mmlspark-trace-id"
# the sender's span id: received spans set it as their parent_id so the
# cross-process tree has real edges, not name-matching heuristics
PARENT_HEADER = "x-mmlspark-parent-span"

_SPAN_SECONDS = histogram(
    "mmlspark_trace_span_seconds",
    "Duration of host-side trace spans, by span name",
    labels=("span",),
)

_tls = threading.local()

# process identity stamped onto every buffered span: the collector's
# per-hop attribution in the assembled tree. Fleet roles override it with
# something an operator recognizes ("serving@host:port").
_process_label = f"pid-{os.getpid()}"


def set_process_label(label: str) -> None:
    global _process_label
    _process_label = str(label)


def process_label() -> str:
    return _process_label

# span-name -> pre-resolved histogram child: labels() validates label
# sets per call, far too slow for per-request span recording
_span_children: dict = {}


def _span_child(name: str) -> Any:
    ch = _span_children.get(name)
    if ch is None:
        ch = _span_children[name] = _SPAN_SECONDS.labels(span=name)
    return ch

# jax.profiler.TraceAnnotation, resolved lazily once: None = not yet
# tried, False = unavailable (obs stays importable without jax)
_TA: Any = None


def _trace_annotation() -> Any:
    global _TA
    if _TA is None:
        try:
            from jax.profiler import TraceAnnotation

            _TA = TraceAnnotation
        except Exception:  # noqa: BLE001 — jax absent or too old
            _TA = False
    return _TA


# id generation: uniqueness, not cryptography. uuid4 reads the OS entropy
# pool per call (~14 µs in sandboxed containers) — far too slow for a
# per-request hot path. pid + process-start nanos make ids unique across
# processes; the C-level counter makes them unique (and thread-safe)
# within one.
_ID_BASE = f"{os.getpid():08x}{time.time_ns() & 0xFFFFFFFFFFFF:012x}"
_ID_SEQ = itertools.count()


def new_trace_id() -> str:
    return f"{_ID_BASE}{next(_ID_SEQ) & 0xFFFFFFFFFFFF:012x}"


def new_span_id() -> str:
    """Process-unique span id (pid+start-nanos base, counter suffix).
    Public because retroactive recorders (serving reply paths) mint a
    request span's id BEFORE recording it, so sibling spans can name it
    as their parent."""
    return f"{_ID_BASE[:8]}{next(_ID_SEQ) & 0xFFFFFFFFFFFFFFFF:016x}"


_new_span_id = new_span_id  # internal alias, kept for call-site brevity


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_trace_id() -> Optional[str]:
    """The innermost open span's trace id on this thread, if any."""
    s = _stack()
    return s[-1].trace_id if s else None


class Span:
    """One named interval in a trace. Slotted plain class, not a
    dataclass: spans are created per request on the serving hot path and
    dataclass construction costs ~3x (measured ~1.6 µs vs ~0.5 µs in
    this container)."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "wall_ns", "attrs", "process",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str = "",
        parent_id: Optional[str] = None,
        start_ns: int = 0,
        end_ns: int = 0,
        wall_ns: int = 0,
        attrs: Optional[dict] = None,
        process: Optional[str] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id or _new_span_id()
        self.parent_id = parent_id
        self.start_ns = start_ns  # perf_counter_ns: duration arithmetic
        self.end_ns = end_ns
        # wall-clock start (time_ns): perf_counter epochs differ per
        # process, so cross-process ordering in the assembled tree rides
        # this anchor instead
        self.wall_ns = wall_ns
        self.attrs = attrs
        self.process = process

    @property
    def duration_ns(self) -> int:
        return self.end_ns - self.start_ns

    @property
    def duration_s(self) -> float:
        return self.duration_ns / 1e9

    def set_attr(self, key: str, value: Any) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "wall_ns": self.wall_ns,
            "duration_ms": round(self.duration_ns / 1e6, 4),
            "attrs": self.attrs,
            "process": self.process or _process_label,
        }

    @staticmethod
    def from_dict(d: dict) -> "Span":
        dur_ns = int(round(float(d.get("duration_ms") or 0.0) * 1e6))
        return Span(
            name=d.get("name", ""),
            trace_id=d.get("trace_id", ""),
            span_id=d.get("span_id") or "",
            parent_id=d.get("parent_id"),
            start_ns=0,
            end_ns=dur_ns,
            wall_ns=int(d.get("wall_ns") or 0),
            attrs=d.get("attrs"),
            process=d.get("process"),
        )

    def __repr__(self) -> str:
        return (
            f"Span({self.name!r}, trace={self.trace_id}, "
            f"{self.duration_ns} ns)"
        )


class SpanBuffer:
    """Bounded ring of finished spans, safe for N recording threads and a
    concurrent scraper.

    Records are snapshotted at append time (attrs dict copied), so a
    caller mutating a span after exit can never tear a record a scraper
    already holds. ``snapshot()`` copies the ring under the lock;
    ``clear()`` mid-record is safe (an in-flight ``record`` lands in the
    post-clear ring, never half in each)."""

    def __init__(self, cap: int = 2048):
        self.cap = int(cap)
        self.enabled = True
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=self.cap)

    def record(self, sp: Span) -> None:
        if not self.enabled:
            return
        if sp.attrs is not None:
            # freeze attrs NOW: the recorder may keep mutating its dict
            sp.attrs = dict(sp.attrs)
        if sp.process is None:
            sp.process = _process_label
        with self._lock:
            self._buf.append(sp)

    def snapshot(
        self, name: Optional[str] = None, trace_id: Optional[str] = None
    ) -> list:
        with self._lock:
            spans = list(self._buf)
        return [
            s for s in spans
            if (name is None or s.name == name)
            and (trace_id is None or s.trace_id == trace_id)
        ]

    def trace_ids(self) -> list:
        """Distinct trace ids in the buffer, oldest first."""
        seen: dict = {}
        for s in self.snapshot():
            seen.setdefault(s.trace_id, None)
        return list(seen)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


_BUFFER_CAP = int(os.environ.get("MMLSPARK_TRACE_BUFFER_CAP", "2048"))
BUFFER = SpanBuffer(cap=_BUFFER_CAP)


def traces_payload(trace_id: Optional[str] = None) -> dict:
    """The ``GET /traces[/<id>]`` response body: this process's buffered
    spans (optionally one trace's) plus the registry's histogram
    exemplars — the bucket -> trace-id jump table ``fleet traces
    --slowest`` uses."""
    spans = BUFFER.snapshot(trace_id=trace_id)
    return {
        "process": _process_label,
        "count": len(spans),
        "spans": [s.to_dict() for s in spans],
        "exemplars": REGISTRY.exemplars() if trace_id is None else {},
    }


def render_traces(trace_id: Optional[str] = None) -> str:
    return json.dumps(traces_payload(trace_id))


def _record(sp: Span) -> None:
    if not REGISTRY._enabled:
        return
    _span_child(sp.name).observe(sp.duration_s)
    BUFFER.record(sp)


class _SpanContext:
    """Class-based context manager (not ``@contextmanager``: the
    generator protocol costs ~2 µs per use, and spans wrap every
    dispatched serving batch)."""

    __slots__ = ("_name", "_trace_id", "_parent_id", "_attrs", "_sp", "_ann")

    def __init__(self, name: str, trace_id: Optional[str],
                 attrs: Optional[dict], parent_id: Optional[str] = None):
        self._name = name
        self._trace_id = trace_id
        self._parent_id = parent_id
        self._attrs = attrs

    def __enter__(self) -> Span:
        stack = _stack()
        parent = stack[-1] if stack else None
        sp = Span(
            name=self._name,
            trace_id=self._trace_id
            or (parent.trace_id if parent else new_trace_id()),
            parent_id=self._parent_id
            or (parent.span_id if parent else None),
            attrs=self._attrs,
        )
        ta_cls = _trace_annotation()
        self._ann = ta_cls(self._name) if ta_cls else None
        stack.append(sp)
        self._sp = sp
        sp.wall_ns = time.time_ns()
        sp.start_ns = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__enter__()
        return sp

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        sp = self._sp
        sp.end_ns = time.perf_counter_ns()
        _stack().pop()
        _record(sp)
        return False


def span(
    name: str,
    trace_id: Optional[str] = None,
    attrs: Optional[dict] = None,
    parent_id: Optional[str] = None,
) -> _SpanContext:
    """Open a span: ``with span("gateway.forward") as sp: ...``.

    Trace id resolution: explicit argument > enclosing span on this
    thread > freshly minted. Parent resolution: explicit ``parent_id``
    (e.g. a received :data:`PARENT_HEADER` value) > enclosing span on
    this thread. The span enters a ``jax.profiler.TraceAnnotation`` of
    the same name (a no-op outside an active profiler capture), so host
    stages show up nested in device traces. The span is recorded on BOTH
    clean and exceptional exit."""
    return _SpanContext(name, trace_id, attrs, parent_id)


def record_span(
    name: str,
    start_ns: int,
    end_ns: int,
    trace_id: Optional[str] = None,
    attrs: Optional[dict] = None,
    span_id: Optional[str] = None,
    parent_id: Optional[str] = None,
) -> Optional[Span]:
    """Retroactively record a span from already-measured timestamps — the
    hot-serving-path form (no context manager overhead; the timestamps
    are perf_counter_ns values the caller already had, e.g. a request's
    ``arrival_ns``). ``span_id`` lets the caller pre-mint the id (so
    sibling spans recorded in the same pass can parent under it);
    ``parent_id`` links into an upstream span (a received
    :data:`PARENT_HEADER`). Returns the span, or None when the registry
    is disabled."""
    if not REGISTRY._enabled:
        return None
    now_ns = time.perf_counter_ns()
    sp = Span(
        name=name,
        trace_id=trace_id or new_trace_id(),
        span_id=span_id or "",
        parent_id=parent_id,
        start_ns=start_ns,
        end_ns=end_ns,
        # wall anchor reconstructed from "how long ago did it start"
        wall_ns=time.time_ns() - (now_ns - start_ns),
        attrs=attrs,
    )
    _record(sp)
    return sp


def recent_spans(
    name: Optional[str] = None, trace_id: Optional[str] = None
) -> list:
    """Most-recent finished spans (the process SpanBuffer), filtered."""
    return BUFFER.snapshot(name=name, trace_id=trace_id)


def clear_recent_spans() -> None:
    BUFFER.clear()
