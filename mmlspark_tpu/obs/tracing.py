"""Span tracing with trace-id propagation and device-trace nesting.

A :class:`Span` is a named host-side interval tied to a trace id. The
gateway mints a trace id per ingress request and stamps it into the
forwarded request's :data:`TRACE_HEADER`; the worker reads the header and
records its own spans under the same id — one logical request is one
trace across processes, with zero infrastructure (ids ride the existing
HTTP hop).

Spans land in two places:

- the default metrics registry, as the ``mmlspark_trace_span_seconds``
  histogram labeled by span name — so every span family gets a latency
  distribution for free on ``/metrics``;
- ``jax.profiler.TraceAnnotation`` (lazily imported, optional) — inside a
  ``jax.profiler.trace`` capture the host span nests into the device
  timeline, which is how "queue wait vs. TPU dispatch" becomes visible in
  one Perfetto view.

A bounded ring of recently finished spans (:func:`recent_spans`) supports
tests and ad-hoc debugging; it is NOT an export pipeline.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Optional

from mmlspark_tpu.obs.registry import REGISTRY, histogram

# the one header the gateway stamps and workers read (lowercased: the
# WorkerServer parser lowercases header names on ingress)
TRACE_HEADER = "x-mmlspark-trace-id"

_SPAN_SECONDS = histogram(
    "mmlspark_trace_span_seconds",
    "Duration of host-side trace spans, by span name",
    labels=("span",),
)

_RECENT_CAP = 512
_recent: deque = deque(maxlen=_RECENT_CAP)
_recent_lock = threading.Lock()
_tls = threading.local()

# span-name -> pre-resolved histogram child: labels() validates label
# sets per call, far too slow for per-request span recording
_span_children: dict = {}


def _span_child(name: str) -> Any:
    ch = _span_children.get(name)
    if ch is None:
        ch = _span_children[name] = _SPAN_SECONDS.labels(span=name)
    return ch

# jax.profiler.TraceAnnotation, resolved lazily once: None = not yet
# tried, False = unavailable (obs stays importable without jax)
_TA: Any = None


def _trace_annotation() -> Any:
    global _TA
    if _TA is None:
        try:
            from jax.profiler import TraceAnnotation

            _TA = TraceAnnotation
        except Exception:  # noqa: BLE001 — jax absent or too old
            _TA = False
    return _TA


# id generation: uniqueness, not cryptography. uuid4 reads the OS entropy
# pool per call (~14 µs in sandboxed containers) — far too slow for a
# per-request hot path. pid + process-start nanos make ids unique across
# processes; the C-level counter makes them unique (and thread-safe)
# within one.
_ID_BASE = f"{os.getpid():08x}{time.time_ns() & 0xFFFFFFFFFFFF:012x}"
_ID_SEQ = itertools.count()


def new_trace_id() -> str:
    return f"{_ID_BASE}{next(_ID_SEQ) & 0xFFFFFFFFFFFF:012x}"


def _new_span_id() -> str:
    return f"{next(_ID_SEQ) & 0xFFFFFFFFFFFFFFFF:016x}"


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def current_trace_id() -> Optional[str]:
    """The innermost open span's trace id on this thread, if any."""
    s = _stack()
    return s[-1].trace_id if s else None


class Span:
    """One named interval in a trace. Slotted plain class, not a
    dataclass: spans are created per request on the serving hot path and
    dataclass construction costs ~3x (measured ~1.6 µs vs ~0.5 µs in
    this container)."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start_ns", "end_ns",
        "attrs",
    )

    def __init__(
        self,
        name: str,
        trace_id: str,
        span_id: str = "",
        parent_id: Optional[str] = None,
        start_ns: int = 0,
        end_ns: int = 0,
        attrs: Optional[dict] = None,
    ):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id or _new_span_id()
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.attrs = attrs

    @property
    def duration_ns(self) -> int:
        return self.end_ns - self.start_ns

    @property
    def duration_s(self) -> float:
        return self.duration_ns / 1e9

    def __repr__(self) -> str:
        return (
            f"Span({self.name!r}, trace={self.trace_id}, "
            f"{self.duration_ns} ns)"
        )


def _record(sp: Span) -> None:
    if not REGISTRY._enabled:
        return
    _span_child(sp.name).observe(sp.duration_s)
    with _recent_lock:
        _recent.append(sp)


class _SpanContext:
    """Class-based context manager (not ``@contextmanager``: the
    generator protocol costs ~2 µs per use, and spans wrap every
    dispatched serving batch)."""

    __slots__ = ("_name", "_trace_id", "_attrs", "_sp", "_ann")

    def __init__(self, name: str, trace_id: Optional[str],
                 attrs: Optional[dict]):
        self._name = name
        self._trace_id = trace_id
        self._attrs = attrs

    def __enter__(self) -> Span:
        stack = _stack()
        parent = stack[-1] if stack else None
        sp = Span(
            name=self._name,
            trace_id=self._trace_id
            or (parent.trace_id if parent else new_trace_id()),
            parent_id=parent.span_id if parent else None,
            attrs=self._attrs,
        )
        ta_cls = _trace_annotation()
        self._ann = ta_cls(self._name) if ta_cls else None
        stack.append(sp)
        self._sp = sp
        sp.start_ns = time.perf_counter_ns()
        if self._ann is not None:
            self._ann.__enter__()
        return sp

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        sp = self._sp
        sp.end_ns = time.perf_counter_ns()
        _stack().pop()
        _record(sp)
        return False


def span(
    name: str,
    trace_id: Optional[str] = None,
    attrs: Optional[dict] = None,
) -> _SpanContext:
    """Open a span: ``with span("gateway.forward") as sp: ...``.

    Trace id resolution: explicit argument > enclosing span on this
    thread > freshly minted. The span enters a
    ``jax.profiler.TraceAnnotation`` of the same name (a no-op outside an
    active profiler capture), so host stages show up nested in device
    traces. The span is recorded on BOTH clean and exceptional exit."""
    return _SpanContext(name, trace_id, attrs)


def record_span(
    name: str,
    start_ns: int,
    end_ns: int,
    trace_id: Optional[str] = None,
    attrs: Optional[dict] = None,
) -> Optional[Span]:
    """Retroactively record a span from already-measured timestamps — the
    hot-serving-path form (no context manager overhead; the timestamps
    are perf_counter_ns values the caller already had, e.g. a request's
    ``arrival_ns``). Returns the span, or None when the registry is
    disabled."""
    if not REGISTRY._enabled:
        return None
    sp = Span(
        name=name,
        trace_id=trace_id or new_trace_id(),
        start_ns=start_ns,
        end_ns=end_ns,
        attrs=attrs,
    )
    _record(sp)
    return sp


def recent_spans(
    name: Optional[str] = None, trace_id: Optional[str] = None
) -> list:
    """Most-recent finished spans (bounded ring), optionally filtered."""
    with _recent_lock:
        spans = list(_recent)
    return [
        s for s in spans
        if (name is None or s.name == name)
        and (trace_id is None or s.trace_id == trace_id)
    ]


def clear_recent_spans() -> None:
    with _recent_lock:
        _recent.clear()
