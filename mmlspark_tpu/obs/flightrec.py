"""Always-on flight recorder: a bounded ring of recent request records
that persists itself to disk the moment something goes wrong.

Metrics say *that* the p99 blew up; traces say *where* one request spent
its time — the flight recorder answers "what were the last N requests
through this process when it broke", with zero steady-state cost beyond
one dict + ring append per request. Every serving reply path records a
:func:`record` (trace id, model, status, latency, queue wait, outcome),
and every fired fault-injection point records one too, so a chaos run's
injected failures are in the ring next to the requests they broke.

Auto-dump: a record whose outcome is ``error``/``shed``, whose status is
5xx, or whose latency exceeds ``latency_dump_ms`` triggers a JSON dump of
the whole ring — debounced (``min_dump_interval_s``) and retention-capped
(``max_dumps`` files / ``max_bytes`` total, oldest deleted first), so a
crash-looping fleet can never fill a disk. On-demand dumps ride
``POST /debug/dump`` (served inline by every WorkerServer and the driver
registry) and ``SIGUSR1`` (installed by the fleet CLI roles).

Dump file shape::

    {"process": "...", "reason": "status_5xx", "ts": 1690000000.0,
     "records": [{"ts": ..., "trace_id": ..., "model": ..., "path": ...,
                  "status": 503, "latency_ms": ..., "queue_wait_ms": ...,
                  "deadline_ms": ..., "outcome": "5xx", "detail": ...}]}

Environment knobs: ``MMLSPARK_FLIGHTREC_DIR`` (dump directory, default
``<tmp>/mmlspark_flightrec``), ``MMLSPARK_FLIGHTREC_CAP`` (ring size,
default 1024), ``MMLSPARK_FLIGHTREC_LAT_MS`` (latency dump threshold,
default off).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Optional

from mmlspark_tpu.obs import tracing
from mmlspark_tpu.obs.registry import REGISTRY, counter, gauge

_M_RECORDS = gauge(
    "mmlspark_trace_flight_records_count",
    "Request records currently held in the flight-recorder ring",
)
_M_DUMPS = counter(
    "mmlspark_trace_flight_dumps_total",
    "Flight-recorder dumps written, by trigger reason", labels=("reason",),
)

# outcomes that always trigger an auto-dump (latency is threshold-gated)
_DUMP_OUTCOMES = frozenset(("error", "shed"))


class FlightRecorder:
    """Bounded, thread-safe ring of request records with auto-persist."""

    def __init__(
        self,
        cap: int = 1024,
        dump_dir: Optional[str] = None,
        max_dumps: int = 20,
        max_bytes: int = 16 << 20,
        min_dump_interval_s: float = 30.0,
        latency_dump_ms: Optional[float] = None,
    ):
        self.cap = int(cap)
        self.dump_dir = dump_dir or os.path.join(
            tempfile.gettempdir(), "mmlspark_flightrec"
        )
        self.max_dumps = int(max_dumps)
        self.max_bytes = int(max_bytes)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.latency_dump_ms = latency_dump_ms
        self.enabled = True
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=self.cap)
        self._last_dump = 0.0  # monotonic; 0 = never
        self.dumps_written = 0
        self.dumps_suppressed = 0

    # -- recording (reply-path hot code) --------------------------------------

    def record(
        self,
        outcome: str,
        status: int = 0,
        trace_id: Optional[str] = None,
        model: Optional[str] = None,
        path: Optional[str] = None,
        latency_ms: Optional[float] = None,
        queue_wait_ms: Optional[float] = None,
        deadline_ms: Optional[float] = None,
        detail: Optional[str] = None,
    ) -> None:
        """Append one request record; auto-dump when it smells like an
        incident. Call sites gate on their metrics child's ``_on`` flag,
        so a disabled registry skips the whole call."""
        if not self.enabled:
            return
        rec = {
            "ts": round(time.time(), 3),
            "trace_id": trace_id,
            "model": model,
            "path": path,
            "status": int(status),
            "latency_ms": (
                round(latency_ms, 3) if latency_ms is not None else None
            ),
            "queue_wait_ms": (
                round(queue_wait_ms, 3) if queue_wait_ms is not None else None
            ),
            "deadline_ms": deadline_ms,
            "outcome": outcome,
            "detail": detail,
        }
        with self._lock:
            self._buf.append(rec)
            n = len(self._buf)
        if _M_RECORDS._on:
            _M_RECORDS.set(n)
        reason = self._dump_reason(rec)
        if reason is not None:
            # auto-dumps write on a side thread: the recorder is called
            # from reply/routing threads, and a disk write (retention
            # scan + JSON of the whole ring) must not stall serving —
            # incidents are exactly when those threads are busiest. The
            # debounce inside dump() serializes concurrent triggers.
            threading.Thread(
                target=self.dump, args=(reason,),
                name="flightrec-dump", daemon=True,
            ).start()

    def _dump_reason(self, rec: dict) -> Optional[str]:
        if rec["outcome"] in _DUMP_OUTCOMES:
            return f"outcome_{rec['outcome']}"
        if rec["status"] >= 500:
            return "status_5xx"
        lat = rec.get("latency_ms")
        if (
            self.latency_dump_ms is not None
            and lat is not None
            and lat > self.latency_dump_ms
        ):
            return "latency_threshold"
        return None

    # -- inspection ------------------------------------------------------------

    def snapshot(self, outcome: Optional[str] = None) -> list:
        with self._lock:
            recs = list(self._buf)
        if outcome is not None:
            recs = [r for r in recs if r["outcome"] == outcome]
        return recs

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
        if _M_RECORDS._on:
            _M_RECORDS.set(0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    # -- persistence -----------------------------------------------------------

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Write the ring to ``dump_dir`` as one JSON file; returns the
        path, or None when debounced/empty/failed. Manual dumps
        (``reason="manual"``: the /debug/dump and SIGUSR1 paths) skip the
        debounce — an operator asking twice gets two files."""
        now = time.monotonic()
        with self._lock:
            if reason != "manual" and (
                self._last_dump
                and now - self._last_dump < self.min_dump_interval_s
            ):
                self.dumps_suppressed += 1
                return None
            recs = list(self._buf)
            if not recs:
                return None
            self._last_dump = now
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            self._enforce_retention()
            fname = (
                f"flightrec-{time.strftime('%Y%m%d-%H%M%S')}"
                f"-{os.getpid()}-{self.dumps_written}-{reason}.json"
            )
            final = os.path.join(self.dump_dir, fname)
            tmp = final + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "process": tracing.process_label(),
                        "reason": reason,
                        "ts": round(time.time(), 3),
                        "records": recs,
                    },
                    f,
                )
            os.replace(tmp, final)  # a reader never sees a half dump
        except OSError:
            return None  # a broken disk must not take the reply path down
        self.dumps_written += 1
        if REGISTRY._enabled:
            _M_DUMPS.labels(reason=reason).inc()
        return final

    def _enforce_retention(self) -> None:
        """Delete oldest dumps until under the file-count and byte caps
        (with room for the dump about to be written)."""
        try:
            entries = []
            for f in os.listdir(self.dump_dir):
                if f.startswith("flightrec-") and f.endswith(".json"):
                    p = os.path.join(self.dump_dir, f)
                    st = os.stat(p)
                    entries.append((st.st_mtime, st.st_size, p))
            entries.sort()
            total = sum(e[1] for e in entries)
            while entries and (
                len(entries) >= self.max_dumps or total > self.max_bytes
            ):
                mtime, size, p = entries.pop(0)
                os.remove(p)
                total -= size
        except OSError:
            pass


def _env_float(name: str) -> Optional[float]:
    v = os.environ.get(name)
    try:
        return float(v) if v else None
    except ValueError:
        return None


# the process-wide recorder every serving reply path reports into
FLIGHT = FlightRecorder(
    cap=int(os.environ.get("MMLSPARK_FLIGHTREC_CAP", "1024")),
    dump_dir=os.environ.get("MMLSPARK_FLIGHTREC_DIR"),
    latency_dump_ms=_env_float("MMLSPARK_FLIGHTREC_LAT_MS"),
)


def record(outcome: str, **kw: Any) -> None:
    """Module-level convenience: ``FLIGHT.record(...)``."""
    FLIGHT.record(outcome, **kw)


def install_sigusr1() -> bool:
    """SIGUSR1 -> dump the flight recorder (fleet CLI roles call this;
    signal handlers only install from the main thread). Returns whether
    the handler was installed."""
    import signal

    def on_sig(signum: int, frame: Any) -> None:
        path = FLIGHT.dump("sigusr1")
        print(f"flightrec: dumped to {path}", flush=True)

    try:
        signal.signal(signal.SIGUSR1, on_sig)
        return True
    except (ValueError, OSError):  # non-main thread / unsupported platform
        return False
