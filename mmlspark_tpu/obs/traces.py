"""Trace collector: scrape per-process span buffers, join by trace id,
render a cross-process tree.

Dapper-shape assembly with zero pipeline infrastructure: every
instrumented server keeps its own bounded :class:`~.tracing.SpanBuffer`
and serves it on ``GET /traces`` (``GET /traces/<id>`` for one trace);
the collector fans a scrape across the fleet (gateway + registry roster
+ explicit workers), deduplicates spans by span id (co-located roles
share one process buffer), and stitches parent/child edges — real edges:
the gateway stamps its forward span's id into
:data:`~.tracing.PARENT_HEADER`, so worker spans name their upstream
parent instead of being glued on heuristics.

``fleet trace <id>`` renders one request's tree with per-hop timings;
``fleet traces --slowest N`` starts from the latency histograms'
**exemplars** (each bucket remembers the trace id of its last
observation) and jumps straight from the p99 bucket to real traces.

A worker that predates the ``/traces`` endpoint answers 404; the
collector skips it (the rest of the fleet still assembles) — rolling
upgrades must not break the debugging tool they most need.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional, Tuple

from mmlspark_tpu.obs.tracing import Span

# exemplar sources consulted for --slowest, most-informative first: the
# gateway's end-to-end latency sees every hop, the worker's only its own
SLOWEST_METRICS = (
    "mmlspark_gateway_request_latency_seconds",
    "mmlspark_serving_request_latency_seconds",
    "mmlspark_modelstore_dispatch_latency_seconds",
)


def fetch_traces(
    url: str, trace_id: Optional[str] = None, timeout: float = 5.0
) -> Optional[dict]:
    """GET one endpoint's ``/traces[/<id>]`` -> parsed payload, or None
    when unreachable or the endpoint doesn't serve traces (404 from a
    pre-trace worker: skip, don't crash)."""
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    base = url.rstrip("/")
    if not base.endswith("/traces"):
        base = base + "/traces"
    if trace_id:
        base = f"{base}/{trace_id}"
    try:
        resp = send_request(HTTPRequestData(base, "GET"), timeout=timeout)
    except Exception:  # noqa: BLE001 — a dead worker is a skip, not a crash
        return None
    if resp["status_code"] != 200:
        return None
    body = resp["entity"]
    if isinstance(body, bytes):
        body = body.decode("utf-8", "replace")
    try:
        payload = json.loads(body)
    except ValueError:
        return None
    return payload if isinstance(payload, dict) else None


def collect(
    endpoints: Iterable[str],
    trace_id: Optional[str] = None,
    timeout: float = 5.0,
) -> Tuple[List[Span], dict, List[str]]:
    """Scrape every endpoint's buffer and join.

    Returns ``(spans, exemplars, scraped)``: spans deduplicated by span
    id (an in-process gateway+worker pair shares one buffer and would
    otherwise double every span), exemplars merged per histogram name,
    and the endpoints that actually answered."""
    spans: dict = {}
    exemplars: dict = {}
    scraped: List[str] = []
    for url in endpoints:
        payload = fetch_traces(url, trace_id=trace_id, timeout=timeout)
        if payload is None:
            continue
        scraped.append(url)
        for d in payload.get("spans", ()):
            if not isinstance(d, dict) or not d.get("span_id"):
                continue
            spans.setdefault(d["span_id"], Span.from_dict(d))
        for name, samples in (payload.get("exemplars") or {}).items():
            exemplars.setdefault(name, []).extend(samples)
    out = sorted(spans.values(), key=lambda s: (s.wall_ns, s.span_id))
    return out, exemplars, scraped


def slowest_traces(
    exemplars: dict,
    n: int = 5,
    metrics: Iterable[str] = SLOWEST_METRICS,
) -> List[Tuple[float, str]]:
    """Distinct trace ids with the highest exemplar latencies, worst
    first — the p99-bucket -> real-trace jump. Uses the first metric in
    ``metrics`` that has exemplars (gateway view preferred: it times the
    whole hop chain)."""
    for name in metrics:
        samples = exemplars.get(name) or ()
        best: dict = {}
        for s in samples:
            tid = s.get("trace_id")
            if not tid:
                continue
            v = float(s.get("value") or 0.0)
            if v > best.get(tid, -1.0):
                best[tid] = v
        if best:
            ranked = sorted(
                ((v, tid) for tid, v in best.items()), reverse=True
            )
            return ranked[:n]
    return []


class _Node:
    __slots__ = ("span", "children")

    def __init__(self, span: Span):
        self.span = span
        self.children: list = []


def assemble(spans: List[Span]) -> List[_Node]:
    """Parent/child forest for ONE trace's spans. Spans whose parent was
    not collected (evicted from a ring, or a process that was never
    scraped) surface as roots — a partial tree beats no tree."""
    nodes = {s.span_id: _Node(s) for s in spans}
    roots: list = []
    for s in spans:
        node = nodes[s.span_id]
        parent = nodes.get(s.parent_id) if s.parent_id else None
        if parent is not None and parent is not node:
            parent.children.append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node.children.sort(key=lambda c: (c.span.wall_ns, c.span.span_id))
    roots.sort(key=lambda r: (r.span.wall_ns, r.span.span_id))
    return roots


def _span_line(sp: Span) -> str:
    attrs = ""
    if sp.attrs:
        attrs = " " + " ".join(
            f"{k}={v}" for k, v in sorted(sp.attrs.items())
        )
    return (
        f"{sp.name} {sp.duration_ns / 1e6:.2f} ms "
        f"[{sp.process or '?'}]{attrs}"
    )


def render_tree(spans: List[Span], trace_id: str) -> str:
    """ASCII tree with per-hop durations, the ``fleet trace <id>`` view."""
    if not spans:
        return f"trace {trace_id}: no spans found (buffers are bounded " \
               "rings — old traces age out)"
    procs = {sp.process for sp in spans if sp.process}
    total_ms = max(sp.duration_ns for sp in spans) / 1e6
    lines = [
        f"trace {trace_id} — {len(spans)} span(s), "
        f"{len(procs)} process(es), {total_ms:.2f} ms"
    ]

    def walk(node: _Node, prefix: str, last: bool) -> None:
        branch = "└─ " if last else "├─ "
        lines.append(prefix + branch + _span_line(node.span))
        child_prefix = prefix + ("   " if last else "│  ")
        for i, c in enumerate(node.children):
            walk(c, child_prefix, i == len(node.children) - 1)

    roots = assemble(spans)
    for i, r in enumerate(roots):
        walk(r, "", i == len(roots) - 1)
    return "\n".join(lines)


def span_names(spans: List[Span]) -> set:
    return {s.name for s in spans}


def has_gateway_and_worker_hop(spans: List[Span]) -> bool:
    """The smoke/e2e gate: one assembled trace crosses the gateway AND a
    worker (either dispatcher flavor)."""
    names = span_names(spans)
    gateway = {"gateway.request", "gateway.forward"}
    worker = {"serving.request", "serving.dispatch", "serving.queue",
              "modelstore.dispatch"}
    return bool(names & gateway) and bool(names & worker)
