"""ServingUDFs analogues (ServingUDFs.scala:16-50): turn typed data into
HTTP reply payloads and request rows into typed data."""

from __future__ import annotations

import json
from typing import Any, Optional, Union

import numpy as np

from mmlspark_tpu.serving.server import CachedRequest


def make_reply(data: Any, code: int = 200) -> tuple:
    """Typed value -> (status, body, headers) reply triple (makeReplyUDF)."""
    if isinstance(data, (bytes, bytearray)):
        return code, bytes(data), {"Content-Type": "application/octet-stream"}
    if isinstance(data, str):
        return code, data.encode("utf-8"), {"Content-Type": "text/plain"}
    if isinstance(data, np.ndarray):
        data = data.tolist()
    if isinstance(data, np.generic):
        data = data.item()
    return code, json.dumps(data).encode("utf-8"), {"Content-Type": "application/json"}


def request_to_text(req: CachedRequest) -> str:
    return req.body.decode("utf-8", "replace")


def request_to_json(req: CachedRequest) -> Any:
    """parseRequest analogue for JSON bodies; None on empty/invalid."""
    if not req.body:
        return None
    try:
        return json.loads(req.body)
    except json.JSONDecodeError:
        return None
