"""Adaptive concurrency control: AIMD limit on in-flight serving work.

The overload failure mode this exists for: a worker whose queue grows
past its deadline serves *every* request late — goodput collapses to
zero while the server stays "busy". The fix (the Overload-control /
adaptive-concurrency lineage: TCP congestion control applied to RPC
admission) is to bound in-flight work and shed the excess **at ingress**
with a fast 429 + ``Retry-After``, so the requests that are admitted
still meet their deadlines.

:class:`AdmissionController` is shared by
:class:`~mmlspark_tpu.serving.query.ServingQuery` and the modelstore's
:class:`~mmlspark_tpu.serving.modelstore.ModelDispatcher`: the
:class:`~mmlspark_tpu.serving.server.WorkerServer` ingress consults
``try_acquire()`` before enqueuing a request (the shed path costs
microseconds on the asyncio thread) and releases on reply; the dispatch
loops feed ``observe()`` with the queue-wait + service-time samples the
limit adapts on.

The control law is AIMD fed by the queue-wait signal (the same samples
the ``mmlspark_serving_queue_wait_seconds`` histogram records):

- queue wait in the last window above ``wait_factor x`` the service-time
  EWMA (queueing is building faster than the handler drains it) —
  multiplicative decrease, ``limit *= decrease``;
- window healthy — additive increase, ``limit += 1``;
- the limit is clamped to ``[min_limit, max_limit]`` and in-flight work
  above it is shed 429 before it ever queues.

Fault point ``admission.shed`` fires on every admission decision: a
truthy payload forces a shed (chaos-testing the client's 429 handling),
``delay_s`` stalls ingress (a latency fault on the admission path).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from mmlspark_tpu import obs

# canonical request-budget headers (the modelstore dispatcher re-exports
# DEADLINE_HEADER for back-compat; the gateway decrements it per hop)
DEADLINE_HEADER = "x-mmlspark-deadline-ms"
RETRY_BUDGET_HEADER = "x-mmlspark-retry-budget"
SHED_HEADER = "x-mmlspark-shed"

_M_LIMIT = obs.gauge(
    "mmlspark_admission_limit_requests",
    "Current adaptive in-flight limit (AIMD)", labels=("server",),
)
_M_INFLIGHT = obs.gauge(
    "mmlspark_admission_inflight_requests",
    "Requests currently admitted and not yet replied", labels=("server",),
)
_M_SHED = obs.counter(
    "mmlspark_admission_shed_total",
    "Requests shed 429 at ingress by the concurrency limit",
    labels=("server",),
)
_M_DECREASES = obs.counter(
    "mmlspark_admission_limit_decreases_total",
    "Multiplicative-decrease events (overload signals)", labels=("server",),
)


def deadline_ms_from(headers: dict, default: Optional[float] = None,
                     ) -> Optional[float]:
    """Parse ``x-mmlspark-deadline-ms`` out of a header dict; a missing
    or malformed value falls back to ``default`` (None = no deadline)."""
    raw = headers.get(DEADLINE_HEADER)
    if raw is None:
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


class AdmissionController:
    """AIMD limit on in-flight requests for ONE serving worker.

    ``wait_factor``: the overload threshold — a window whose worst queue
    wait exceeds ``wait_factor * svc_ewma`` (but at least
    ``min_target_s``) triggers a multiplicative decrease. The service
    EWMA comes from the same ``observe()`` calls, so the target scales
    with the model actually being served instead of hard-coding a
    millisecond budget that is absurd for one model and lax for another.
    """

    def __init__(
        self,
        server: str = "serving",
        initial_limit: int = 32,
        min_limit: int = 2,
        max_limit: int = 4096,
        decrease: float = 0.7,
        wait_factor: float = 1.5,
        min_target_s: float = 0.002,
        window_samples: int = 16,
        window_s: float = 0.25,
        retry_after_s: float = 1.0,
    ):
        self.server = server
        self.min_limit = max(1, int(min_limit))
        self.max_limit = int(max_limit)
        self.decrease = decrease
        self.wait_factor = wait_factor
        self.min_target_s = min_target_s
        self.window_samples = max(1, int(window_samples))
        self.window_s = window_s
        self.retry_after_s = retry_after_s
        self._lock = threading.Lock()
        self._limit = float(min(max(initial_limit, self.min_limit),
                                self.max_limit))
        self._inflight = 0
        self.shed = 0
        # adjustment window state (guarded by the lock)
        self._svc_ewma_s = 0.0
        self._win_worst_wait_s = 0.0
        self._win_n = 0
        self._win_started = time.monotonic()
        self._m_limit = _M_LIMIT.labels(server=server)
        self._m_inflight = _M_INFLIGHT.labels(server=server)
        self._m_shed = _M_SHED.labels(server=server)
        self._m_decreases = _M_DECREASES.labels(server=server)
        self._m_limit.set(int(self._limit))
        self._m_inflight.set(0)

    # -- admission (ingress thread) ------------------------------------------

    @property
    def limit(self) -> int:
        return int(self._limit)

    @property
    def inflight(self) -> int:
        return self._inflight

    def try_acquire(self) -> bool:
        """One admission slot, or False (the caller sheds 429). The
        ingress calls this once per would-be-queued request."""
        with self._lock:
            if self._inflight >= int(self._limit):
                self.shed += 1
                self._m_shed.inc()
                return False
            self._inflight += 1
            self._m_inflight.set(self._inflight)
            return True

    def release(self) -> None:
        """The admitted request was replied (any status) — free its slot."""
        with self._lock:
            if self._inflight > 0:
                self._inflight -= 1
                self._m_inflight.set(self._inflight)

    def force_shed(self) -> None:
        """Count a shed forced from outside the limit check (the
        ``admission.shed`` fault point) with the same locked accounting
        as a real limit shed — counter and metric stay in step."""
        with self._lock:
            self.shed += 1
            self._m_shed.inc()

    # -- the control law (dispatcher threads) --------------------------------

    def observe(self, queue_wait_s: float, service_s: float) -> None:
        """Feed one dispatched request's queue wait + per-request service
        time into the AIMD window; adjusts the limit when the window
        closes (``window_samples`` samples or ``window_s`` elapsed)."""
        now = time.monotonic()
        with self._lock:
            a = 0.2
            self._svc_ewma_s = (
                service_s if self._svc_ewma_s <= 0.0
                else (1 - a) * self._svc_ewma_s + a * service_s
            )
            if queue_wait_s > self._win_worst_wait_s:
                self._win_worst_wait_s = queue_wait_s
            self._win_n += 1
            if (
                self._win_n < self.window_samples
                and now - self._win_started < self.window_s
            ):
                return
            target_s = max(
                self.min_target_s, self.wait_factor * self._svc_ewma_s
            )
            if self._win_worst_wait_s > target_s:
                self._limit = max(
                    float(self.min_limit), self._limit * self.decrease
                )
                self._m_decreases.inc()
            else:
                self._limit = min(float(self.max_limit), self._limit + 1.0)
            self._m_limit.set(int(self._limit))
            self._win_worst_wait_s = 0.0
            self._win_n = 0
            self._win_started = now

    # -- the shed reply ------------------------------------------------------

    def shed_headers(self) -> dict:
        return {
            "Retry-After": str(max(1, int(round(self.retry_after_s)))),
            SHED_HEADER: "admission",
            "Content-Type": "application/json",
        }
