"""Serving-fleet entrypoint: registry, worker, and gateway roles as one CLI.

The deployment story for the serving layer (the reference ships docker +
helm recipes under tools/docker and tools/helm that bring up a Spark
master/worker/zeppelin fleet; here the unit is registry + model workers +
gateway). Each role is one process:

    python -m mmlspark_tpu.serving.fleet registry --port 9090
    python -m mmlspark_tpu.serving.fleet worker \
        --registry http://registry:9090/ --model zoo:ResNet8_Digits
    python -m mmlspark_tpu.serving.fleet gateway \
        --registry http://registry:9090/ --port 8080

Workers register with the driver registry on start and heartbeat by
re-registering; the gateway discovers them by polling the registry
(serving/distributed.py), so workers can join/leave/restart without
touching the gateway — the reference's DistributedHTTPSource re-discovery
semantics. ``tools/deploy/`` packages these roles as docker-compose and
k8s manifests with a smoke script.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time
from typing import Any, Callable, Optional

import numpy as np


def make_model_handler(model_spec: str) -> Callable:
    """Model spec -> batch handler for :class:`ServingQuery`.

    - ``echo``           — replies with the parsed request body (smoke tests)
    - ``zoo:<name>``     — ImageFeaturizer on the named zoo backbone; body
      ``{"image": [[...]]}`` (H, W, C) uint8 -> ``{"features": [...]}``
    - ``module:pkg.fn``  — import ``pkg.fn``; it must return a handler
    """
    if model_spec == "echo":

        def handler(reqs: list) -> dict:
            out = {}
            for r in reqs:
                try:
                    body = json.loads(r.body) if r.body else {}
                    out[r.id] = (200, json.dumps({"echo": body}).encode(), {})
                except ValueError as e:
                    out[r.id] = (400, json.dumps({"error": str(e)}).encode(), {})
            return out

        return handler
    if model_spec.startswith("module:"):
        import importlib

        mod_name, _, fn_name = model_spec[len("module:"):].rpartition(".")
        return getattr(importlib.import_module(mod_name), fn_name)()
    if model_spec.startswith("zoo:"):
        from mmlspark_tpu.models import ImageFeaturizer

        feat = ImageFeaturizer(
            input_col="image", output_col="features",
            model_name=model_spec[len("zoo:"):],
        )
        inner = feat._build()

        def handler(reqs: list) -> dict:
            out = {}
            imgs, ids = [], []
            for r in reqs:
                try:
                    imgs.append(
                        np.asarray(json.loads(r.body)["image"], np.uint8)
                    )
                    ids.append(r.id)
                except (ValueError, KeyError) as e:
                    out[r.id] = (400, json.dumps({"error": str(e)}).encode(), {})
            if imgs:
                feats = inner.apply_batch(np.stack(imgs))
                for rid, f in zip(ids, feats):
                    out[rid] = (
                        200,
                        json.dumps({"features": np.asarray(f).tolist()}).encode(),
                        {},
                    )
            return out

        return handler
    raise ValueError(f"unknown model spec {model_spec!r}")


def run_registry(
    host: str = "0.0.0.0", port: int = 9090, ttl_s: Optional[float] = None
) -> Any:
    from mmlspark_tpu.serving.registry import DriverRegistry

    reg = DriverRegistry(host=host, port=port, ttl_s=ttl_s)
    print(f"registry: {reg.url}", flush=True)
    return reg


class _WorkerStopper:
    """Shutdown handle for a fleet worker: stops the heartbeat AND
    deregisters from the registry, so a clean SIGTERM removes the roster
    entry immediately instead of leaving it stale until TTL expiry or
    gateway-failure eviction. Keeps the Event surface (``set``/``is_set``/
    ``wait``) callers and tests already use."""

    def __init__(self, ev: threading.Event, registry_url: str, info: Any):
        self._ev = ev
        self._registry_url = registry_url
        self._info = info
        self._beat: Optional[threading.Thread] = None

    def set(self) -> None:
        from mmlspark_tpu.serving.registry import DriverRegistry

        if self._ev.is_set():
            return
        self._ev.set()
        if self._beat is not None:
            # no heartbeat may land AFTER the goodbye, or the entry would
            # resurrect until the next expiry — so outwait even a register
            # POST stuck at its full 10 s send_request timeout
            self._beat.join(12.0)
        try:
            DriverRegistry.deregister(self._registry_url, self._info)
        except Exception as e:  # noqa: BLE001 — registry may already be gone
            print(f"worker: deregister failed: {e}", file=sys.stderr, flush=True)

    stop = set

    def is_set(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._ev.wait(timeout)


def run_worker(
    registry_url: str,
    model: str = "echo",
    host: str = "0.0.0.0",
    port: int = 0,
    service_name: str = "serving",
    heartbeat_s: float = 5.0,
    advertise_host: Optional[str] = None,
) -> tuple:
    """Start a worker, register it, and re-register on a heartbeat thread
    (a restarted registry re-learns live workers within one beat). The
    returned stopper deregisters on shutdown (clean-SIGTERM path)."""
    from mmlspark_tpu.serving.query import ServingQuery
    from mmlspark_tpu.serving.registry import DriverRegistry
    from mmlspark_tpu.serving.server import WorkerServer

    srv = WorkerServer(host=host, port=port, name=service_name)
    info = srv.start()
    if advertise_host:
        # the registry roster must carry an address OTHER containers can
        # reach, not the 0.0.0.0 bind address
        import dataclasses

        info = dataclasses.replace(info, host=advertise_host)
    q = ServingQuery(srv, make_model_handler(model)).start()
    stop = threading.Event()
    stopper = _WorkerStopper(stop, registry_url, info)

    def beat() -> None:
        while not stop.is_set():
            try:
                # checked INSIDE the try so a shutdown signaled between the
                # loop test and the POST still skips the re-register
                if not stop.is_set():
                    DriverRegistry.register(registry_url, info)
            except Exception as e:  # noqa: BLE001 — registry may be restarting
                print(f"worker: register failed: {e}", file=sys.stderr, flush=True)
            stop.wait(heartbeat_s)

    stopper._beat = threading.Thread(target=beat, name="worker-heartbeat", daemon=True)
    stopper._beat.start()
    print(f"worker: {info.host}:{info.port} model={model}", flush=True)
    return srv, q, stopper


def run_gateway(
    registry_url: str,
    host: str = "0.0.0.0",
    port: int = 8080,
    service_name: str = "serving",
) -> Any:
    from mmlspark_tpu.serving.distributed import ServingGateway

    gw = ServingGateway(
        registry_url=registry_url, service_name=service_name,
        host=host, port=port,
    )
    ginfo = gw.start()
    print(f"gateway: http://{ginfo.host}:{ginfo.port}/", flush=True)
    return gw


def _serve_forever(stoppables: list, drain_s: float = 0.0) -> None:
    ev = threading.Event()

    def on_sig(signum: int, frame: Any) -> None:
        ev.set()

    signal.signal(signal.SIGTERM, on_sig)
    signal.signal(signal.SIGINT, on_sig)
    ev.wait()
    for s in stoppables:
        try:
            if drain_s > 0 and hasattr(s, "drain"):
                # gateway roll: 503 /health, finish accepted requests, stop
                s.drain(timeout_s=drain_s)
            elif hasattr(s, "stop"):
                s.stop()
            else:
                s.set()
        except Exception:  # noqa: BLE001
            pass


def main(argv: Optional[list] = None) -> None:
    ap = argparse.ArgumentParser(prog="mmlspark_tpu.serving.fleet")
    ap.add_argument(
        "--fault-plan", default=None,
        help="JSON fault plan (inline or a file path) armed for this "
        "process — chaos-smokes a docker-compose fleet (core/faults.py)",
    )
    sub = ap.add_subparsers(dest="role", required=True)
    r = sub.add_parser("registry")
    r.add_argument("--host", default="0.0.0.0")
    r.add_argument("--port", type=int, default=9090)
    r.add_argument(
        "--ttl-s", type=float, default=None,
        help="drop roster entries not re-registered within this many "
        "seconds (a few worker heartbeat periods)",
    )
    w = sub.add_parser("worker")
    w.add_argument("--registry", required=True)
    w.add_argument("--model", default="echo")
    w.add_argument("--host", default="0.0.0.0")
    w.add_argument("--port", type=int, default=0)
    w.add_argument("--service-name", default="serving")
    w.add_argument("--heartbeat-s", type=float, default=5.0)
    w.add_argument(
        "--advertise-host", default=None,
        help="hostname other containers reach this worker by (compose/k8s)",
    )
    g = sub.add_parser("gateway")
    g.add_argument("--registry", required=True)
    g.add_argument("--host", default="0.0.0.0")
    g.add_argument("--port", type=int, default=8080)
    g.add_argument("--service-name", default="serving")
    g.add_argument(
        "--drain-s", type=float, default=10.0,
        help="on SIGTERM: finish accepted requests for up to this long "
        "(0 = stop immediately)",
    )
    args = ap.parse_args(argv)
    if args.fault_plan:
        from mmlspark_tpu.core.faults import FaultPlan

        FaultPlan.from_spec(args.fault_plan).install()
        print(f"fleet: fault plan armed ({args.fault_plan})", flush=True)
    if args.role == "registry":
        reg = run_registry(args.host, args.port, args.ttl_s)
        _serve_forever([reg])
    elif args.role == "worker":
        srv, q, stop = run_worker(
            args.registry, args.model, args.host, args.port,
            args.service_name, args.heartbeat_s, args.advertise_host,
        )
        _serve_forever([stop, q, srv])
    else:
        gw = run_gateway(args.registry, args.host, args.port, args.service_name)
        _serve_forever([gw], drain_s=args.drain_s)


if __name__ == "__main__":
    main()
