"""Serving-fleet entrypoint: registry, worker, and gateway roles as one CLI.

The deployment story for the serving layer (the reference ships docker +
helm recipes under tools/docker and tools/helm that bring up a Spark
master/worker/zeppelin fleet; here the unit is registry + model workers +
gateway). Each role is one process:

    python -m mmlspark_tpu.serving.fleet registry --port 9090
    python -m mmlspark_tpu.serving.fleet worker \
        --registry http://registry:9090/ --model zoo:ResNet8_Digits
    python -m mmlspark_tpu.serving.fleet gateway \
        --registry http://registry:9090/ --port 8080
    python -m mmlspark_tpu.serving.fleet supervise \
        --registry http://registry:9090/ --worker "--model echo --port 9101"

Workers register with the driver registry on start and heartbeat by
re-registering; the gateway discovers them by polling the registry
(serving/distributed.py), so workers can join/leave/restart without
touching the gateway — the reference's DistributedHTTPSource re-discovery
semantics. ``tools/deploy/`` packages these roles as docker-compose and
k8s manifests with a smoke script.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time
from typing import Any, Callable, Optional


def make_model_handler(model_spec: str) -> Callable:
    """Model spec -> batch handler for :class:`ServingQuery`.

    - ``echo``           — replies with the parsed request body (smoke tests)
    - ``zoo:<name>``     — ImageFeaturizer on the named zoo backbone; body
      ``{"image": [[...]]}`` (H, W, C) uint8 -> ``{"features": [...]}``
    - ``module:pkg.fn``  — import ``pkg.fn``; it may return a handler or a
      :class:`~mmlspark_tpu.serving.modelstore.LoadedModel`

    The spec grammar lives in serving/modelstore/loaders.py (the fleet
    workers' ModelStore path, which adds byte accounting, warmup and
    eviction hooks); this is the bare-handler view of the same resolver
    for embedding a single model in a :class:`ServingQuery`."""
    from mmlspark_tpu.serving.modelstore import build_loaded_model

    return build_loaded_model(model_spec).handler


def run_registry(
    host: str = "0.0.0.0", port: int = 9090, ttl_s: Optional[float] = None,
    peers: Optional[list] = None, reconcile_s: float = 5.0,
) -> Any:
    from mmlspark_tpu import obs
    from mmlspark_tpu.serving.registry import DriverRegistry

    reg = DriverRegistry(
        host=host, port=port, ttl_s=ttl_s, peers=peers,
        reconcile_s=reconcile_s,
    )
    obs.set_process_label(f"registry@{reg.host}:{reg.port}")
    print(f"registry: {reg.url}", flush=True)
    return reg


def split_registry_urls(registry_url: Any) -> list:
    """Registry HA: one URL, a comma-separated string, or a sequence ->
    the list of registries a role talks to (workers heartbeat to ALL,
    the gateway fails roster refreshes over to the next live one)."""
    if not registry_url:
        return []
    if isinstance(registry_url, str):
        return [u.strip() for u in registry_url.split(",") if u.strip()]
    return list(registry_url)


def beat_timeout(heartbeat_s: float, factor: float = 1.0) -> float:
    """Socket timeout for one registry heartbeat/deregister call: short
    and explicit — a blackholed registry (asymmetric partition, chaos
    proxy) must cost a bounded slice of the beat period, never the
    transport default. ONE clamp for every role's beat policy."""
    return max(1.0, min(3.0, factor * float(heartbeat_s)))


class _WorkerStopper:
    """Shutdown handle for a fleet worker: stops the heartbeat AND
    deregisters from every registry, so a clean SIGTERM removes the
    roster entries immediately instead of leaving them stale until TTL
    expiry or gateway-failure eviction. Keeps the Event surface (``set``/
    ``is_set``/``wait``) callers and tests already use.

    Every registry HTTP call carries an explicit SHORT socket timeout
    (``beat_timeout_s``): a blackholed registry (asymmetric partition,
    chaos proxy) costs one bounded beat, never parks the heartbeat
    thread — and can never hang a clean SIGTERM shutdown (the TTL covers
    a goodbye the registry never heard)."""

    def __init__(self, ev: threading.Event, registry_url: str, info: Any,
                 beat_timeout_s: float = 3.0):
        self._ev = ev
        self._registry_urls = split_registry_urls(registry_url)
        self._info = info
        self._beat: Optional[threading.Thread] = None
        self.beat_timeout_s = float(beat_timeout_s)
        self.slo_engine: Any = None
        # the serving pieces a graceful drain sequences (run_worker sets
        # them); None leaves drain() equivalent to set()
        self._srv: Any = None

    def set(self) -> None:
        from mmlspark_tpu.serving.registry import DriverRegistry

        if self._ev.is_set():
            return
        self._ev.set()
        if self.slo_engine is not None:
            self.slo_engine.stop()
        if self._beat is not None:
            # no heartbeat may land AFTER the goodbye, or the entry would
            # resurrect until the next expiry — outwait a beat stuck at
            # its full (short, explicit) timeout against every registry
            self._beat.join(
                2.0 + self.beat_timeout_s * max(1, len(self._registry_urls))
            )
        for url in self._registry_urls:
            try:
                DriverRegistry.deregister(
                    url, self._info, timeout=self.beat_timeout_s
                )
            except Exception as e:  # noqa: BLE001 — registry may already be gone
                print(
                    f"worker: deregister from {url} failed: {e}",
                    file=sys.stderr, flush=True,
                )

    stop = set

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Graceful-drain lifecycle for a fleet roll (SIGTERM path):
        deregister (gateways stop routing within one roster refresh) ->
        stop accepting new connections -> wait until every accepted
        request AND staged continuous batch has been replied to. The
        caller then stops the dispatcher and ingress as usual — with
        zero dropped requests (pinned by the rolling-restart drill)."""
        self.set()
        # the goodbye above is separately bounded (every registry call
        # carries beat_timeout_s); the drain budget starts AFTER it, or
        # a blackholed registry would eat the whole timeout and starve
        # the in-flight wait down to its 0.5 s floor — dropping exactly
        # the requests the drain exists to protect
        t0 = time.monotonic()
        if self._srv is None:
            return True
        # the deregistration must propagate: gateways refresh their
        # roster every ~1 s and prune pooled connections on the refresh
        time.sleep(min(2.0, timeout_s / 3))
        self._srv.pause_accepting()
        remaining = timeout_s - (time.monotonic() - t0)
        drained = self._srv.drain_inflight(max(0.5, remaining))
        if not drained:
            print(
                "worker: drain timed out with requests still in flight",
                file=sys.stderr, flush=True,
            )
        return drained

    def is_set(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._ev.wait(timeout)


def _start_slo_engine(
    service_name: str,
    targets_spec: Optional[str],
    availability: float,
    p99_ms: Optional[float],
    interval_s: float,
    gateway: bool = False,
) -> Any:
    """Start the in-process SLO engine a fleet role exports burn-rate
    gauges from (``--slo-targets`` JSON overrides the role default)."""
    from mmlspark_tpu.obs import slo

    targets = (
        slo.load_targets(targets_spec) if targets_spec
        else slo.default_targets(
            service_name, availability=availability, p99_ms=p99_ms,
            gateway=gateway,
        )
    )
    return slo.SLOEngine(targets, interval_s=interval_s).start()


def run_worker(
    registry_url: str,
    model: str = "echo",
    host: str = "0.0.0.0",
    port: int = 0,
    service_name: str = "serving",
    heartbeat_s: float = 5.0,
    advertise_host: Optional[str] = None,
    extra_models: Optional[list] = None,
    hbm_budget_bytes: Optional[int] = None,
    default_deadline_ms: Optional[float] = None,
    slo_targets: Optional[str] = None,
    slo_availability: float = 0.999,
    slo_p99_ms: Optional[float] = 250.0,
    slo_interval_s: float = 15.0,
    admission: bool = True,
    admission_initial_limit: int = 32,
    admission_min_target_ms: Optional[float] = None,
    artifact_dir: Optional[str] = None,
    reactors: int = 2,
    header_deadline_s: Optional[float] = 15.0,
) -> tuple:
    """Start a ModelStore-backed worker, register it, and re-register on a
    heartbeat thread (a restarted registry re-learns live workers within
    one beat). The returned stopper deregisters on shutdown (clean-SIGTERM
    path).

    Cold-start ordering (the routable-before-jitted fix): the default
    model is loaded AND warmed — its dummy bucket batch compiled — before
    the worker registers, so the gateway never routes to a worker whose
    first request would pay a compile; ``GET /health`` reports readiness
    for probes that want to see it. ``extra_models``: additional
    ``name=spec`` entries loaded (also pre-registration) for multi-model
    serving; all names are advertised on the roster for model-aware
    gateway routing.

    ``admission`` (default on): attach an adaptive-concurrency
    :class:`~mmlspark_tpu.serving.admission.AdmissionController` — the
    AIMD in-flight limit that sheds 429 + Retry-After at ingress instead
    of queueing past every deadline (docs/robustness.md)."""
    from mmlspark_tpu.serving.modelstore import (
        ModelDispatcher,
        ModelStore,
        model_name_from_spec,
    )
    from mmlspark_tpu.serving.registry import DriverRegistry
    from mmlspark_tpu.serving.server import WorkerServer

    # multi-reactor ingress (serving/server.py): fleet workers default to
    # 2 so one slow client or a multi-MB /artifacts window can't stall
    # request intake; unit-level WorkerServer keeps the single loop
    srv = WorkerServer(
        host=host, port=port, name=service_name, num_reactors=reactors,
        # hostile-client hardening (docs/chaos.md): fleet workers face
        # real networks, so the slowloris deadline defaults tighter
        # than the unit-level WorkerServer's
        header_deadline_s=header_deadline_s,
    )
    info = srv.start()
    from mmlspark_tpu import obs
    from mmlspark_tpu.serving import artifacts as artifacts_mod

    # content-addressed artifact plane (serving/artifacts.py): every
    # worker is both a CONSUMER (``artifact:`` model specs resolve by
    # digest against the registries) and a PEER (fetched blobs re-serve
    # off this ingress and are advertised each heartbeat, so replication
    # fans out instead of hammering the producer)
    if artifact_dir:
        art_store = artifacts_mod.ArtifactStore(artifact_dir)
        artifacts_mod.configure(store=art_store, registry_urls=registry_url)
    else:
        artifacts_mod.configure(registry_urls=registry_url)
        art_store = artifacts_mod.default_store()
    artifacts_mod.attach(srv, art_store)

    # trace-tree hop attribution: spans from this process carry an
    # operator-recognizable label instead of a bare pid
    obs.set_process_label(
        f"{service_name}@{advertise_host or info.host}:{info.port}"
    )
    store = ModelStore(budget_bytes=hbm_budget_bytes)
    specs = [(model_name_from_spec(model), model)] if model else []
    for entry in extra_models or ():
        name, _, spec = entry.partition("=")
        if not spec:
            name, spec = model_name_from_spec(entry), entry
        specs.append((name, spec))
    for name, spec in specs:
        store.load(name, spec, wait=True)  # warm BEFORE registering
    ctrl = None
    if admission:
        # adaptive-concurrency shed at ingress (serving/admission.py):
        # beyond the AIMD in-flight limit, requests get a fast 429 +
        # Retry-After instead of joining a queue past every deadline
        from mmlspark_tpu.serving.admission import AdmissionController

        kwargs = {}
        if admission_min_target_ms is not None:
            # queue-wait floor below which a window never reads as
            # overload: deployments on slow or noisy boxes raise it so
            # scheduler jitter alone cannot collapse the AIMD limit
            kwargs["min_target_s"] = admission_min_target_ms / 1e3
        ctrl = AdmissionController(
            server=service_name, initial_limit=admission_initial_limit,
            **kwargs,
        )
    q = ModelDispatcher(
        srv, store, default_model=specs[0][0] if specs else None,
        default_deadline_ms=default_deadline_ms, admission=ctrl,
    ).start()
    import dataclasses

    if advertise_host:
        # the registry roster must carry an address OTHER containers can
        # reach, not the 0.0.0.0 bind address
        info = dataclasses.replace(info, host=advertise_host)
    info = dataclasses.replace(info, models=tuple(n for n, _ in specs))
    stop = threading.Event()
    beat_timeout_s = beat_timeout(heartbeat_s)
    stopper = _WorkerStopper(
        stop, registry_url, info, beat_timeout_s=beat_timeout_s
    )
    stopper._srv = srv
    stopper.slo_engine = _start_slo_engine(
        service_name, slo_targets, slo_availability, slo_p99_ms,
        slo_interval_s,
    )

    registry_urls = split_registry_urls(registry_url)

    def beat() -> None:
        while not stop.is_set():
            # registry HA: every live registry learns this worker each
            # beat, so the gateway can fail roster refreshes over to any
            # of them; a dead registry is skipped, not fatal
            fresh = dataclasses.replace(
                info, models=tuple(store.model_names()),
                artifacts=tuple(art_store.refs()),
            )
            for url in registry_urls:
                try:
                    # checked INSIDE the try so a shutdown signaled between
                    # the loop test and the POST still skips the re-register
                    if not stop.is_set():
                        # re-advertise the store's CURRENT models each beat:
                        # a model loaded at runtime through the control plane
                        # becomes gateway-routable within one heartbeat
                        DriverRegistry.register(
                            url, fresh, timeout=beat_timeout_s
                        )
                except Exception as e:  # noqa: BLE001 — may be restarting
                    print(
                        f"worker: register to {url} failed: {e}",
                        file=sys.stderr, flush=True,
                    )
            stop.wait(heartbeat_s)

    stopper._beat = threading.Thread(target=beat, name="worker-heartbeat", daemon=True)
    stopper._beat.start()
    print(
        f"worker: {info.host}:{info.port} "
        f"models={','.join(info.models or ())}",
        flush=True,
    )
    return srv, q, stopper


def run_model_verb(
    action: str,
    url: str,
    name: Optional[str] = None,
    spec: Optional[str] = None,
    version: Optional[int] = None,
    pin: bool = False,
    no_wait: bool = False,
    activate: Optional[str] = None,
) -> int:
    """``fleet model <action>`` — drive a worker's (or, routed, a
    gateway's) model control plane. Returns a process exit code; prints
    the JSON response."""
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    base = url.rstrip("/")
    if action == "list":
        req = HTTPRequestData(f"{base}/models", "GET")
    else:
        if not name:
            print("fleet model: --name is required", file=sys.stderr)
            return 2
        body: dict = {}
        if action == "load":
            if not spec:
                print("fleet model load: --spec is required", file=sys.stderr)
                return 2
            body["spec"] = spec
            if pin:
                body["pin"] = True
            if no_wait:
                body["wait"] = False
            if activate:
                body["activate"] = activate
        if version is not None:
            body["version"] = version
        req = HTTPRequestData(
            f"{base}/models/{name}/{action}", "POST",
            {"Content-Type": "application/json"}, json.dumps(body),
        )
    resp = send_request(req, timeout=300.0)
    entity = resp["entity"]
    if isinstance(entity, bytes):
        entity = entity.decode("utf-8", "replace")
    print(entity, flush=True)
    return 0 if resp["status_code"] in (200, 202) else 1


def scrape_metrics(url: str, timeout: float = 5.0) -> Optional[dict]:
    """GET a /metrics endpoint -> parsed samples, or None when
    unreachable / non-200 (a dead worker must not kill the whole fleet
    summary). Shared by ``fleet top`` and the deploy smoke gate."""
    from mmlspark_tpu import obs
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    resp = send_request(HTTPRequestData(url, "GET"), timeout=timeout)
    if resp["status_code"] != 200:
        return None
    body = resp["entity"]
    if isinstance(body, bytes):
        body = body.decode("utf-8", "replace")
    return obs.parse_text(body)


def roster_entries_from_registry(
    registry_url: str, service_name: str = "serving", timeout: float = 5.0
) -> list:
    """Roster -> raw entry dicts for one service (host/port plus any
    forwarded endpoint). ``registry_url`` may be comma-separated
    (registry HA): the first live registry answers. Raises when EVERY
    registry is unreachable — callers decide how to degrade."""
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    last_err: Optional[Exception] = None
    for url in split_registry_urls(registry_url):
        try:
            resp = send_request(
                HTTPRequestData(url.rstrip("/") + "/", "GET"),
                timeout=timeout,
            )
            if resp["status_code"] != 200:
                raise ConnectionError(
                    f"registry {url} answered {resp['status_code']}"
                )
            roster = json.loads(resp["entity"])
            return list(roster.get(service_name, []))
        except Exception as e:  # noqa: BLE001 — try the next registry
            last_err = e
    raise ConnectionError(
        f"no live registry among {registry_url!r}: {last_err}"
    )


def worker_urls_from_registry(
    registry_url: str, service_name: str = "serving", timeout: float = 5.0
) -> list:
    """Roster -> worker base URLs (preferring forwarded endpoints)."""
    return [
        f"http://{i.get('forwarded_host') or i['host']}"
        f":{i.get('forwarded_port') or i['port']}"
        for i in roster_entries_from_registry(
            registry_url, service_name, timeout
        )
    ]


def _hist_stats(parsed: dict, name: str, match: Optional[dict] = None) -> tuple:
    """(p50_estimate, mean, p99_estimate) in the histogram's native unit
    from exposition samples. Quantiles come from the SLO engine's bucket
    helpers — ONE implementation of "smallest bound reaching the rank",
    so fleet-top p99 and the SLO engine's p99 can never diverge."""
    from mmlspark_tpu import obs
    from mmlspark_tpu.obs.slo import _buckets_of, _quantile_from_buckets

    count = obs.sum_samples(parsed, f"{name}_count", match)
    total = obs.sum_samples(parsed, f"{name}_sum", match)
    if count <= 0:
        return (0.0, 0.0, 0.0)
    buckets = _buckets_of(parsed, name, match or {})
    return (
        _quantile_from_buckets(buckets, 0.5),
        total / count,
        _quantile_from_buckets(buckets, 0.99),
    )


def run_top(
    registry_url: Optional[str] = None,
    gateway_url: Optional[str] = None,
    worker_urls: Optional[list] = None,
    service_name: str = "serving",
) -> str:
    """One-screen fleet summary from /metrics scrapes (``fleet top``).

    Worker endpoints come from ``worker_urls`` and/or the registry roster;
    the gateway row needs ``gateway_url``. Everything rides the same
    Prometheus text any external scraper would consume — this is the
    zero-infrastructure view of it."""
    from mmlspark_tpu import obs

    endpoints: list = [(u.rstrip("/"), None) for u in (worker_urls or ())]
    notes: list = []
    if registry_url:
        try:
            for ep in worker_urls_from_registry(registry_url, service_name):
                if ep not in [e for e, _ in endpoints]:
                    endpoints.append((ep, None))
        except Exception as e:  # noqa: BLE001 — summary must degrade, not die
            # still report the explicitly-passed workers and the gateway:
            # the registry being the one dead component is exactly when
            # the operator needs the rest of the picture
            notes.append(f"fleet top: registry scrape failed: {e}")
    from mmlspark_tpu.obs import slo as slo_mod

    def slo_cell(parsed: dict) -> str:
        # each endpoint's own SLO engine exports its status gauge; a
        # pre-SLO worker simply has none — show '-', don't crash
        status = slo_mod.status_from_scrape(parsed)
        return (
            "-" if status is None
            else slo_mod.STATUS_NAMES.get(status, "?")
        )

    title = (
        f"fleet top — service {service_name!r}, {len(endpoints)} worker(s)"
    )
    if registry_url:
        # fleet supervise status (when a supervisor is registered) rides
        # the header line — the "is anything auto-healing?" glance
        sup = supervisor_status_from_registry(registry_url, service_name)
        if sup:
            title += f" — {sup}"
    lines = notes + [title]
    # the gateway scrape feeds BOTH its own summary line and the
    # per-worker BREAKER column (breaker state lives in the gateway —
    # it is the gateway's verdict about each backend)
    gw_parsed = scrape_metrics(gateway_url) if gateway_url else None
    breaker_names = {0: "closed", 1: "OPEN", 2: "half_open"}
    breakers: dict = {}
    if gw_parsed is not None:
        for (name, labels), v in gw_parsed.items():
            if name == "mmlspark_gateway_breaker_state":
                breakers[dict(labels).get("backend", "")] = (
                    breaker_names.get(int(v), "?")
                )
    hdr = (
        f"{'WORKER':<26} {'ACCEPT':>8} {'QDEPTH':>7} {'ERR':>5} "
        f"{'ERR_PCT':>7} {'QWAIT_P50_MS':>13} {'LAT_P50_MS':>11} "
        f"{'LAT_P99_MS':>11} {'BATCH_AVG':>10} {'INFL/LIM':>9} "
        f"{'BREAKER':>9} {'SLO':>6}"
    )
    lines.append(hdr)
    tot_accept = 0.0
    for ep, _ in endpoints:
        parsed = scrape_metrics(ep)
        addr = ep.split("//", 1)[-1]
        if parsed is None:
            lines.append(f"{addr:<26} {'DOWN':>8}")
            continue
        m = {"server": service_name}
        accept = obs.sum_samples(parsed, "mmlspark_serving_requests_total", m)
        qdepth = obs.sum_samples(
            parsed, "mmlspark_serving_queue_depth_requests", m
        )
        errs = obs.sum_samples(
            parsed, "mmlspark_serving_handler_errors_total", m
        )
        err_pct = (100.0 * errs / accept) if accept > 0 else 0.0
        qwait_p50, _, _ = _hist_stats(
            parsed, "mmlspark_serving_queue_wait_seconds", m
        )
        lat_p50, _, lat_p99 = _hist_stats(
            parsed, "mmlspark_serving_request_latency_seconds", m
        )
        _, batch_avg, _ = _hist_stats(
            parsed, "mmlspark_serving_batch_size_requests", m
        )
        # adaptive-concurrency cell: a pre-PR-5 worker (or --no-admission)
        # exports no admission gauges FOR THIS SERVICE — show '-', don't
        # invent zeros (label-matched: a co-located process may export
        # another server's admission series)
        has_adm = any(
            name == "mmlspark_admission_limit_requests"
            and ("server", service_name) in labels
            for (name, labels) in parsed
        )
        if has_adm:
            infl = obs.sum_samples(
                parsed, "mmlspark_admission_inflight_requests", m
            )
            lim = obs.sum_samples(
                parsed, "mmlspark_admission_limit_requests", m
            )
            adm_cell = f"{infl:.0f}/{lim:.0f}"
        else:
            adm_cell = "-"
        tot_accept += accept
        lines.append(
            f"{addr:<26} {accept:>8.0f} {qdepth:>7.0f} {errs:>5.0f} "
            f"{err_pct:>7.2f} {qwait_p50 * 1e3:>13.2f} "
            f"{lat_p50 * 1e3:>11.2f} {lat_p99 * 1e3:>11.2f} "
            f"{batch_avg:>10.1f} {adm_cell:>9} "
            f"{breakers.get(addr, '-'):>9} {slo_cell(parsed):>6}"
        )
    if gateway_url:
        parsed = gw_parsed
        addr = gateway_url.rstrip("/").split("//", 1)[-1]
        if parsed is None:
            lines.append(f"gateway {addr}: DOWN")
        else:
            gm = {"server": f"{service_name}-gateway"}
            accepted = obs.sum_samples(
                parsed, "mmlspark_serving_requests_total", gm
            )
            fwd = obs.sum_samples(parsed, "mmlspark_gateway_requests_total")
            retried = obs.sum_samples(parsed, "mmlspark_gateway_retries_total")
            failed = obs.sum_samples(parsed, "mmlspark_gateway_failures_total")
            backends = obs.sum_samples(
                parsed, "mmlspark_gateway_backends_count"
            )
            lat_p50, _, lat_p99 = _hist_stats(
                parsed, "mmlspark_gateway_request_latency_seconds"
            )
            containment = ""
            if breakers:
                n_open = sum(1 for s in breakers.values() if s != "closed")
                budget = obs.sum_samples(
                    parsed, "mmlspark_gateway_retry_budget_remaining_ratio"
                )
                hedges = obs.sum_samples(
                    parsed, "mmlspark_gateway_hedges_total"
                )
                containment = (
                    f", breakers {n_open}/{len(breakers)} open, "
                    f"retry budget {budget * 100:.0f}%"
                    + (f", hedges {hedges:.0f}" if hedges else "")
                )
            lines.append(
                f"gateway {addr}: accepted {accepted:.0f}, forwarded "
                f"{fwd:.0f}, retried {retried:.0f}, failed {failed:.0f}, "
                f"backends {backends:.0f}, p50 {lat_p50 * 1e3:.2f} ms, "
                f"p99 {lat_p99 * 1e3:.2f} ms{containment}, "
                f"slo {slo_cell(parsed)}"
            )
    lines.append(f"total accepted across workers: {tot_accept:.0f}")
    return "\n".join(lines)


def _trace_endpoints(
    registry_url: Optional[str],
    gateway_url: Optional[str],
    worker_urls: Optional[list],
    service_name: str = "serving",
) -> tuple:
    """(endpoints, notes): every /traces-scrapeable base URL the caller
    named plus the registry roster — and the registry's OWN endpoint,
    whose spans cover control-plane traffic."""
    endpoints: list = [u.rstrip("/") for u in (worker_urls or ())]
    notes: list = []
    if gateway_url:
        gu = gateway_url.rstrip("/")
        if gu not in endpoints:
            endpoints.append(gu)
    if registry_url:
        try:
            for ep in worker_urls_from_registry(registry_url, service_name):
                if ep not in endpoints:
                    endpoints.append(ep)
        except Exception as e:  # noqa: BLE001 — assemble what's reachable
            notes.append(f"trace: registry roster unavailable: {e}")
        ru = registry_url.rstrip("/")
        if ru not in endpoints:
            endpoints.append(ru)
    return endpoints, notes


def run_trace(
    trace_id: str,
    registry_url: Optional[str] = None,
    gateway_url: Optional[str] = None,
    worker_urls: Optional[list] = None,
    service_name: str = "serving",
) -> str:
    """``fleet trace <id>``: scrape every span buffer in the fleet, join
    the trace, render the cross-process tree. Endpoints that don't serve
    ``/traces`` (pre-trace workers: 404) are skipped."""
    from mmlspark_tpu.obs import traces as traces_mod

    endpoints, notes = _trace_endpoints(
        registry_url, gateway_url, worker_urls, service_name
    )
    spans, _, scraped = traces_mod.collect(endpoints, trace_id=trace_id)
    if not scraped:
        notes.append(
            f"trace: none of {len(endpoints)} endpoint(s) served /traces"
        )
    notes.append(traces_mod.render_tree(spans, trace_id))
    return "\n".join(notes)


def run_traces_slowest(
    n: int = 5,
    registry_url: Optional[str] = None,
    gateway_url: Optional[str] = None,
    worker_urls: Optional[list] = None,
    service_name: str = "serving",
) -> str:
    """``fleet traces --slowest N``: jump from the latency histograms'
    p99-bucket exemplars to real traces and render each tree, worst
    first. Falls back to the longest buffered request spans when no
    exemplar carried a trace id yet."""
    from mmlspark_tpu.obs import traces as traces_mod

    endpoints, notes = _trace_endpoints(
        registry_url, gateway_url, worker_urls, service_name
    )
    spans, exemplars, scraped = traces_mod.collect(endpoints)
    if not scraped:
        notes.append(
            f"traces: none of {len(endpoints)} endpoint(s) served /traces"
        )
        return "\n".join(notes)
    ranked = traces_mod.slowest_traces(exemplars, n=n)
    if not ranked:
        # no exemplars yet (cold fleet): rank the buffered request spans
        best: dict = {}
        for s in spans:
            if s.name in ("gateway.request", "serving.request"):
                best[s.trace_id] = max(
                    best.get(s.trace_id, 0.0), s.duration_ns / 1e9
                )
        ranked = sorted(
            ((v, t) for t, v in best.items()), reverse=True
        )[:n]
    if not ranked:
        notes.append("traces: no request traces buffered yet")
        return "\n".join(notes)
    by_trace: dict = {}
    for s in spans:
        by_trace.setdefault(s.trace_id, []).append(s)
    notes.append(
        f"slowest {len(ranked)} trace(s) across {len(scraped)} endpoint(s):"
    )
    for v, tid in ranked:
        notes.append(f"--- {v * 1e3:.2f} ms ---")
        notes.append(traces_mod.render_tree(by_trace.get(tid, []), tid))
    return "\n".join(notes)


def scrape_profile(url: str, timeout: float = 5.0) -> Optional[str]:
    """GET a /profile endpoint -> collapsed-stack text, or None when the
    endpoint is unreachable / pre-profiler (404). The scrape itself
    starts the remote sampler if it wasn't running."""
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    if not url.rstrip("/").endswith("/profile"):
        url = url.rstrip("/") + "/profile"
    try:
        resp = send_request(HTTPRequestData(url, "GET"), timeout=timeout)
    except Exception:  # noqa: BLE001 — a dead process is a note, not a crash
        return None
    if resp["status_code"] != 200:
        return None
    body = resp["entity"]
    if isinstance(body, bytes):
        body = body.decode("utf-8", "replace")
    return body


def run_profile(
    seconds: float = 5.0,
    registry_url: Optional[str] = None,
    gateway_url: Optional[str] = None,
    worker_urls: Optional[list] = None,
    service_name: str = "serving",
) -> str:
    """``fleet profile [--seconds N]``: scrape every /profile ingress
    twice ``seconds`` apart, diff the collapsed-stack counts so only
    samples taken *inside the window* survive, and merge the per-process
    windows into one fleet-wide flamegraph-ready view (each stack
    prefixed by its process label). The first scrape also starts any
    sampler that wasn't running, so the window is live even on a fleet
    booted without profiling."""
    from mmlspark_tpu.obs import prof

    endpoints, notes = _trace_endpoints(
        registry_url, gateway_url, worker_urls, service_name
    )
    before: dict = {}
    for ep in endpoints:
        text = scrape_profile(ep)
        if text is not None:
            before[ep] = prof.parse_collapsed(text)
    if not before:
        notes.append(
            f"profile: none of {len(endpoints)} endpoint(s) served /profile"
        )
        return "\n".join(notes)
    time.sleep(max(0.0, float(seconds)))
    per_process: dict = {}
    for ep, base in before.items():
        text = scrape_profile(ep)
        if text is None:
            notes.append(f"profile: {ep} vanished mid-window; skipped")
            continue
        window: dict = {}
        for stack, n in prof.parse_collapsed(text).items():
            d = n - base.get(stack, 0)
            if d > 0:
                window[stack] = d
        label = ep
        for line in text.splitlines():  # prefer the payload's own label
            if line.startswith("# process:"):
                label = line.split(":", 1)[1].strip() or ep
                break
        if label in per_process:  # two processes, same label: keep both
            label = f"{label} {ep}"
        per_process[label] = window
    notes.append(
        f"# fleet profile: {len(per_process)} process(es), "
        f"{seconds:g}s window"
    )
    notes.append(prof.merge_collapsed(per_process).rstrip("\n"))
    return "\n".join(notes)


def run_gateway(
    registry_url: str,
    host: str = "0.0.0.0",
    port: int = 8080,
    service_name: str = "serving",
    slo_targets: Optional[str] = None,
    slo_availability: float = 0.999,
    slo_p99_ms: Optional[float] = 250.0,
    slo_interval_s: float = 15.0,
    hedge_ms: Optional[float] = None,
    retry_budget_ratio: float = 0.2,
    breaker_cooldown_s: float = 5.0,
    reactors: int = 2,
    num_dispatchers: int = 4,
    header_deadline_s: Optional[float] = 15.0,
) -> Any:
    from mmlspark_tpu import obs
    from mmlspark_tpu.serving.distributed import ServingGateway

    gw = ServingGateway(
        registry_url=registry_url, service_name=service_name,
        host=host, port=port, hedge_ms=hedge_ms,
        retry_budget_ratio=retry_budget_ratio,
        cooldown_s=breaker_cooldown_s,
        num_reactors=reactors, num_dispatchers=num_dispatchers,
        header_deadline_s=header_deadline_s,
    )
    ginfo = gw.start()
    obs.set_process_label(
        f"{service_name}-gateway@{ginfo.host}:{ginfo.port}"
    )
    gw.slo_engine = _start_slo_engine(
        service_name, slo_targets, slo_availability, slo_p99_ms,
        slo_interval_s, gateway=True,
    )
    print(f"gateway: http://{ginfo.host}:{ginfo.port}/", flush=True)
    return gw


def run_train(
    registry_url: str,
    name: str,
    data: str,
    ckpt_dir: str,
    partitions: int = 8,
    world_size: int = 1,
    service_name: str = "train",
    num_iterations: int = 100,
    num_leaves: int = 31,
    learning_rate: float = 0.1,
    min_data_in_leaf: int = 20,
    seed: int = 0,
    objective: str = "binary",
    boosting_type: str = "gbdt",
    growth_policy: str = "lossguide",
    checkpoint_every: int = 2,
    heartbeat_s: float = 0.5,
    gen_timeout_s: float = 120.0,
    advertise_host: str = "127.0.0.1",
    straggler_factor: float = 3.0,
    straggler_rounds: int = 3,
    evict_stragglers: bool = False,
    min_world: int = 1,
    resume_from: Optional[str] = None,
    status_file: Optional[str] = None,
    out_model: Optional[str] = None,
    allow_growback: bool = True,
    artifact_dir: Optional[str] = None,
    allreduce_port: int = 0,
    advertise_allreduce_port: Optional[int] = None,
    reduce_mode: str = "ring",
    tree_parallelism: str = "data",
    top_k: int = 20,
    sketch_bits: int = 16,
) -> Any:
    """``fleet train``: one elastic training host (parallel/elastic.py).

    All hosts of the gang run this same role with the same ``--data`` /
    config and a shared ``--ckpt-dir``; membership and the generation
    record ride the ``--registry`` (run it with ``--ttl-s`` a few
    heartbeat periods so a dead host's loss is detectable). A SIGKILLed
    trainer restarted by ``fleet supervise --train`` auto-resumes from
    its checkpoint dir and grows back into the gang at the next
    checkpoint boundary. Batch-style role: returns the booster when the
    run completes (the process exits, unlike the serving roles)."""
    import hashlib

    from mmlspark_tpu import obs
    from mmlspark_tpu.models.gbdt.train import TrainConfig
    from mmlspark_tpu.parallel.elastic import (
        ElasticTrainer,
        is_streaming_spec,
        load_streaming_data,
        load_training_data,
    )

    obs.set_process_label(f"{service_name}@{name}")
    if is_streaming_spec(data):
        # out-of-core mode: rows stream chunk-by-chunk (binning via
        # reducer-merged sketches); the float matrix never materializes
        stream, n_rows, n_features = load_streaming_data(data)
        x = y = None
    else:
        stream, n_rows, n_features = None, None, None
        x, y = load_training_data(data)
    cfg = TrainConfig(
        objective=objective, num_iterations=num_iterations,
        num_leaves=num_leaves, learning_rate=learning_rate,
        min_data_in_leaf=min_data_in_leaf, seed=seed,
        boosting_type=boosting_type, growth_policy=growth_policy,
        parallelism=(
            "voting_parallel" if tree_parallelism == "voting"
            else "data_parallel"
        ),
        top_k=top_k,
    )
    # persist the exported model BEFORE the trainer flips its status file
    # to done: a status watcher (supervisor, drill, operator script) must
    # be able to read --out-model the instant it observes done=true
    persisted: dict = {}

    def _persist_model(booster: Any) -> None:
        model = booster.to_model_string()
        if out_model:
            import os as _os

            tmp = out_model + ".tmp"
            with open(tmp, "w") as f:
                f.write(model)
            _os.replace(tmp, out_model)
        persisted["model"] = model

    trainer = ElasticTrainer(
        registry_url, name, x, y, cfg, ckpt_dir,
        n_partitions=partitions, world_size=world_size,
        service=service_name, checkpoint_every=checkpoint_every,
        heartbeat_s=heartbeat_s, gen_timeout_s=gen_timeout_s,
        resume_from=resume_from, advertise_host=advertise_host,
        straggler_factor=straggler_factor,
        straggler_rounds=straggler_rounds,
        evict_stragglers=evict_stragglers, min_world=min_world,
        status_file=status_file, allow_growback=allow_growback,
        artifact_dir=artifact_dir,
        allreduce_port=allreduce_port,
        advertise_allreduce_port=advertise_allreduce_port,
        reduce_mode=reduce_mode,
        stream=stream, n_rows=n_rows, n_features=n_features,
        sketch_bits=sketch_bits,
        on_complete=_persist_model,
    )
    booster = trainer.run()
    model = persisted.get("model")
    if model is None:  # pragma: no cover — on_complete always ran above
        model = booster.to_model_string()
    digest = hashlib.sha256(model.encode()).hexdigest()
    print(f"train: {name} done, model sha256 {digest}", flush=True)
    return booster


def run_supervise(
    registry_url: str,
    workers: list,
    service_name: str = "serving",
    probe_s: float = 2.0,
    wedge_after: int = 3,
    backoff_s: float = 1.0,
    backoff_max_s: float = 30.0,
    host: str = "127.0.0.1",
    port: int = 0,
    autoscale: bool = False,
    min_replicas: int = 1,
    max_replicas: int = 4,
    worker_template: Optional[str] = None,
    scale_out_cooldown_s: float = 10.0,
    scale_in_cooldown_s: float = 30.0,
    idle_after_s: float = 30.0,
    util_threshold: float = 0.85,
    gateway_url: Optional[str] = None,
    trains: Optional[list] = None,
    spawn_cmd: Optional[str] = None,
    placement: Optional[str] = None,
) -> Any:
    """``fleet supervise``: spawn each ``--worker`` charge as a ``fleet
    worker`` process and keep it alive — restart on crash, kill+restart
    on a wedged ``/health``, capped exponential backoff between restarts
    (serving/supervisor.py). The supervisor registers its own status
    endpoint under ``<service-name>-supervisor`` so ``fleet top`` shows
    it in the header.

    ``--autoscale`` (docs/online-learning.md): the supervisor also
    DECIDES the replica count — the SLO-burn/admission-signal policy in
    ``mmlspark_tpu/online/autoscaler.py`` scrapes the gateway and the
    rostered workers each tick, spawns a ``--worker-template`` replica
    before the breaker trips (sheds/utilization/red burn) and reaps
    autoscaled replicas on sustained idle, clamped to
    ``[--min-replicas, --max-replicas]``."""
    from mmlspark_tpu import obs
    from mmlspark_tpu.serving.supervisor import (
        FleetSupervisor,
        charge_from_train_args,
        charge_from_worker_args,
    )

    charges = [
        charge_from_worker_args(w, registry_url, i)
        for i, w in enumerate(workers)
    ]
    # training charges: a SIGKILLed elastic trainer restarts with its
    # full argv, auto-resumes from its --ckpt-dir, and grows back into
    # the gang at the next checkpoint boundary (parallel/elastic.py)
    charges += [
        charge_from_train_args(t, registry_url, i)
        for i, t in enumerate(trains or [])
    ]
    autoscaler = signals_fn = None
    template = worker_template
    if autoscale:
        from mmlspark_tpu.online.autoscaler import Autoscaler, FleetSignals

        autoscaler = Autoscaler(
            min_replicas=min_replicas, max_replicas=max_replicas,
            util_threshold=util_threshold,
            scale_out_cooldown_s=scale_out_cooldown_s,
            scale_in_cooldown_s=scale_in_cooldown_s,
            idle_after_s=idle_after_s,
        )
        signals_fn = FleetSignals(
            registry_url=registry_url, gateway_url=gateway_url,
            service_name=service_name,
        )
        if template is None and workers:
            # autoscaled replicas default to the first charge's shape,
            # minus any fixed --port (replicas need ephemeral ports)
            template = _strip_port(workers[0])
    sup = FleetSupervisor(
        charges, registry_url=registry_url, service_name=service_name,
        probe_s=probe_s, wedge_after=wedge_after, backoff_s=backoff_s,
        backoff_max_s=backoff_max_s, host=host, port=port,
        autoscaler=autoscaler, worker_template=template,
        signals_fn=signals_fn, spawn_cmd=spawn_cmd, placement=placement,
    ).start()
    obs.set_process_label(
        f"{service_name}-supervisor@{sup._info.host}:{sup._info.port}"
    )
    print(
        f"supervisor: {sup.url} watching {len(charges)} worker(s)"
        + (
            f", autoscaling {min_replicas}..{max_replicas}"
            if autoscale else ""
        ),
        flush=True,
    )
    return sup


def _strip_port(worker_args: str) -> str:
    """Remove ``--port N`` / ``--port=N`` from a worker arg string
    (autoscaled replicas must bind ephemeral ports — two replicas
    cannot share the operator's fixed one)."""
    import shlex

    toks = shlex.split(worker_args)
    out = []
    i = 0
    while i < len(toks):
        if toks[i] == "--port" and i + 1 < len(toks):
            i += 2
            continue
        if toks[i].startswith("--port="):
            i += 1
            continue
        out.append(toks[i])
        i += 1
    return " ".join(out)


def run_online(
    registry_url: Optional[str] = None,
    model: str = "vw-online",
    host: str = "0.0.0.0",
    port: int = 0,
    service_name: str = "serving",
    worker_urls: Optional[list] = None,
    snapshot_dir: Optional[str] = None,
    publish_every_s: float = 2.0,
    freshness_slo_ms: float = 5000.0,
    heartbeat_s: float = 5.0,
    advertise_host: Optional[str] = None,
    num_bits: int = 18,
    loss: str = "logistic",
    lr: float = 0.5,
    batch: int = 64,
    label_col: str = "label",
    features_col: str = "features",
    text_col: Optional[str] = None,
    distributed: bool = False,
    artifact_dir: Optional[str] = None,
    publish_epoch: Optional[int] = None,
    replicas: int = 0,
) -> tuple:
    """``fleet online``: run the continuous-learning loop as a fleet
    role. Starts the HTTP ingest ingress (``POST /ingest``; ``GET
    /metrics`` inline), trains the device-resident VW learner on every
    ingested micro-batch, and every ``publish_every_s`` publishes a
    versioned ``vw:`` snapshot through the zero-drop load -> warm ->
    swap path on every rostered worker (and/or explicit
    ``--worker-url``\\ s). Registers under ``<service>-online`` so
    ``fleet top`` and the deploy smoke's freshness gate find it; the
    freshness SLO engine runs in-process and exports burn-rate gauges.

    ``--artifact-dir`` switches publication to **artifact mode** (no
    shared filesystem): snapshots are published as
    ``artifact:vw:<name>@<sha256>`` specs, served ranged off this
    process's ingest ingress and advertised on its heartbeats — workers
    pull the bytes over HTTP, hash-verified and resumable
    (docs/artifacts.md). ``--replicas N`` adds replication-before-ack:
    each snapshot must be confirmed on N other artifact holders before
    any worker is driven to load it (docs/robustness.md).

    Returns ``(stream, loop, stopper)``."""
    import dataclasses

    from mmlspark_tpu import obs
    from mmlspark_tpu.online import (
        FeedbackStream,
        OnlineLearningLoop,
        OnlineTrainer,
        Publisher,
    )
    from mmlspark_tpu.serving.registry import DriverRegistry

    if not registry_url and not worker_urls:
        raise ValueError("fleet online needs --registry and/or --worker-url")
    stream = FeedbackStream()
    info = stream.serve(host=host, port=port, name=f"{service_name}-online")
    obs.set_process_label(
        f"{service_name}-online@{advertise_host or info.host}:{info.port}"
    )
    trainer = OnlineTrainer(
        num_bits=num_bits, loss=loss, lr=lr, batch=batch,
        label_col=label_col, features_col=features_col, text_col=text_col,
        distributed=distributed,
    )
    art_store = None
    artifact_url = None
    if artifact_dir:
        from mmlspark_tpu.serving import artifacts as artifacts_mod

        art_store = artifacts_mod.ArtifactStore(artifact_dir)
        # snapshots serve ranged off the SAME ingest ingress (the
        # /metrics contract: inline, never queued or counted)
        artifacts_mod.attach(stream._ingress, art_store)
        artifact_url = (
            f"http://{advertise_host or info.host}:{info.port}"
        )
    publisher = Publisher(
        model=model, snapshot_dir=snapshot_dir,
        worker_urls=worker_urls, registry_url=registry_url,
        service_name=service_name,
        artifact_store=art_store, artifact_url=artifact_url,
        epoch=publish_epoch, replicas=replicas,
    )
    loop = OnlineLearningLoop(
        stream, trainer, publisher, publish_every_s=publish_every_s,
        freshness_budget_ms=freshness_slo_ms or None,
    ).start()
    if advertise_host:
        info = dataclasses.replace(info, host=advertise_host)
    stop = threading.Event()
    registry_urls = split_registry_urls(registry_url)
    beat_timeout_s = beat_timeout(heartbeat_s)

    def beat() -> None:
        while not stop.is_set():
            fresh = info
            if art_store is not None:
                # advertise the snapshot artifacts each beat so workers
                # can also resolve peers from the roster (the spec's
                # embedded URL hint is merely the fast path)
                fresh = dataclasses.replace(
                    info, artifacts=tuple(art_store.refs())
                )
            for url in registry_urls:
                try:
                    if not stop.is_set():
                        # explicit short timeout: a blackholed registry
                        # must not park the heartbeat thread
                        DriverRegistry.register(
                            url, fresh, timeout=beat_timeout_s,
                        )
                except Exception as e:  # noqa: BLE001 — may be restarting
                    print(
                        f"online: register to {url} failed: {e}",
                        file=sys.stderr, flush=True,
                    )
            stop.wait(heartbeat_s)

    beat_t = threading.Thread(target=beat, name="online-heartbeat", daemon=True)
    beat_t.start()

    class _OnlineStopper:
        def stop(self) -> None:
            if stop.is_set():
                return
            stop.set()
            beat_t.join(12.0)
            loop.stop(final_publish=True)
            stream.close()
            for url in registry_urls:
                try:
                    DriverRegistry.deregister(url, info)
                except Exception:  # noqa: BLE001 — registry may be gone
                    pass

        set = stop

    print(
        f"online: ingest http://{info.host}:{info.port}/ingest -> model "
        f"{model!r}, publish every {publish_every_s}s", flush=True,
    )
    return stream, loop, _OnlineStopper()


def supervisor_status_from_registry(
    registry_url: str, service_name: str = "serving",
) -> Optional[str]:
    """One-line ``fleet supervise`` status for ``fleet top``'s header, or
    None when no supervisor is registered / reachable."""
    from mmlspark_tpu import obs

    try:
        urls = worker_urls_from_registry(
            registry_url, f"{service_name}-supervisor"
        )
    except Exception:  # noqa: BLE001 — registry down: top degrades already
        return None
    for u in urls:
        parsed = scrape_metrics(u)
        if parsed is None:
            continue
        charges = obs.sum_samples(
            parsed, "mmlspark_supervisor_charges_count"
        )
        up = obs.sum_samples(
            parsed, "mmlspark_supervisor_charges_up_count"
        )
        restarts = obs.sum_samples(
            parsed, "mmlspark_supervisor_restarts_total"
        )
        return (
            f"supervise: up {up:.0f}/{charges:.0f}, "
            f"restarts {restarts:.0f}"
        )
    return None


def _install_forensics() -> None:
    """Every long-running fleet role carries the same forensics kit:
    SIGUSR1 -> flight-recorder dump, SIGUSR2 -> all-thread stall dump,
    and the always-on sampling profiler (``MMLSPARK_PROF_HZ=0`` opts
    out). Stall forensics: docs/observability.md."""
    from mmlspark_tpu.obs import prof, watchdog
    from mmlspark_tpu.obs.flightrec import install_sigusr1

    install_sigusr1()
    watchdog.install_sigusr2()
    prof.ensure_started()


def _serve_forever(stoppables: list, drain_s: float = 0.0) -> None:
    ev = threading.Event()

    def on_sig(signum: int, frame: Any) -> None:
        ev.set()

    signal.signal(signal.SIGTERM, on_sig)
    signal.signal(signal.SIGINT, on_sig)
    ev.wait()
    for s in stoppables:
        try:
            if drain_s > 0 and hasattr(s, "drain"):
                # gateway roll: 503 /health, finish accepted requests, stop
                s.drain(timeout_s=drain_s)
            elif hasattr(s, "stop"):
                s.stop()
            else:
                s.set()
        except Exception:  # noqa: BLE001
            pass


def main(argv: Optional[list] = None) -> None:
    ap = argparse.ArgumentParser(prog="mmlspark_tpu.serving.fleet")
    ap.add_argument(
        "--fault-plan", default=None,
        help="JSON fault plan (inline or a file path) armed for this "
        "process — chaos-smokes a docker-compose fleet (core/faults.py)",
    )
    sub = ap.add_subparsers(dest="role", required=True)
    r = sub.add_parser("registry")
    r.add_argument("--host", default="0.0.0.0")
    r.add_argument("--port", type=int, default=9090)
    r.add_argument(
        "--ttl-s", type=float, default=None,
        help="drop roster entries not re-registered within this many "
        "seconds (a few worker heartbeat periods)",
    )
    r.add_argument(
        "--peer", action="append", default=[],
        help="peer registry base URL for anti-entropy (repeatable): "
        "rosters are periodically pulled from peers and merged by "
        "newest registration stamp, so partitioned registries reconverge",
    )
    r.add_argument(
        "--reconcile-s", type=float, default=5.0,
        help="anti-entropy pull interval against --peer registries",
    )
    w = sub.add_parser("worker")
    w.add_argument("--registry", required=True)
    w.add_argument("--model", default="echo")
    w.add_argument("--host", default="0.0.0.0")
    w.add_argument("--port", type=int, default=0)
    w.add_argument("--service-name", default="serving")
    w.add_argument("--heartbeat-s", type=float, default=5.0)
    w.add_argument(
        "--advertise-host", default=None,
        help="hostname other containers reach this worker by (compose/k8s)",
    )
    w.add_argument(
        "--load", action="append", default=[], metavar="NAME=SPEC",
        help="additional model to load+warm before registering "
        "(repeatable; bare SPEC derives the name from the spec)",
    )
    w.add_argument(
        "--hbm-budget-bytes", type=int, default=None,
        help="cap resident model-weight bytes; past it the ModelStore "
        "LRU-evicts unpinned non-serving versions (docs/modelstore.md)",
    )
    w.add_argument(
        "--default-deadline-ms", type=float, default=None,
        help="admission-control deadline applied to requests that carry "
        "no x-mmlspark-deadline-ms header (None = shed only on request)",
    )
    w.add_argument(
        "--no-admission", action="store_true",
        help="disable the adaptive in-flight limit (AIMD admission "
        "control, on by default; serving/admission.py)",
    )
    w.add_argument(
        "--admission-initial-limit", type=int, default=32,
        help="starting in-flight limit for the AIMD controller",
    )
    w.add_argument(
        "--admission-min-target-ms", type=float, default=None,
        help="queue-wait floor (ms) below which a window never counts "
        "as overload (default 2ms) — raise on slow/noisy boxes so "
        "scheduler jitter cannot collapse the AIMD limit",
    )
    w.add_argument(
        "--artifact-dir", default=None,
        help="root of this worker's content-addressed artifact cache "
        "(artifact: model specs fetch into it and re-serve off the "
        "ingress; default: a private tempdir)",
    )
    w.add_argument(
        "--reactors", type=int, default=2,
        help="ingress event loops sharing the listening socket (one slow "
        "client stalls only its own reactor; docs/serving.md)",
    )
    w.add_argument(
        "--header-deadline-s", type=float, default=15.0,
        help="slowloris shed: a request's full head (and body, floored "
        "at 256 KiB/s) must arrive within this budget of its first byte "
        "or the connection is answered 408 and closed (docs/chaos.md)",
    )
    w.add_argument(
        "--drain-s", type=float, default=10.0,
        help="on SIGTERM: deregister, stop accepting, and finish every "
        "accepted request (incl. staged continuous batches) for up to "
        "this long before exiting (0 = stop immediately; docs/chaos.md)",
    )

    def add_slo_flags(p) -> None:
        p.add_argument(
            "--slo-targets", default=None,
            help="JSON list of SLO targets (inline or a file path; "
            "obs/slo.py SLOTarget fields) — overrides the role default",
        )
        p.add_argument(
            "--slo-availability", type=float, default=0.999,
            help="default target availability (good/total)",
        )
        p.add_argument(
            "--slo-p99-ms", type=float, default=250.0,
            help="default p99 latency budget; requests over it burn the "
            "error budget too (0 disables the latency SLI)",
        )

    add_slo_flags(w)
    g = sub.add_parser("gateway")
    g.add_argument("--registry", required=True)
    g.add_argument("--host", default="0.0.0.0")
    g.add_argument("--port", type=int, default=8080)
    g.add_argument("--service-name", default="serving")
    g.add_argument(
        "--drain-s", type=float, default=10.0,
        help="on SIGTERM: finish accepted requests for up to this long "
        "(0 = stop immediately)",
    )
    g.add_argument(
        "--hedge-ms", type=float, default=None,
        help="tail hedging: duplicate a request still pending after this "
        "many ms to a second backend, first answer wins (0 = derive the "
        "delay from the forward-latency p95; idempotent handlers only)",
    )
    g.add_argument(
        "--retry-budget-ratio", type=float, default=0.2,
        help="retries+hedges capped at this fraction of recent request "
        "volume (the anti-retry-storm token bucket)",
    )
    g.add_argument(
        "--breaker-cooldown-s", type=float, default=5.0,
        help="circuit-breaker open period (doubles per consecutive "
        "open, capped; half-open probe after it elapses)",
    )
    g.add_argument(
        "--reactors", type=int, default=2,
        help="gateway-ingress event loops sharing the listening socket",
    )
    g.add_argument(
        "--dispatchers", type=int, default=4,
        help="forwarding threads (each keeps its own keep-alive "
        "connection per backend)",
    )
    g.add_argument(
        "--header-deadline-s", type=float, default=15.0,
        help="slowloris shed at the gateway front door: a request's "
        "full head must arrive within this budget of its first byte "
        "(408 + close; docs/chaos.md)",
    )
    add_slo_flags(g)
    sv = sub.add_parser(
        "supervise",
        help="spawn and watch local fleet workers: restart crashed/"
        "wedged processes with capped exponential backoff",
    )
    sv.add_argument("--registry", required=True)
    sv.add_argument(
        "--worker", action="append", default=[],
        metavar="\"WORKER ARGS\"",
        help="one supervised worker's `fleet worker` arguments, quoted "
        "(repeatable); --registry is prepended automatically. A fixed "
        "--port enables /health wedge detection",
    )
    sv.add_argument(
        "--train", action="append", default=[],
        metavar="\"TRAIN ARGS\"",
        help="one supervised elastic trainer's `fleet train` arguments, "
        "quoted (repeatable); a SIGKILLed trainer restarts warm from "
        "its --ckpt-dir and rejoins the gang at the next checkpoint "
        "boundary",
    )
    sv.add_argument("--service-name", default="serving")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument(
        "--port", type=int, default=0,
        help="status endpoint port (GET /metrics; registered under "
        "<service-name>-supervisor)",
    )
    sv.add_argument("--probe-s", type=float, default=2.0,
                    help="health-probe / process-poll interval")
    sv.add_argument(
        "--wedge-after", type=int, default=3,
        help="consecutive failed /health probes before a running worker "
        "is declared wedged and killed+restarted",
    )
    sv.add_argument("--backoff-s", type=float, default=1.0,
                    help="base restart backoff (doubles per fast death)")
    sv.add_argument("--backoff-max-s", type=float, default=30.0,
                    help="restart backoff cap")
    sv.add_argument(
        "--autoscale", action="store_true",
        help="SLO-driven autoscaling: spawn a replica on admission "
        "sheds / high utilization / red SLO burn, reap on sustained "
        "idle (mmlspark_tpu/online/autoscaler.py)",
    )
    sv.add_argument("--min-replicas", type=int, default=1)
    sv.add_argument("--max-replicas", type=int, default=4)
    sv.add_argument(
        "--worker-template", default=None,
        help="fleet-worker args for autoscaled replicas (default: the "
        "first --worker, with any fixed --port stripped)",
    )
    sv.add_argument("--scale-out-cooldown-s", type=float, default=10.0)
    sv.add_argument("--scale-in-cooldown-s", type=float, default=30.0)
    sv.add_argument(
        "--idle-after-s", type=float, default=30.0,
        help="sustained-idle window before an autoscaled replica is reaped",
    )
    sv.add_argument("--util-threshold", type=float, default=0.85)
    sv.add_argument(
        "--gateway", default=None,
        help="gateway base URL scraped for scale signals (backpressure, "
        "breakers, SLO status)",
    )
    sv.add_argument(
        "--spawn-cmd", default=None,
        help="pluggable placement: a command template wrapping every "
        "spawn (restart AND autoscale-out). A bare {argv} token splices "
        "the argv (local wrappers, 'kubectl run w --image=i -- {argv}'); "
        "{argv} embedded in a larger token substitutes the shell-quoted "
        "line for remote shells (\"ssh worker-7 'exec {argv}'\"). Remote "
        "charges boot from pulled artifacts — no shared filesystem",
    )
    sv.add_argument(
        "--placement", default=None,
        help="placement provider for every spawn: 'local', 'ssh:<host>' "
        "(SSH-shaped remote exec), 'k8s:<image>[@<namespace>]' "
        "(kubectl-run-shaped stub), or a raw wrapper template (the "
        "--spawn-cmd form). Remote charges pull models/checkpoints as "
        "artifacts by digest — the supervisor's filesystem is never "
        "assumed shared. Fencing (boot stamps, epoch tokens, "
        "majority-claim deferral) applies to remote placements verbatim",
    )
    on = sub.add_parser(
        "online",
        help="continuous-learning loop: HTTP feedback ingest -> online "
        "VW training -> zero-drop publication to the fleet's workers "
        "(docs/online-learning.md)",
    )
    on.add_argument("--registry", default=None)
    on.add_argument(
        "--worker-url", action="append", default=[],
        help="explicit worker base URL to publish to (repeatable; "
        "adds to the registry roster)",
    )
    on.add_argument("--model", default="vw-online")
    on.add_argument("--host", default="0.0.0.0")
    on.add_argument("--port", type=int, default=0,
                    help="HTTP ingest port (POST /ingest; GET /metrics)")
    on.add_argument("--service-name", default="serving")
    on.add_argument("--snapshot-dir", default=None)
    on.add_argument("--publish-every-s", type=float, default=2.0)
    on.add_argument(
        "--freshness-slo-ms", type=float, default=5000.0,
        help="freshness budget: example-ingested -> model-servable over "
        "this burns the SLO error budget (0 disables the engine)",
    )
    on.add_argument("--heartbeat-s", type=float, default=5.0)
    on.add_argument("--advertise-host", default=None)
    on.add_argument(
        "--publish-epoch", type=int, default=None,
        help="fencing token stamped on every publication: workers "
        "reject load/swap bodies whose epoch is older than the highest "
        "seen (docs/robustness.md split brain)",
    )
    on.add_argument("--num-bits", type=int, default=18)
    on.add_argument("--loss", default="logistic")
    on.add_argument("--lr", type=float, default=0.5)
    on.add_argument("--batch", type=int, default=64)
    on.add_argument("--label-col", default="label")
    on.add_argument("--features-col", default="features")
    on.add_argument(
        "--text-col", default=None,
        help="hash this text column through the VW featurizer instead "
        "of reading pre-hashed sparse rows",
    )
    on.add_argument(
        "--distributed", action="store_true",
        help="shard micro-batches over the device mesh with a pmean "
        "allreduce per pass (multi-chip training)",
    )
    on.add_argument(
        "--artifact-dir", default=None,
        help="publish snapshots as content-addressed artifacts served "
        "off the ingest ingress (no shared filesystem): workers pull "
        "artifact:vw:<name>@<sha256> over HTTP, hash-verified + "
        "resumable (docs/artifacts.md)",
    )
    on.add_argument(
        "--replicas", type=int, default=0,
        help="replication-before-ack (artifact mode): each snapshot "
        "must be confirmed on this many OTHER artifact holders before "
        "any worker loads it — a SIGKILLed publisher host never "
        "strands the only copy (docs/robustness.md)",
    )
    tn = sub.add_parser(
        "train",
        help="one elastic training host: gang membership over the "
        "registry, TCP histogram allreduce, reshard-and-resume on host "
        "loss (parallel/elastic.py; docs/robustness.md)",
    )
    tn.add_argument("--registry", required=True)
    tn.add_argument("--name", required=True,
                    help="this host's gang member name")
    tn.add_argument(
        "--data", required=True,
        help="training data spec: synth:<n>x<d>:<seed>, npz:<path>, or "
        "an out-of-core stream — stream-synth:<n>x<d>:<seed>[:<chunk>] "
        "/ stream-csv:<path>:<label>[:<chunk>] — binned from streaming "
        "sketches within a fixed memory budget (every host must see the "
        "same dataset)",
    )
    tn.add_argument("--ckpt-dir", required=True,
                    help="shared checkpoint dir (doubles as auto-resume)")
    tn.add_argument("--partitions", type=int, default=8)
    tn.add_argument("--world-size", type=int, default=1,
                    help="members to wait for before generation 1 forms")
    tn.add_argument("--service-name", default="train")
    tn.add_argument("--num-iterations", type=int, default=100)
    tn.add_argument("--num-leaves", type=int, default=31)
    tn.add_argument("--learning-rate", type=float, default=0.1)
    tn.add_argument("--min-data-in-leaf", type=int, default=20)
    tn.add_argument("--seed", type=int, default=0)
    tn.add_argument("--objective", default="binary")
    tn.add_argument("--boosting-type", default="gbdt")
    tn.add_argument("--growth-policy", default="lossguide")
    tn.add_argument("--checkpoint-every", type=int, default=2)
    tn.add_argument("--heartbeat-s", type=float, default=0.5)
    tn.add_argument("--gen-timeout-s", type=float, default=120.0)
    tn.add_argument("--advertise-host", default="127.0.0.1")
    tn.add_argument("--straggler-factor", type=float, default=3.0)
    tn.add_argument("--straggler-rounds", type=int, default=3)
    tn.add_argument("--evict-stragglers", action="store_true")
    tn.add_argument("--min-world", type=int, default=1)
    tn.add_argument("--resume-from", default=None,
                    help="resume from this checkpoint dir/snapshot "
                    "instead of --ckpt-dir's LATEST")
    tn.add_argument("--status-file", default=None,
                    help="JSON progress/recovery-timing file (atomic "
                    "rewrites; the bench and chaos tests read it)")
    tn.add_argument("--out-model", default=None,
                    help="write the final model string here")
    tn.add_argument(
        "--no-growback", action="store_true",
        help="do not admit re-registered hosts at checkpoint boundaries",
    )
    tn.add_argument(
        "--artifact-dir", default=None,
        help="artifact mode: --ckpt-dir is HOST-LOCAL (every member "
        "writes its own checkpoints); reshard snapshots replicate as "
        "content-addressed artifacts pulled over HTTP from surviving "
        "peers — no shared checkpoint filesystem (docs/artifacts.md)",
    )
    tn.add_argument(
        "--allreduce-port", type=int, default=0,
        help="fix the allreduce listener port (default: ephemeral)",
    )
    tn.add_argument(
        "--advertise-allreduce-port", type=int, default=None,
        help="advertise THIS port on the roster instead of the bound "
        "one — peers dial it, so the member's allreduce link can be "
        "pointed through a chaos proxy or NAT (docs/chaos.md)",
    )
    tn.add_argument(
        "--reduce-mode", choices=("ring", "mesh"), default="ring",
        help="gang allreduce wire pattern: chunked ring reduce-scatter "
        "+ allgather (default) or the legacy full-mesh baseline — "
        "bit-identical results, fewer bytes on the ring",
    )
    tn.add_argument(
        "--tree-parallelism", choices=("data", "voting"), default="data",
        help="histogram exchange: full data-parallel plane (default) or "
        "PV-Tree voting — only the top-2*K candidate features' columns "
        "cross the wire (O(2k) payload on wide data; documented quality "
        "tolerance, docs/gbdt-training.md)",
    )
    tn.add_argument(
        "--top-k", type=int, default=20,
        help="voting-parallel K: each member nominates its local top-K "
        "features; the global top-2K become exact-scan candidates",
    )
    tn.add_argument(
        "--sketch-bits", type=int, default=16,
        help="streaming-binning sketch resolution (buckets = 2^bits "
        "per feature; out-of-core --data specs only)",
    )
    tu = sub.add_parser(
        "tune",
        help="ASHA experiment controller: schedule trials as supervisor "
        "charges, promote the top 1/eta per rung via generation-CAS "
        "records, auto-publish the winner into serving "
        "(mmlspark_tpu/experiments/; docs/experiments.md)",
    )
    tu.add_argument("--registry", required=True)
    tu.add_argument("--experiment", default="exp",
                    help="experiment name (prefixes every registry record)")
    tu.add_argument("--trials", type=int, default=6)
    tu.add_argument(
        "--space", default=None,
        help="search-space JSON: {param: [choices]} or "
        '{param: {"low": .., "high": .., "log"?: true, "int"?: true}} '
        "(default: the stock GBDT space)",
    )
    tu.add_argument("--data", default="synth:512x8:1")
    tu.add_argument("--valid", default="synth:256x8:99",
                    help="held-out eval spec (same grammar as --data)")
    tu.add_argument("--min-iters", type=int, default=2)
    tu.add_argument("--max-iters", type=int, default=8)
    tu.add_argument("--eta", type=int, default=2)
    tu.add_argument("--seed", type=int, default=0)
    tu.add_argument("--lower-is-better", action="store_true")
    tu.add_argument("--workdir", default=None)
    tu.add_argument(
        "--spawn-cmd", default=None,
        help="trial placement template, supervisor semantics: bare "
        "{argv} splices, embedded {argv} substitutes the shell-quoted "
        "command (fleet supervise --spawn-cmd docs)",
    )
    tu.add_argument(
        "--placement", default=None,
        help="trial placement provider, supervisor grammar: 'local', "
        "'ssh:<host>', 'k8s:<image>[@<namespace>]', or a raw wrapper "
        "template (fleet supervise --placement docs)",
    )
    tu.add_argument("--tick-s", type=float, default=0.25)
    tu.add_argument("--heartbeat-s", type=float, default=0.5)
    tu.add_argument("--poll-s", type=float, default=0.25)
    tu.add_argument("--decision-timeout-s", type=float, default=120.0)
    tu.add_argument("--partitions", type=int, default=4)
    tu.add_argument("--max-reschedules", type=int, default=5)
    tu.add_argument(
        "--publish-model", default=None,
        help="serve the winner under this model name via the "
        "epoch-fenced Publisher path (load -> warm -> swap on every "
        "roster worker); omit to only CAS the winner record",
    )
    tu.add_argument("--publish-service", default="serving")
    tu.add_argument("--publish-epoch", type=int, default=None)
    tu.add_argument("--status-file", default=None,
                    help="atomic JSON status (the invariant checker "
                    "joins these; docs/experiments.md)")
    tu.add_argument("--deadline-s", type=float, default=600.0)
    tl = sub.add_parser(
        "trial",
        help="one ASHA trial charge (spawned by fleet tune; trains "
        "through rung boundaries, CAS-reports metrics, self-reaps on "
        "demotion)",
    )
    tl.add_argument("--registry", required=True)
    tl.add_argument("--experiment", required=True)
    tl.add_argument("--trial", required=True)
    tl.add_argument("--params", required=True,
                    help="sampled hyperparameter JSON (controller-built)")
    tl.add_argument("--data", required=True)
    tl.add_argument("--valid", required=True)
    tl.add_argument("--workdir", required=True)
    tl.add_argument("--min-iters", type=int, default=2)
    tl.add_argument("--max-iters", type=int, default=8)
    tl.add_argument("--eta", type=int, default=2)
    tl.add_argument("--seed", type=int, default=0)
    tl.add_argument("--lower-is-better", action="store_true")
    tl.add_argument("--heartbeat-s", type=float, default=0.5)
    tl.add_argument("--poll-s", type=float, default=0.25)
    tl.add_argument("--decision-timeout-s", type=float, default=120.0)
    tl.add_argument("--partitions", type=int, default=4)
    tl.add_argument("--status-file", default=None)
    t = sub.add_parser(
        "top", help="scrape /metrics across the fleet, print a summary"
    )
    t.add_argument("--registry", default=None)
    t.add_argument("--gateway", default=None)
    t.add_argument("--service-name", default="serving")
    t.add_argument(
        "--worker", action="append", default=[],
        help="explicit worker base URL (repeatable; adds to the roster)",
    )
    t.add_argument(
        "--watch", type=float, default=0.0,
        help="refresh every N seconds (0 = print once and exit)",
    )
    def add_trace_endpoint_flags(p) -> None:
        p.add_argument("--registry", default=None)
        p.add_argument("--gateway", default=None)
        p.add_argument("--service-name", default="serving")
        p.add_argument(
            "--worker", action="append", default=[],
            help="explicit worker base URL (repeatable)",
        )

    tr = sub.add_parser(
        "trace",
        help="fetch one trace id across the fleet's span buffers and "
        "render the cross-process tree",
    )
    tr.add_argument("trace_id")
    add_trace_endpoint_flags(tr)
    trs = sub.add_parser(
        "traces",
        help="rank recent traces by latency (histogram-bucket exemplars) "
        "and render the slowest trees",
    )
    trs.add_argument(
        "--slowest", type=int, default=5, metavar="N",
        help="how many traces to render, worst first",
    )
    add_trace_endpoint_flags(trs)
    pf = sub.add_parser(
        "profile",
        help="scrape every /profile ingress twice, N seconds apart, and "
        "merge the sampling window into one fleet-wide collapsed-stack "
        "flame view (stall forensics: docs/observability.md)",
    )
    pf.add_argument(
        "url", nargs="?", default=None,
        help="one base URL to profile directly (any /profile ingress); "
        "omit and pass --registry/--gateway to sweep the fleet",
    )
    pf.add_argument(
        "--seconds", type=float, default=5.0,
        help="sampling window between the two scrapes",
    )
    add_trace_endpoint_flags(pf)
    ch = sub.add_parser(
        "chaos",
        help="drive a timed hostile-wire scenario against a live fleet: "
        "seeded TCP chaos proxies + process signals + the invariant "
        "checker (mmlspark_tpu/chaos/; docs/chaos.md)",
    )
    ch.add_argument(
        "--scenario", required=True,
        help="scenario JSON (inline or a file path): seed + timed steps "
        "(rules / clear / signal / check / sleep / mark)",
    )
    ch.add_argument(
        "--proxy", action="append", default=[],
        metavar="NAME=LISTEN_PORT:TARGET_HOST:TARGET_PORT",
        help="one chaos proxy the scenario's rules/clear steps address "
        "by NAME (repeatable); point the fleet link at LISTEN_PORT",
    )
    ch.add_argument(
        "--pid", action="append", default=[], metavar="NAME=PID",
        help="one process the scenario's signal steps address by NAME "
        "(repeatable)",
    )
    ch.add_argument("--gateway", default=None,
                    help="gateway base URL for the check step's invariants")
    ch.add_argument("--registry", default=None,
                    help="registry base URL (resolves worker /metrics "
                    "endpoints for the invariant checker)")
    ch.add_argument("--service-name", default="serving")
    ch.add_argument("--seed", type=int, default=None,
                    help="override the scenario's seed")
    ch.add_argument(
        "--status-file", action="append", default=[], metavar="PATH",
        help="one elastic-trainer status JSON for the check step's "
        "single_writer law (repeatable; docs/chaos.md)",
    )
    m = sub.add_parser(
        "model",
        help="model lifecycle control against a worker or gateway "
        "(GET/POST /models control plane)",
    )
    m.add_argument(
        "action", choices=["list", "load", "swap", "unload", "pin", "unpin"],
    )
    m.add_argument(
        "--url", required=True,
        help="worker base URL (or gateway: the op routes to one backend "
        "advertising the model)",
    )
    m.add_argument("--name", default=None, help="model name")
    m.add_argument("--spec", default=None, help="model spec (load)")
    m.add_argument("--version", type=int, default=None)
    m.add_argument(
        "--pin", action="store_true",
        help="load: pin the new version against eviction",
    )
    m.add_argument(
        "--no-wait", action="store_true",
        help="load: return 202 immediately, load in the background",
    )
    m.add_argument(
        "--activate", default=None, choices=["auto", "always", "never"],
        help="load: alias policy (default auto: first version serves, "
        "later versions wait for an explicit swap)",
    )
    args = ap.parse_args(argv)
    if args.fault_plan:
        from mmlspark_tpu.core.faults import FaultPlan

        FaultPlan.from_spec(args.fault_plan).install()
        print(f"fleet: fault plan armed ({args.fault_plan})", flush=True)
    if args.role == "chaos":
        from mmlspark_tpu.chaos.conductor import run_chaos_cli

        raise SystemExit(run_chaos_cli(
            args.scenario, args.proxy, args.pid,
            gateway_url=args.gateway, registry_url=args.registry,
            service_name=args.service_name, seed=args.seed,
            status_files=args.status_file,
        ))
    if args.role == "model":
        raise SystemExit(run_model_verb(
            args.action, args.url, name=args.name, spec=args.spec,
            version=args.version, pin=args.pin, no_wait=args.no_wait,
            activate=args.activate,
        ))
    if args.role == "trace":
        print(run_trace(
            args.trace_id, registry_url=args.registry,
            gateway_url=args.gateway, worker_urls=args.worker or None,
            service_name=args.service_name,
        ), flush=True)
        return
    if args.role == "traces":
        print(run_traces_slowest(
            args.slowest, registry_url=args.registry,
            gateway_url=args.gateway, worker_urls=args.worker or None,
            service_name=args.service_name,
        ), flush=True)
        return
    if args.role == "profile":
        urls = list(args.worker or ())
        if args.url:
            urls.append(args.url)
        print(run_profile(
            args.seconds, registry_url=args.registry,
            gateway_url=args.gateway, worker_urls=urls or None,
            service_name=args.service_name,
        ), flush=True)
        return
    if args.role == "top":
        while True:
            print(
                run_top(
                    registry_url=args.registry, gateway_url=args.gateway,
                    worker_urls=args.worker or None,
                    service_name=args.service_name,
                ),
                flush=True,
            )
            if args.watch <= 0:
                break
            time.sleep(args.watch)
    elif args.role == "train":
        _install_forensics()
        run_train(
            args.registry, args.name, args.data, args.ckpt_dir,
            partitions=args.partitions, world_size=args.world_size,
            service_name=args.service_name,
            num_iterations=args.num_iterations,
            num_leaves=args.num_leaves, learning_rate=args.learning_rate,
            min_data_in_leaf=args.min_data_in_leaf, seed=args.seed,
            objective=args.objective, boosting_type=args.boosting_type,
            growth_policy=args.growth_policy,
            checkpoint_every=args.checkpoint_every,
            heartbeat_s=args.heartbeat_s,
            gen_timeout_s=args.gen_timeout_s,
            advertise_host=args.advertise_host,
            straggler_factor=args.straggler_factor,
            straggler_rounds=args.straggler_rounds,
            evict_stragglers=args.evict_stragglers,
            min_world=args.min_world, resume_from=args.resume_from,
            status_file=args.status_file, out_model=args.out_model,
            allow_growback=not args.no_growback,
            artifact_dir=args.artifact_dir,
            allreduce_port=args.allreduce_port,
            advertise_allreduce_port=args.advertise_allreduce_port,
            reduce_mode=args.reduce_mode,
            tree_parallelism=args.tree_parallelism,
            top_k=args.top_k,
            sketch_bits=args.sketch_bits,
        )
    elif args.role == "tune":
        from mmlspark_tpu.experiments.controller import (
            ExperimentController,
            space_from_json,
        )

        ctrl = ExperimentController(
            args.registry, args.experiment, n_trials=args.trials,
            space=(
                space_from_json(json.loads(args.space))
                if args.space else None
            ),
            data=args.data, valid=args.valid,
            min_iters=args.min_iters, max_iters=args.max_iters,
            eta=args.eta, seed=args.seed,
            higher_is_better=not args.lower_is_better,
            workdir=args.workdir, spawn_cmd=args.spawn_cmd,
            placement=args.placement,
            tick_s=args.tick_s, heartbeat_s=args.heartbeat_s,
            poll_s=args.poll_s,
            decision_timeout_s=args.decision_timeout_s,
            partitions=args.partitions,
            max_reschedules=args.max_reschedules,
            publish_model=args.publish_model,
            publish_service=args.publish_service,
            publish_epoch=args.publish_epoch,
            status_file=args.status_file, deadline_s=args.deadline_s,
        )
        try:
            ctrl.run()
        finally:
            ctrl.close()
    elif args.role == "trial":
        from mmlspark_tpu.experiments.trial import run_trial

        _install_forensics()
        raise SystemExit(run_trial(
            args.registry, args.experiment, args.trial,
            json.loads(args.params), args.data, args.valid, args.workdir,
            min_iters=args.min_iters, max_iters=args.max_iters,
            eta=args.eta, seed=args.seed,
            higher_is_better=not args.lower_is_better,
            heartbeat_s=args.heartbeat_s, poll_s=args.poll_s,
            decision_timeout_s=args.decision_timeout_s,
            partitions=args.partitions, status_file=args.status_file,
        ))
    elif args.role == "registry":
        _install_forensics()
        reg = run_registry(
            args.host, args.port, args.ttl_s, peers=args.peer or None,
            reconcile_s=args.reconcile_s,
        )
        _serve_forever([reg])
    elif args.role == "worker":
        _install_forensics()
        srv, q, stop = run_worker(
            args.registry, args.model, args.host, args.port,
            args.service_name, args.heartbeat_s, args.advertise_host,
            extra_models=args.load,
            hbm_budget_bytes=args.hbm_budget_bytes,
            default_deadline_ms=args.default_deadline_ms,
            slo_targets=args.slo_targets,
            slo_availability=args.slo_availability,
            slo_p99_ms=args.slo_p99_ms or None,
            admission=not args.no_admission,
            admission_initial_limit=args.admission_initial_limit,
            admission_min_target_ms=args.admission_min_target_ms,
            artifact_dir=args.artifact_dir,
            reactors=args.reactors,
            header_deadline_s=args.header_deadline_s or None,
        )
        # SIGTERM with --drain-s: stop.drain() deregisters, pauses
        # accepting and waits out in-flight work; then q/srv stop as
        # usual — the graceful-drain lifecycle (docs/chaos.md)
        _serve_forever([stop, q, srv], drain_s=args.drain_s)
    elif args.role == "supervise":
        if not args.worker and not args.train:
            ap.error("supervise needs at least one --worker or --train")
        sup = run_supervise(
            args.registry, args.worker, service_name=args.service_name,
            trains=args.train,
            probe_s=args.probe_s, wedge_after=args.wedge_after,
            backoff_s=args.backoff_s, backoff_max_s=args.backoff_max_s,
            host=args.host, port=args.port,
            autoscale=args.autoscale, min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            worker_template=args.worker_template,
            scale_out_cooldown_s=args.scale_out_cooldown_s,
            scale_in_cooldown_s=args.scale_in_cooldown_s,
            idle_after_s=args.idle_after_s,
            util_threshold=args.util_threshold,
            gateway_url=args.gateway,
            spawn_cmd=args.spawn_cmd,
            placement=args.placement,
        )
        _serve_forever([sup])
    elif args.role == "online":
        _install_forensics()
        _stream, _loop, stopper = run_online(
            registry_url=args.registry, model=args.model, host=args.host,
            port=args.port, service_name=args.service_name,
            worker_urls=args.worker_url or None,
            snapshot_dir=args.snapshot_dir,
            publish_every_s=args.publish_every_s,
            freshness_slo_ms=args.freshness_slo_ms,
            heartbeat_s=args.heartbeat_s,
            advertise_host=args.advertise_host, num_bits=args.num_bits,
            loss=args.loss, lr=args.lr, batch=args.batch,
            label_col=args.label_col, features_col=args.features_col,
            text_col=args.text_col, distributed=args.distributed,
            artifact_dir=args.artifact_dir,
            publish_epoch=args.publish_epoch,
            replicas=args.replicas,
        )
        _serve_forever([stopper])
    else:
        _install_forensics()
        gw = run_gateway(
            args.registry, args.host, args.port, args.service_name,
            slo_targets=args.slo_targets,
            slo_availability=args.slo_availability,
            slo_p99_ms=args.slo_p99_ms or None,
            hedge_ms=args.hedge_ms,
            retry_budget_ratio=args.retry_budget_ratio,
            breaker_cooldown_s=args.breaker_cooldown_s,
            reactors=args.reactors,
            num_dispatchers=args.dispatchers,
            header_deadline_s=args.header_deadline_s or None,
        )
        _serve_forever([gw], drain_s=args.drain_s)


if __name__ == "__main__":
    main()
