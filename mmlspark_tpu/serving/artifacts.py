"""Content-addressed artifact plane: hash-verified, resumable replication
of checkpoints and model snapshots over HTTP — no shared filesystem.

The last piece of the detect -> react loop (PRs 1/5/10) that still
silently depended on one disk: gang checkpoints resumed from a shared
``--ckpt-dir`` and the online Publisher shipped ``vw:`` snapshots through
a shared ``snapshot_dir``. This module replaces that single point of
failure with the TensorFlow-style durable-artifact primitive (PAPERS:
1605.08695): producers ``put()`` a file or directory into a local
:class:`ArtifactStore`, advertise ``name@sha256`` through their
DriverRegistry heartbeats, and serve ranged ``GET /artifacts/<digest>``
off their existing :class:`~mmlspark_tpu.serving.server.WorkerServer`
ingress; consumers ``fetch()`` by digest from ANY advertising peer.

Transfer contract (docs/artifacts.md):

- **hash-verified** — every completed transfer (and every local cache
  hit) is sha256-verified against the digest it was addressed by; a
  mismatch can never be served or consumed.
- **resumable** — a transfer that dies mid-stream leaves its partial
  bytes on disk; the next attempt resumes with ``Range: bytes=<off>-``
  from the same or any other peer (the bytes are content-addressed, so
  peers are interchangeable mid-file).
- **failover** — peers are tried in order with
  :func:`~mmlspark_tpu.core.utils.retry_with_backoff` pacing between
  rounds; one dead peer costs one attempt, not the fetch.
- **quarantine** — a blob that fails verification is moved aside (never
  served, excluded from advertisement) and the fetch continues on the
  remaining peers; a later good copy clears the quarantine.
- **bounded** — zero-length and oversized artifacts are rejected before
  any bytes land; the store itself is LRU-bounded (``max_bytes``) and
  never evicts pinned or mid-pull artifacts.

Since PR 20 the plane also replicates the other way: a producer that is
about to become load-bearing state (a Publisher snapshot, a reshard
checkpoint, an experiment winner) PUSHES its blob to N replica holders
over ``PUT /artifacts/<digest>`` (windowed ``Content-Range`` uploads
with the holder's recorded offset as the resume currency) and only
acks — publishes, commits the generation — once a quorum of holders has
verified and installed the digest (**replication-before-ack**). A
SIGKILLed source host then never strands the only copy: consumers pull
by digest from any surviving holder through the fetch path above.

Fault points ``artifact.put`` (a refused store), ``artifact.fetch`` (one
transfer attempt dies / stalls), ``artifact.verify`` (a forced
verification failure — drives the quarantine + re-fetch-elsewhere path
without corrupting anything), ``artifact.push`` (one push attempt to one
holder dies) and ``artifact.replicate`` (the whole replication round
refused) make all of the above first-class chaos.
"""

from __future__ import annotations

import hashlib
import http.client
import json
import os
import re
import shutil
import threading
import time
import urllib.parse
from dataclasses import dataclass
from typing import Any, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults

_M_PUTS = obs.counter(
    "mmlspark_artifact_puts_total",
    "Artifacts stored locally (producer side)",
)
_M_FETCHES = obs.counter(
    "mmlspark_artifact_fetches_total",
    "Artifact fetches by outcome (ok / cached / failed)",
    labels=("outcome",),
)
_M_FETCH_S = obs.histogram(
    "mmlspark_artifact_fetch_seconds",
    "Wall time of one successful artifact fetch (all peers, all resumes)",
)
_M_BYTES = obs.counter(
    "mmlspark_artifact_bytes_total",
    "Artifact payload bytes moved, by direction (sent / received)",
    labels=("direction",),
)
_M_RESUMES = obs.counter(
    "mmlspark_artifact_resumes_total",
    "Transfers resumed from a partial file via a Range request",
)
_M_PUSHES = obs.counter(
    "mmlspark_artifacts_pushes_total",
    "Push attempts to one replica holder, by outcome (ok / resumed / failed)",
    labels=("outcome",),
)
_M_REPLICAS = obs.counter(
    "mmlspark_artifacts_replicas_total",
    "Replica confirmations by outcome (confirmed / failed / below_quorum)",
    labels=("outcome",),
)
_M_PULL_RESUMES = obs.counter(
    "mmlspark_artifacts_pull_resumes_total",
    "Pulls resumed from a partial file via a Range request "
    "(successor of mmlspark_artifact_resumes_total, kept in lockstep)",
)
_M_VERIFY_FAIL = obs.counter(
    "mmlspark_artifact_verify_failures_total",
    "Completed transfers or cache hits whose sha256 did not match",
)
_M_QUARANTINES = obs.counter(
    "mmlspark_artifact_quarantines_total",
    "Blobs moved to quarantine after failing verification",
)
_M_EVICTIONS = obs.counter(
    "mmlspark_artifact_evictions_total",
    "Artifacts LRU-evicted to honor the store's byte budget",
)
_M_STORE_BYTES = obs.gauge(
    "mmlspark_artifact_store_bytes",
    "Resident artifact-blob bytes in the local store",
)
_M_STORE_COUNT = obs.gauge(
    "mmlspark_artifact_store_count",
    "Artifacts resident in the local store",
)

_DIGEST_RE = re.compile(r"^[0-9a-f]{64}$")
_CHUNK = 1 << 16
# a directory artifact is packed into one self-describing blob: a magic
# header line, then per file (sorted relpath order — deterministic bytes
# for identical trees) a JSON header line followed by the raw contents
_DIR_MAGIC = b'{"mmlspark_artifact_dir": 1}\n'


class ArtifactError(Exception):
    """Base class for artifact-plane failures."""


class ArtifactVerifyError(ArtifactError):
    """A transfer completed but its bytes do not hash to the digest."""


class ArtifactFetchError(ArtifactError):
    """Every peer was exhausted without a verified copy landing."""


class ArtifactPushError(ArtifactError):
    """One push attempt to one replica holder failed for good."""


class ArtifactReplicationError(ArtifactError):
    """Fewer holders confirmed the digest than the required quorum —
    replication-before-ack raises here instead of false-acking."""


@dataclass
class ArtifactRef:
    """One stored artifact: its advertised identity and local home."""

    name: str
    digest: str
    size: int
    path: str = ""

    @property
    def spec(self) -> str:
        return f"{self.name}@{self.digest}"


def parse_ref(ref: str) -> tuple:
    """``name@sha256hex`` -> (name, digest); raises on malformed refs."""
    name, _, digest = ref.rpartition("@")
    if not name or not _DIGEST_RE.match(digest):
        raise ValueError(f"malformed artifact ref {ref!r} (want name@sha256)")
    return name, digest


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(_CHUNK)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


# -- directory packing ---------------------------------------------------------


def pack_dir(src_dir: str, dst_path: str) -> None:
    """Pack a directory tree into one blob (deterministic for identical
    trees: files walk in sorted relative-path order, headers carry only
    path + size — no mtimes, owners or modes)."""
    files = []
    for root, dirs, names in os.walk(src_dir):
        dirs.sort()
        for n in sorted(names):
            full = os.path.join(root, n)
            files.append((os.path.relpath(full, src_dir), full))
    files.sort()
    with open(dst_path, "wb") as out:
        out.write(_DIR_MAGIC)
        for rel, full in files:
            size = os.path.getsize(full)
            out.write(
                json.dumps({"p": rel.replace(os.sep, "/"), "n": size})
                .encode() + b"\n"
            )
            with open(full, "rb") as f:
                shutil.copyfileobj(f, out, _CHUNK)


def is_dir_blob(path: str) -> bool:
    with open(path, "rb") as f:
        return f.read(len(_DIR_MAGIC)) == _DIR_MAGIC


def unpack_dir(blob_path: str, dst_dir: str) -> str:
    """Unpack a :func:`pack_dir` blob into ``dst_dir`` (built in a tmp
    sibling, published with one atomic rename — a concurrent reader never
    sees a half-written tree). Returns ``dst_dir``."""
    if os.path.isdir(dst_dir):
        return dst_dir
    tmp = dst_dir + f".tmp-{os.getpid()}"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    with open(blob_path, "rb") as f:
        if f.readline() != _DIR_MAGIC.rstrip(b"\n") + b"\n":
            shutil.rmtree(tmp, ignore_errors=True)
            raise ArtifactError(f"{blob_path} is not a directory artifact")
        while True:
            head = f.readline()
            if not head:
                break
            meta = json.loads(head)
            rel = meta["p"]
            if rel.startswith("/") or ".." in rel.split("/"):
                shutil.rmtree(tmp, ignore_errors=True)
                raise ArtifactError(f"unsafe path {rel!r} in artifact")
            out_path = os.path.join(tmp, *rel.split("/"))
            os.makedirs(os.path.dirname(out_path), exist_ok=True)
            remaining = int(meta["n"])
            with open(out_path, "wb") as out:
                while remaining:
                    b = f.read(min(_CHUNK, remaining))
                    if not b:
                        shutil.rmtree(tmp, ignore_errors=True)
                        raise ArtifactError(
                            f"truncated directory artifact {blob_path}"
                        )
                    out.write(b)
                    remaining -= len(b)
    try:
        os.rename(tmp, dst_dir)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)  # a racer won
    return dst_dir


# -- the store -----------------------------------------------------------------


class ArtifactStore:
    """Content-addressed local blob store with an LRU byte budget.

    Layout: ``<root>/blobs/<digest>`` (the bytes), ``<root>/meta/<digest>
    .json`` (name + size, so the index survives a restart), ``<root>/
    partial/<digest>.part`` (resumable in-flight downloads), ``<root>/
    quarantine/`` (failed-verification bytes, kept for forensics).
    """

    def __init__(
        self,
        root: str,
        max_bytes: Optional[int] = None,
        max_artifact_bytes: int = 4 << 30,
        serve_window: int = 16 << 20,
    ):
        """``serve_window``: the most bytes one ``GET /artifacts/<d>``
        answers (the rest comes as 206 windows the client chains with
        Range requests) — the handler runs inline on the ingress event
        loop, and a multi-GB read there would stall health probes and
        traffic for the whole transfer."""
        self.root = root
        self.max_bytes = max_bytes
        self.max_artifact_bytes = int(max_artifact_bytes)
        self.serve_window = max(1, int(serve_window))
        self._lock = threading.Lock()
        # one in-flight fetch per digest per process: concurrent fetches
        # sharing partial/<digest>.part would interleave appended ranges
        # and quarantine good bytes; the loser of the race gets a cache
        # hit instead
        self._fetch_locks: dict = {}
        # one in-flight PUSH per digest per process on the receiving
        # side: two pushers interleaving appends into the same partial
        # would corrupt both transfers
        self._push_locks: dict = {}
        self._index: dict[str, ArtifactRef] = {}
        self._last_used: dict[str, float] = {}
        self._pinned: set = set()
        self._active: dict[str, int] = {}   # digest -> open serves/pulls
        self._quarantined: set = set()
        for d in ("blobs", "meta", "partial", "quarantine", "unpacked"):
            os.makedirs(os.path.join(root, d), exist_ok=True)
        # rebuild the index from disk: artifacts survive a process restart
        for fn in sorted(os.listdir(os.path.join(root, "meta"))):
            if not fn.endswith(".json"):
                continue
            digest = fn[:-len(".json")]
            blob = self._blob_path(digest)
            if not os.path.exists(blob):
                continue
            try:
                with open(os.path.join(root, "meta", fn)) as f:
                    meta = json.load(f)
                self._index[digest] = ArtifactRef(
                    name=meta.get("name", digest[:12]), digest=digest,
                    size=int(meta.get("size", os.path.getsize(blob))),
                    path=blob,
                )
                self._last_used[digest] = os.path.getmtime(blob)
            except (OSError, ValueError):
                continue
        self._export_locked()

    # -- internals ------------------------------------------------------------

    def _blob_path(self, digest: str) -> str:
        return os.path.join(self.root, "blobs", digest)

    def _export_locked(self) -> None:
        _M_STORE_BYTES.set(sum(r.size for r in self._index.values()))
        _M_STORE_COUNT.set(len(self._index))

    def _evict_locked(self) -> None:
        if self.max_bytes is None:
            return
        total = sum(r.size for r in self._index.values())
        for digest in sorted(self._last_used, key=self._last_used.get):
            if total <= self.max_bytes:
                break
            if digest in self._pinned or self._active.get(digest, 0) > 0:
                continue  # never evict pinned or mid-pull artifacts
            ref = self._index.pop(digest, None)
            if ref is None:
                continue
            self._last_used.pop(digest, None)
            for p in (self._blob_path(digest),
                      os.path.join(self.root, "meta", digest + ".json")):
                try:
                    os.remove(p)
                except OSError:
                    pass
            total -= ref.size
            _M_EVICTIONS.inc()

    def _install_locked(self, tmp_blob: str, digest: str, name: str) -> ArtifactRef:
        blob = self._blob_path(digest)
        size = os.path.getsize(tmp_blob)
        os.replace(tmp_blob, blob)
        with open(os.path.join(self.root, "meta", digest + ".json"), "w") as f:
            json.dump({"name": name, "size": size}, f)
        ref = ArtifactRef(name=name, digest=digest, size=size, path=blob)
        self._index[digest] = ref
        self._last_used[digest] = time.time()
        self._quarantined.discard(digest)  # a good copy clears the flag
        self._evict_locked()
        self._export_locked()
        return ref

    # -- producer side --------------------------------------------------------

    def put(self, path: str, name: Optional[str] = None) -> ArtifactRef:
        """Store a file or directory as a content-addressed artifact and
        return its :class:`ArtifactRef`. Directories are packed into one
        deterministic blob (:func:`pack_dir`). Fault point
        ``artifact.put``: an injected error is a refused push."""
        faults.inject("artifact.put", context={"path": path})
        name = name or os.path.basename(path.rstrip(os.sep))
        with obs.span("artifact.put", attrs={"name": name}):
            return self._put(path, name)

    def _put(self, path: str, name: str) -> ArtifactRef:
        tmp = os.path.join(
            self.root, "partial", f"put-{os.getpid()}-{threading.get_ident()}"
        )
        try:
            if os.path.isdir(path):
                pack_dir(path, tmp)
            else:
                shutil.copyfile(path, tmp)
            size = os.path.getsize(tmp)
            if size == 0:
                raise ArtifactError(f"refusing zero-length artifact {path!r}")
            if size > self.max_artifact_bytes:
                raise ArtifactError(
                    f"artifact {path!r} is {size} bytes > max "
                    f"{self.max_artifact_bytes}"
                )
            digest = sha256_file(tmp)
            with self._lock:
                if digest in self._index:
                    os.remove(tmp)
                    self._last_used[digest] = time.time()
                    return self._index[digest]
                ref = self._install_locked(tmp, digest, name)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        _M_PUTS.inc()
        return ref

    def put_bytes(self, data: bytes, name: str) -> ArtifactRef:
        tmp = os.path.join(
            self.root, "partial",
            f"putb-{os.getpid()}-{threading.get_ident()}",
        )
        with open(tmp, "wb") as f:
            f.write(data)
        try:
            return self.put(tmp, name=name)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)

    # -- lookup / lifecycle ---------------------------------------------------

    def has(self, digest: str) -> bool:
        with self._lock:
            return digest in self._index and digest not in self._quarantined

    def path(self, digest: str) -> Optional[str]:
        with self._lock:
            ref = self._index.get(digest)
            if ref is None or digest in self._quarantined:
                return None
            self._last_used[digest] = time.time()
            return ref.path

    def refs(self) -> list:
        """``name@digest`` strings for everything advertisable (resident,
        not quarantined) — the heartbeat advertisement payload."""
        with self._lock:
            return sorted(
                r.spec for d, r in self._index.items()
                if d not in self._quarantined
            )

    def pin(self, digest: str) -> None:
        with self._lock:
            self._pinned.add(digest)

    def unpin(self, digest: str) -> None:
        with self._lock:
            self._pinned.discard(digest)

    def removable(self, digest: str) -> bool:
        """May this artifact be dropped right now? False while pinned or
        mid-pull (an open ranged read / in-flight fetch holds a count) —
        the Publisher GC's safety check."""
        with self._lock:
            return (
                digest not in self._pinned
                and self._active.get(digest, 0) == 0
            )

    def remove(self, digest: str, force: bool = False) -> bool:
        """Unadvertise + delete an artifact; refuses (returns False)
        while pinned or mid-pull unless ``force``."""
        with self._lock:
            if not force and (
                digest in self._pinned or self._active.get(digest, 0) > 0
            ):
                return False
            ref = self._index.pop(digest, None)
            self._last_used.pop(digest, None)
            self._pinned.discard(digest)
            if ref is None:
                return False
            for p in (self._blob_path(digest),
                      os.path.join(self.root, "meta", digest + ".json")):
                try:
                    os.remove(p)
                except OSError:
                    pass
            self._export_locked()
            return True

    def quarantine(self, digest: str, reason: str = "") -> None:
        """Never serve this digest's local bytes again: move the blob to
        the quarantine dir (kept for forensics) and drop it from the
        index. A later verified fetch clears the flag."""
        with self._lock:
            self._quarantined.add(digest)
            ref = self._index.pop(digest, None)
            self._last_used.pop(digest, None)
            if ref is not None:
                try:
                    os.replace(
                        ref.path,
                        os.path.join(self.root, "quarantine", digest),
                    )
                except OSError:
                    pass
                try:
                    os.remove(
                        os.path.join(self.root, "meta", digest + ".json")
                    )
                except OSError:
                    pass
            self._export_locked()
        _M_QUARANTINES.inc()

    def verify(self, digest: str) -> bool:
        """Re-hash a resident blob against its digest; quarantines on
        mismatch. Fault point ``artifact.verify``: a truthy payload
        forces the failure verdict (chaos for the quarantine path)."""
        p = self.path(digest)
        if p is None:
            return False
        forced = faults.inject("artifact.verify", context={"digest": digest})
        ok = not forced and sha256_file(p) == digest
        if not ok:
            _M_VERIFY_FAIL.inc()
            self.quarantine(digest, reason="verify failed")
        return ok

    def unpack(self, digest: str, dst_dir: Optional[str] = None) -> str:
        """Unpack a directory artifact; defaults to a content-addressed
        dir under the store so repeated unpacks are free."""
        p = self.path(digest)
        if p is None:
            raise ArtifactError(f"artifact {digest} not in store")
        dst = dst_dir or os.path.join(self.root, "unpacked", digest)
        return unpack_dir(p, dst)

    # -- HTTP serving (called inline by WorkerServer's ingress) ---------------

    def handle_http(
        self,
        path_only: str,
        headers: dict,
        method: str = "GET",
        body: bytes = b"",
    ) -> tuple:
        """``GET /artifacts`` -> advertisement JSON; ``GET /artifacts/
        <digest>`` -> the blob (206 + Content-Range under a ``Range:
        bytes=<start>-`` header); ``PUT /artifacts/<digest>`` -> accept a
        pushed replica window (:meth:`_handle_push`). Returns ``(code,
        body, headers)``."""
        if method in ("PUT", "POST"):
            if path_only.rstrip("/") == "/artifacts":
                return 405, b"push addresses a digest", {}
            return self._handle_push(
                path_only[len("/artifacts/"):], headers, body
            )
        if path_only.rstrip("/") == "/artifacts":
            with self._lock:
                body = json.dumps({
                    "artifacts": [
                        {"name": r.name, "digest": d, "size": r.size}
                        for d, r in sorted(self._index.items())
                        if d not in self._quarantined
                    ],
                }).encode()
            return 200, body, {"Content-Type": "application/json"}
        digest = path_only[len("/artifacts/"):]
        with self._lock:
            ref = self._index.get(digest)
            if ref is None or digest in self._quarantined:
                return 404, b"unknown artifact", {}
            self._last_used[digest] = time.time()
            self._active[digest] = self._active.get(digest, 0) + 1
        try:
            start = 0
            rng = headers.get("range", "")
            m = re.match(r"bytes=(\d+)-$", rng) if rng else None
            if m:
                start = int(m.group(1))
            if start >= ref.size:
                return 416, b"range beyond artifact", {
                    "Content-Range": f"bytes */{ref.size}",
                }
            # serve at most one window per request: the handler runs
            # inline on the ingress event loop, so a multi-GB blob goes
            # out as a chain of 206 windows the client follows with
            # Range requests — other traffic interleaves between them
            end = min(ref.size, start + self.serve_window)
            with open(ref.path, "rb") as f:
                f.seek(start)
                body = f.read(end - start)
            _M_BYTES.labels(direction="sent").inc(len(body))
            hdrs = {
                "Content-Type": "application/octet-stream",
                "X-Artifact-Sha256": digest,
                "X-Artifact-Size": str(ref.size),
            }
            if start or end < ref.size:
                hdrs["Content-Range"] = f"bytes {start}-{end - 1}/{ref.size}"
                return 206, body, hdrs
            return 200, body, hdrs
        except OSError as e:
            return 404, f"artifact read failed: {e}".encode(), {}
        finally:
            with self._lock:
                self._active[digest] = max(0, self._active.get(digest, 1) - 1)
                if not self._active[digest]:
                    del self._active[digest]

    # -- push receiving (replica-holder side) ---------------------------------

    def _handle_push(self, digest: str, headers: dict, body: bytes) -> tuple:
        """Accept one pushed window of ``digest``. Protocol (the server
        analogue of :meth:`push_to` — docs/robustness.md "Artifact
        plane"):

        - ``Content-Range: bytes */<total>`` + empty body is a PROBE:
          answers 308 with ``X-Artifact-Offset: <recorded offset>`` so a
          pusher resumes exactly where the last push died (200 if the
          digest is already installed — pushes are idempotent).
        - ``Content-Range: bytes <s>-<e>/<total>`` + body appends a
          window; a start that disagrees with the recorded offset gets
          409 + the offset (the pusher resyncs — this, not trust, is how
          a truncated push resumes). 202 + offset while incomplete.
        - On the final window the whole partial is sha256-verified
          BEFORE install: a flipped byte quarantines the bytes and
          answers 422 — a corrupt replica can never be installed, so it
          can never count toward a replication quorum.
        """
        if not _DIGEST_RE.match(digest):
            return 400, b"malformed digest", {}
        m = re.match(
            r"bytes (?:(\d+)-(\d+)|\*)/(\d+)$",
            headers.get("content-range", ""),
        )
        if m is None:
            return 400, (
                b"push needs Content-Range: bytes <s>-<e>/<total> "
                b"(or bytes */<total> to probe)"
            ), {}
        total = int(m.group(3))
        if total <= 0:
            return 400, b"refusing zero-length artifact", {}
        if total > self.max_artifact_bytes:
            return 413, (
                f"artifact is {total} bytes > max "
                f"{self.max_artifact_bytes}".encode()
            ), {}
        name = headers.get("x-artifact-name") or digest[:12]
        with self._lock:
            plock = self._push_locks.setdefault(digest, threading.Lock())
        with plock:
            if self.has(digest):
                return 200, b"already stored", {
                    "X-Artifact-Offset": str(total),
                }
            part = os.path.join(self.root, "partial", digest + ".push")
            have = os.path.getsize(part) if os.path.exists(part) else 0
            if m.group(1) is None:
                return 308, b"", {"X-Artifact-Offset": str(have)}
            start = int(m.group(1))
            if start != have:
                return 409, b"offset mismatch", {
                    "X-Artifact-Offset": str(have),
                }
            if len(body) != int(m.group(2)) - start + 1:
                return 400, b"body length disagrees with Content-Range", {
                    "X-Artifact-Offset": str(have),
                }
            if have + len(body) > total:
                try:
                    os.remove(part)
                except OSError:
                    pass
                return 409, b"overshoot, restarting", {
                    "X-Artifact-Offset": "0",
                }
            with open(part, "ab" if have else "wb") as out:
                out.write(body)
            have += len(body)
            _M_BYTES.labels(direction="received").inc(len(body))
            if have < total:
                return 202, b"", {"X-Artifact-Offset": str(have)}
            # complete: verify BEFORE install — a flipped byte on the
            # wire must never become a servable (quorum-countable) copy
            if sha256_file(part) != digest:
                _M_VERIFY_FAIL.inc()
                _M_QUARANTINES.inc()
                try:
                    os.replace(part, os.path.join(
                        self.root, "quarantine", digest + ".bad",
                    ))
                except OSError:
                    pass
                return 422, b"pushed bytes do not hash to the digest", {
                    "X-Artifact-Offset": "0",
                }
            with self._lock:
                if digest in self._index:
                    os.remove(part)
                    self._quarantined.discard(digest)
                else:
                    self._install_locked(part, digest, name)
            return 201, b"", {"X-Artifact-Offset": str(total)}

    # -- push sending (producer side) -----------------------------------------

    def push_to(
        self, peer: str, digest: str, timeout_s: float = 30.0
    ) -> None:
        """Push a resident blob to one replica holder (base URL serving
        ``/artifacts``), resuming from the holder's recorded offset.
        Windows are capped at ``serve_window`` so each PUT stays under
        the ingress body bound and other traffic interleaves between
        them. Raises :class:`ArtifactPushError` (or the transport error)
        on failure; fault point ``artifact.push`` fires per call."""
        try:
            faults.inject(
                "artifact.push", context={"digest": digest, "peer": peer}
            )
            resumed = self._push_serial(peer, digest, timeout_s)
        except Exception:
            _M_PUSHES.labels(outcome="failed").inc()
            raise
        _M_PUSHES.labels(outcome="resumed" if resumed else "ok").inc()

    def _push_serial(
        self, peer: str, digest: str, timeout_s: float
    ) -> bool:
        src = self.path(digest)
        if src is None:
            raise ArtifactPushError(
                f"artifact {digest[:12]}… not in local store"
            )
        with self._lock:
            ref = self._index.get(digest)
            name = ref.name if ref is not None else digest[:12]
            # an in-flight push counts as "mid-pull" for GC/eviction:
            # the source bytes must survive until the holder confirms
            self._active[digest] = self._active.get(digest, 0) + 1
        try:
            total = os.path.getsize(src)
            u = urllib.parse.urlparse(
                peer if "//" in peer else "http://" + peer
            )

            def one(body: bytes, content_range: str) -> tuple:
                conn = http.client.HTTPConnection(
                    u.hostname, u.port or 80, timeout=timeout_s
                )
                try:
                    conn.request(
                        "PUT", f"/artifacts/{digest}", body=body,
                        headers={
                            "Content-Range": content_range,
                            "Content-Type": "application/octet-stream",
                            "X-Artifact-Name": name,
                        },
                    )
                    resp = conn.getresponse()
                    resp.read()
                    return resp.status, resp.headers
                finally:
                    conn.close()

            # probe for the holder's recorded offset (resume currency)
            status, hdrs = one(b"", f"bytes */{total}")
            if status == 200:
                return False  # idempotent: the holder already has it
            if status != 308:
                raise ArtifactPushError(
                    f"{peer} answered {status} to the push probe"
                )
            offset = int(hdrs.get("X-Artifact-Offset") or 0)
            resumed = offset > 0
            resyncs = 0
            with open(src, "rb") as f:
                while offset < total:
                    f.seek(offset)
                    chunk = f.read(min(self.serve_window, total - offset))
                    status, hdrs = one(
                        chunk,
                        f"bytes {offset}-{offset + len(chunk) - 1}/{total}",
                    )
                    if status == 409:
                        # the holder's offset moved under us (or an
                        # overshoot reset it): resync and continue —
                        # but a resync that never converges is a
                        # broken holder, not a race
                        resyncs += 1
                        if resyncs > 4:
                            raise ArtifactPushError(
                                f"{peer} never converged on an offset"
                            )
                        offset = int(hdrs.get("X-Artifact-Offset") or 0)
                        resumed = True
                        continue
                    if status == 422:
                        raise ArtifactPushError(
                            f"{peer} quarantined the pushed bytes "
                            f"(hash mismatch on arrival)"
                        )
                    if status not in (200, 201, 202):
                        raise ArtifactPushError(
                            f"{peer} answered {status} mid-push"
                        )
                    _M_BYTES.labels(direction="sent").inc(len(chunk))
                    offset += len(chunk)
                    if status in (200, 201):
                        break
            return resumed
        finally:
            with self._lock:
                self._active[digest] = max(0, self._active.get(digest, 1) - 1)
                if not self._active[digest]:
                    del self._active[digest]

    def replicate(
        self,
        digest: str,
        holders: list,
        need: int = 1,
        timeout_s: float = 30.0,
        backoffs_ms: tuple = (100, 300, 800),
    ) -> list:
        """Push ``digest`` to holders until ``need`` of them confirm a
        verified installed copy; returns the confirmed holder URLs.
        Below quorum it RAISES :class:`ArtifactReplicationError` — the
        replication-before-ack rule: a publish or generation commit that
        rides this call can only proceed once the bytes are durable on
        ``need`` other processes; there is no false-ack path. Fault
        point ``artifact.replicate`` refuses the whole round."""
        from mmlspark_tpu.core.utils import retry_with_backoff

        faults.inject(
            "artifact.replicate", context={"digest": digest, "need": need}
        )
        if need <= 0:
            return []
        remaining = list(dict.fromkeys(holders))
        confirmed: list = []
        errors: list = []

        def one_round() -> list:
            for holder in list(remaining):
                if len(confirmed) >= need:
                    break
                try:
                    self.push_to(holder, digest, timeout_s=timeout_s)
                except Exception as e:  # noqa: BLE001 — holder down: next
                    errors.append(f"{holder}: {type(e).__name__}: {e}")
                    _M_REPLICAS.labels(outcome="failed").inc()
                    continue
                confirmed.append(holder)
                remaining.remove(holder)
                _M_REPLICAS.labels(outcome="confirmed").inc()
            if len(confirmed) < need:
                raise ArtifactReplicationError(
                    f"artifact {digest[:12]}… replicated to "
                    f"{len(confirmed)}/{need} holder(s) "
                    f"({len(remaining)} candidate(s) left): "
                    f"{'; '.join(errors[-3:])}"
                )
            return list(confirmed)

        with obs.span(
            "artifact.replicate",
            attrs={"digest": digest[:12], "need": need,
                   "holders": len(remaining)},
        ):
            try:
                return retry_with_backoff(one_round, backoffs_ms=backoffs_ms)
            except ArtifactReplicationError:
                _M_REPLICAS.labels(outcome="below_quorum").inc()
                raise

    # -- consumer side --------------------------------------------------------

    def fetch(
        self,
        digest: str,
        peers: list,
        name: Optional[str] = None,
        timeout_s: float = 30.0,
        backoffs_ms: tuple = (100, 300, 800),
    ) -> str:
        """Ensure a verified local copy of ``digest``; returns its blob
        path. Tries ``peers`` (base URLs serving ``/artifacts``) in order
        with :func:`retry_with_backoff` pacing across rounds; a transfer
        that dies mid-stream leaves its partial bytes and the next
        attempt resumes with a Range request. Every completed transfer is
        sha256-verified; a mismatch quarantines the bytes and the fetch
        continues elsewhere. Fault point ``artifact.fetch`` fires per
        transfer attempt (error = that attempt fails, delay = slow net).
        """
        if not _DIGEST_RE.match(digest):
            raise ValueError(f"malformed artifact digest {digest!r}")
        with self._lock:
            flock = self._fetch_locks.setdefault(digest, threading.Lock())
        with flock:
            return self._fetch_serial(
                digest, peers, name, timeout_s, backoffs_ms
            )

    def _fetch_serial(
        self, digest: str, peers: list, name: Optional[str],
        timeout_s: float, backoffs_ms: tuple,
    ) -> str:
        from mmlspark_tpu.core.utils import retry_with_backoff

        # local hit — but only a VERIFIED one: a corrupted cached blob
        # must be quarantined and re-fetched, not served onward
        if self.has(digest):
            if self.verify(digest):
                _M_FETCHES.labels(outcome="cached").inc()
                return self.path(digest)
        if not peers:
            _M_FETCHES.labels(outcome="failed").inc()
            raise ArtifactFetchError(
                f"no peers advertise artifact {digest[:12]}…"
            )
        t0 = time.perf_counter()
        part = os.path.join(self.root, "partial", digest + ".part")
        errors: list = []
        with self._lock:
            # an in-flight fetch counts as "mid-pull" for GC/eviction
            self._active[digest] = self._active.get(digest, 0) + 1
        try:
            def one_round() -> str:
                for peer in peers:
                    try:
                        faults.inject(
                            "artifact.fetch",
                            context={"digest": digest, "peer": peer},
                        )
                        self._pull_from(peer, digest, part, timeout_s)
                        if sha256_file(part) != digest:
                            _M_VERIFY_FAIL.inc()
                            _M_QUARANTINES.inc()
                            os.replace(part, os.path.join(
                                self.root, "quarantine", digest + ".bad",
                            ))
                            raise ArtifactVerifyError(
                                f"bytes from {peer} do not hash to "
                                f"{digest[:12]}…"
                            )
                        with self._lock:
                            if digest in self._index:
                                os.remove(part)
                                self._quarantined.discard(digest)
                                return self._index[digest].path
                            ref = self._install_locked(
                                part, digest, name or digest[:12]
                            )
                        return ref.path
                    except ArtifactError as e:
                        # size-policy refusals included: a single peer's
                        # SELF-REPORTED headers must not abort the whole
                        # fetch — the next peer may hold (and honestly
                        # describe) the real bytes
                        errors.append(f"{peer}: {e}")
                    except Exception as e:  # noqa: BLE001 — dead peer: next
                        errors.append(f"{peer}: {type(e).__name__}: {e}")
                raise ArtifactFetchError(
                    f"artifact {digest[:12]}… unavailable from "
                    f"{len(peers)} peer(s): {'; '.join(errors[-3:])}"
                )

            try:
                with obs.span(
                    "artifact.fetch",
                    attrs={"digest": digest[:12], "peers": len(peers)},
                ):
                    # every failure retries: even size refusals are one
                    # peer's self-reported headers, and the next round
                    # may reach a peer that describes the bytes honestly
                    path = retry_with_backoff(
                        one_round, backoffs_ms=backoffs_ms,
                    )
            except Exception:
                _M_FETCHES.labels(outcome="failed").inc()
                raise
        finally:
            with self._lock:
                self._active[digest] = max(0, self._active.get(digest, 1) - 1)
                if not self._active[digest]:
                    del self._active[digest]
        _M_FETCHES.labels(outcome="ok").inc()
        _M_FETCH_S.observe(time.perf_counter() - t0)
        return path

    def _pull_from(
        self, peer: str, digest: str, part: str, timeout_s: float
    ) -> None:
        """One transfer attempt: stream ``/artifacts/<digest>`` from
        ``peer`` into the partial file, resuming past whatever it already
        holds. Large blobs arrive as a CHAIN of 206 windows (the server
        caps each response at its ``serve_window``); a complete window
        short of the total just continues the chain with the next Range
        request. Raises on any transport/protocol problem; a partial
        body is KEPT (the resume currency)."""
        start = os.path.getsize(part) if os.path.exists(part) else 0
        if start:
            _M_RESUMES.inc()
            _M_PULL_RESUMES.inc()
        u = urllib.parse.urlparse(peer if "//" in peer else "http://" + peer)
        while True:
            conn = http.client.HTTPConnection(
                u.hostname, u.port or 80, timeout=timeout_s
            )
            try:
                hdrs = {"Range": f"bytes={start}-"} if start else {}
                conn.request("GET", f"/artifacts/{digest}", headers=hdrs)
                resp = conn.getresponse()
                if resp.status == 200 and start:
                    # peer ignored the Range: restart the body from zero
                    start = 0
                if resp.status not in (200, 206):
                    resp.read()
                    raise ArtifactError(
                        f"peer answered {resp.status} for {digest[:12]}…"
                    )
                total = int(resp.headers.get("X-Artifact-Size")
                            or resp.headers.get("Content-Length") or 0)
                window_end = None
                if resp.status == 206:
                    m = re.match(
                        r"bytes (\d+)-(\d+)/(\d+)",
                        resp.headers.get("Content-Range", ""),
                    )
                    if m:
                        total = int(m.group(3))
                        window_end = int(m.group(2)) + 1
                if total == 0:
                    raise ArtifactError(
                        f"peer advertises zero-length artifact "
                        f"{digest[:12]}…"
                    )
                if total > self.max_artifact_bytes:
                    raise ArtifactError(
                        f"oversized artifact: {total} bytes > max "
                        f"{self.max_artifact_bytes}"
                    )
                received = 0
                with open(part, "ab" if start else "wb") as out:
                    while True:
                        # read1, NOT read: read(n) blocks until n bytes
                        # accumulate inside the BufferedReader, and a
                        # reset mid-chunk throws that buffer away — on a
                        # slow link a mid-frame RST lost every byte of a
                        # 64 KiB chunk in flight, leaving NOTHING for the
                        # Range resume (measured via the chaos proxy's
                        # truncate_rst rule). read1 surfaces each arrived
                        # chunk immediately, so progress hits the disk
                        b = resp.read1(_CHUNK)
                        if not b:
                            break
                        out.write(b)
                        received += len(b)
                _M_BYTES.labels(direction="received").inc(received)
            finally:
                conn.close()
            have = os.path.getsize(part)
            if have > total:
                # a botched resume (mixed peers disagreeing) — restart
                os.remove(part)
                raise ArtifactError(
                    f"transfer overshot: {have} > {total} bytes"
                )
            if have == total:
                return
            # short of the total: a COMPLETE declared window continues
            # the chain; anything less is a peer dying mid-stream (the
            # partial stays for the resume). A window that made no
            # progress would loop forever — treat it as a dead peer.
            expected = window_end if window_end is not None else total
            if have < expected or have <= start:
                raise ArtifactError(
                    f"transfer truncated at {have}/{total} bytes"
                )
            start = have


# -- advertisement + resolution -----------------------------------------------


def attach(server: Any, store: ArtifactStore) -> None:
    """Serve ``GET /artifacts[/<digest>]`` off an existing WorkerServer's
    ingress (inline, never queued or counted — the /metrics contract)."""
    server.artifact_store = store


def registry_peers(
    registry_urls: Any, digest: str, timeout: float = 5.0
) -> list:
    """Every base URL on any registry's roster advertising ``digest``
    (any service — checkpoints ride ``<svc>-gang`` entries, snapshots
    ride ``<svc>-online`` / ``serving`` entries). Dead registries skip;
    the first answering registry's roster is used (registry HA)."""
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData
    from mmlspark_tpu.serving.fleet import split_registry_urls

    suffix = "@" + digest
    for url in split_registry_urls(registry_urls):
        try:
            resp = send_request(
                HTTPRequestData(url.rstrip("/") + "/", "GET"), timeout=timeout
            )
            if resp["status_code"] != 200:
                continue
            roster = json.loads(resp["entity"])
        except Exception:  # noqa: BLE001 — registry HA: try the next
            continue
        peers: list = []
        for entries in roster.values():
            for e in entries:
                arts = e.get("artifacts") or ()
                if not any(a.endswith(suffix) for a in arts):
                    continue
                host = (
                    e.get("addr") or e.get("forwarded_host") or e.get("host")
                )
                port = e.get("artifact_port") or e.get("forwarded_port") \
                    or e.get("port")
                if host and port:
                    peers.append(f"http://{host}:{port}")
        if peers:
            return sorted(set(peers))
    return []


def registry_holders(
    registry_urls: Any,
    exclude: Any = (),
    digest: Optional[str] = None,
    timeout: float = 5.0,
    exclude_services: Any = (),
) -> list:
    """Every base URL on any registry's roster running an artifact plane
    (entries carrying an ``artifacts`` advertisement — workers, gang
    members, ArtifactServers) — the candidate replica holders for a
    push. ``digest`` narrows to holders already advertising that digest;
    ``exclude`` drops the pusher's own URL(s); ``exclude_services``
    drops whole roster services — replication that must outlive its
    producer excludes the producer's own EPHEMERAL plane (an
    experiment's trial/controller servers die with the experiment, so a
    replica confirmed there protects nothing). Dead registries skip;
    the first answering registry's roster is used (registry HA)."""
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData
    from mmlspark_tpu.serving.fleet import split_registry_urls

    drop = {u.rstrip("/") for u in (
        [exclude] if isinstance(exclude, str) else exclude
    )}
    drop_services = set(
        [exclude_services] if isinstance(exclude_services, str)
        else exclude_services
    )
    suffix = ("@" + digest) if digest else None
    for url in split_registry_urls(registry_urls):
        try:
            resp = send_request(
                HTTPRequestData(url.rstrip("/") + "/", "GET"),
                timeout=timeout,
            )
            if resp["status_code"] != 200:
                continue
            roster = json.loads(resp["entity"])
        except Exception:  # noqa: BLE001 — registry HA: try the next
            continue
        holders: list = []
        for service, entries in roster.items():
            if service in drop_services:
                continue
            for e in entries:
                arts = e.get("artifacts")
                if arts is None:
                    continue  # no artifact plane on this entry
                if suffix and not any(a.endswith(suffix) for a in arts):
                    continue
                host = (
                    e.get("addr") or e.get("forwarded_host") or e.get("host")
                )
                port = e.get("artifact_port") or e.get("forwarded_port") \
                    or e.get("port")
                if host and port:
                    holders.append(f"http://{host}:{port}")
        holders = sorted(u for u in set(holders) if u.rstrip("/") not in drop)
        if holders:
            return holders
    return []


# process-global consumer context: the fleet worker configures it once
# (its local store + its registries) and the modelstore loader grammar's
# ``artifact:`` resolution rides it — the loader itself stays spec-in,
# spec-out and never learns registry topology
_CTX: dict = {"store": None, "registry_urls": []}
_CTX_LOCK = threading.Lock()


def configure(
    store: Optional[ArtifactStore] = None,
    registry_urls: Any = None,
) -> None:
    with _CTX_LOCK:
        if store is not None:
            _CTX["store"] = store
        if registry_urls is not None:
            from mmlspark_tpu.serving.fleet import split_registry_urls

            _CTX["registry_urls"] = split_registry_urls(registry_urls)


def default_store() -> ArtifactStore:
    """The process's consumer-side cache store (lazily created under a
    private tempdir when nothing was configured)."""
    with _CTX_LOCK:
        if _CTX["store"] is None:
            import tempfile

            _CTX["store"] = ArtifactStore(
                tempfile.mkdtemp(prefix="mmlspark-artifacts-")
            )
        return _CTX["store"]


def parse_spec(spec: str) -> tuple:
    """``artifact:<scheme>:<name>@<digest>[@url[,url...]]`` ->
    ``(scheme, name, digest, hint_urls)``."""
    if not spec.startswith("artifact:"):
        raise ValueError(f"not an artifact spec: {spec!r}")
    body = spec[len("artifact:"):]
    scheme, sep, rest = body.partition(":")
    if not sep or "@" in scheme:
        # bare ``artifact:<name>@<digest>[@urls]`` (fleet model load /
        # --resume-from shorthand): no scheme token before the ref — a
        # real scheme never contains ``@``, so a first segment carrying
        # one (or a colon appearing only inside a peer URL) means the
        # whole body is the ref; the delegate scheme is inferred from
        # the name's extension
        scheme, rest = "", body
    name, _, tail = rest.partition("@")
    digest, _, hints = tail.partition("@")
    if not scheme:
        scheme = "vw" if name.endswith(".npz") else "pipeline"
    if not name or not _DIGEST_RE.match(digest):
        raise ValueError(
            f"malformed artifact spec {spec!r} "
            "(want artifact:<scheme>:<name>@<sha256>[@peer-url,...])"
        )
    urls = [u for u in hints.split(",") if u] if hints else []
    return scheme, name, digest, urls


def resolve_spec(spec: str, timeout_s: float = 60.0) -> str:
    """Resolve an ``artifact:`` model spec into the delegate spec the
    existing loader grammar understands: fetch the blob (spec-embedded
    peer hints first, then every registry-advertised peer), verify, and
    return ``<scheme>:<local path>`` (directory artifacts unpack first).
    """
    scheme, name, digest, hints = parse_spec(spec)
    store = default_store()
    peers = list(hints)
    with _CTX_LOCK:
        registries = list(_CTX["registry_urls"])
    if registries:
        for p in registry_peers(registries, digest):
            if p not in peers:
                peers.append(p)
    path = store.fetch(digest, peers, name=name, timeout_s=timeout_s)
    if is_dir_blob(path):
        path = store.unpack(digest)
    return f"{scheme}:{path}"


# -- a standalone advertisement ingress ---------------------------------------


class ArtifactServer:
    """A minimal artifact-plane presence for processes without their own
    WorkerServer ingress (bench drivers, tests, the elastic trainer's
    checkpoint replication): one WorkerServer serving ``/artifacts`` +
    an optional heartbeat registering ``artifacts=[name@digest,...]``
    under ``<service>`` on every registry."""

    def __init__(
        self,
        store: ArtifactStore,
        host: str = "127.0.0.1",
        port: int = 0,
        registry_urls: Any = None,
        service: str = "artifacts",
        heartbeat_s: float = 2.0,
    ):
        from mmlspark_tpu.serving.fleet import split_registry_urls
        from mmlspark_tpu.serving.server import WorkerServer

        self.store = store
        self.service = service
        self.heartbeat_s = heartbeat_s
        self.registry_urls = split_registry_urls(registry_urls)
        self._srv = WorkerServer(host=host, port=port, name=service)
        attach(self._srv, store)
        self._info = self._srv.start()
        self._stop = threading.Event()
        self._beat: Optional[threading.Thread] = None
        if self.registry_urls:
            self._beat = threading.Thread(
                target=self._beat_loop, name=f"{service}-artifact-beat",
                daemon=True,
            )
            self._beat.start()

    @property
    def url(self) -> str:
        return f"http://{self._info.host}:{self._info.port}"

    @property
    def port(self) -> int:
        return self._info.port

    def _payload(self) -> dict:
        return {
            "name": self.service,
            "host": self._info.host,
            "port": self._info.port,
            "artifacts": self.store.refs(),
        }

    def heartbeat(self) -> None:
        from mmlspark_tpu.io.clients import send_request
        from mmlspark_tpu.io.http_schema import HTTPRequestData

        for url in self.registry_urls:
            try:
                send_request(
                    HTTPRequestData(
                        url, "POST", {"Content-Type": "application/json"},
                        json.dumps(self._payload()),
                    ),
                    timeout=5.0,
                )
            except Exception:  # noqa: BLE001 — registry may be restarting
                pass

    def _beat_loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat()
            self._stop.wait(self.heartbeat_s)

    def stop(self) -> None:
        self._stop.set()
        if self._beat is not None:
            self._beat.join(10.0)
        self._srv.stop()


__all__ = [
    "ArtifactError",
    "ArtifactFetchError",
    "ArtifactPushError",
    "ArtifactRef",
    "ArtifactReplicationError",
    "ArtifactServer",
    "ArtifactStore",
    "ArtifactVerifyError",
    "attach",
    "configure",
    "default_store",
    "is_dir_blob",
    "pack_dir",
    "parse_ref",
    "parse_spec",
    "registry_holders",
    "registry_peers",
    "resolve_spec",
    "sha256_file",
    "unpack_dir",
]
