"""HBM-resident ModelStore: versioned multi-model serving on one worker.

The model-lifecycle layer between the serving ingress
(:class:`~mmlspark_tpu.serving.server.WorkerServer`) and the models it
serves — named models, integer versions, weights resident in device
memory under a byte budget, background load + warmup, atomic
zero-downtime hot-swap, and per-model dispatch with deadline-aware
admission control. See docs/modelstore.md.

- :class:`ModelStore` / :class:`LoadedModel` / :class:`ModelVersion` —
  the store itself (store.py);
- :class:`ModelDispatcher` — per-model queues + control plane on a
  WorkerServer (dispatch.py);
- :func:`build_loaded_model` / :func:`model_name_from_spec` — fleet-spec
  loaders (loaders.py).
"""

from mmlspark_tpu.serving.modelstore.store import (
    EVICTED,
    FAILED,
    HBMBudgetExceeded,
    LOADING,
    LoadedModel,
    ModelStore,
    ModelStoreError,
    ModelVersion,
    READY,
    WARMING,
)
from mmlspark_tpu.serving.modelstore.dispatch import (
    DEADLINE_HEADER,
    MODEL_HEADER,
    ModelDispatcher,
    STATE_HEADER,
)
from mmlspark_tpu.serving.modelstore.loaders import (
    build_loaded_model,
    model_name_from_spec,
    tree_nbytes,
)

__all__ = [
    "DEADLINE_HEADER",
    "EVICTED",
    "FAILED",
    "HBMBudgetExceeded",
    "LOADING",
    "LoadedModel",
    "MODEL_HEADER",
    "ModelDispatcher",
    "ModelStore",
    "ModelStoreError",
    "ModelVersion",
    "READY",
    "STATE_HEADER",
    "WARMING",
    "build_loaded_model",
    "model_name_from_spec",
    "tree_nbytes",
]
