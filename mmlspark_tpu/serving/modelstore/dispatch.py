"""ModelDispatcher: per-model request routing on one WorkerServer.

Replaces the single-handler :class:`~mmlspark_tpu.serving.query.ServingQuery`
loop on multi-model workers. One fast **router thread** pops ingress
requests and does no model work — it answers the control plane and
``/health`` inline (spawning a side thread for verbs that may block on a
load), applies admission control, and pushes data requests into
**per-model queues**. Each model owns a dispatcher thread with its own
batcher, so a slow model's batch never holds another model's traffic,
and each batch resolves its model version through
``ModelStore.acquire()`` — the refcount that lets hot-swap drain the old
version without dropping a request.

Routing: ``POST /models/<name>`` or the ``x-mmlspark-model`` header pick
the model; bare ``POST /`` goes to ``default_model``.

Admission control (deadline-aware shedding): a request carrying
``x-mmlspark-deadline-ms`` (or, with ``default_deadline_ms`` set, every
request) is rejected **429** at routing time when estimated queue wait
plus one service time already blows the deadline — shedding at ingress
costs microseconds, serving a reply the client will discard costs a full
batch slot. The estimate is ``ceil(queue_len / max_batch) * svc + svc``
with ``svc`` an EWMA of recent batch service times.

Control plane (all answered by the worker itself, never queued):

- ``GET  /models``                 — full store listing
- ``GET  /models/<name>``          — one model's versions + serving alias
- ``POST /models/<name>/load``     — body ``{"spec": ..., "version"?,
  "pin"?, "activate"?, "wait"?}``; ``wait=false`` returns 202 and loads
  in the background
- ``POST /models/<name>/swap``     — body ``{"version"?}``
- ``POST /models/<name>/unload``   — body ``{"version"?}``
- ``POST /models/<name>/pin`` / ``/unpin`` — body ``{"version"?}``
- ``GET  /health``                 — 200 once the default model (or, with
  no default, any model) is ready; 503 with per-model states otherwise
"""

from __future__ import annotations

import contextlib
import json
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.obs import watchdog
from mmlspark_tpu.obs.flightrec import FLIGHT
from mmlspark_tpu.serving.admission import (
    DEADLINE_HEADER,
    SHED_HEADER,
    deadline_ms_from,
)
from mmlspark_tpu.serving.modelstore.store import (
    HBMBudgetExceeded,
    ModelStore,
    ModelStoreError,
    READY,
)
# the worker-level families ServingQuery emits: the dispatcher reports
# into them too (labels server=<name>), so `fleet top`, dashboards and
# alerts keyed on mmlspark_serving_* keep working on ModelStore workers
from mmlspark_tpu.serving.query import (
    _M_DEADLINE_EXPIRED as _M_SRV_DEADLINE,
    _M_HANDLER_ERRS as _M_SRV_ERRS,
    _M_LATENCY as _M_SRV_LATENCY,
    _M_OVERLAP as _M_SRV_OVERLAP,
    LatencyRing,
    handler_stages,
)
from mmlspark_tpu.serving.server import WorkerServer

MODEL_HEADER = "x-mmlspark-model"
# DEADLINE_HEADER is canonical in serving/admission.py (re-exported here
# for back-compat with pre-PR-5 imports)
# stamped on 503s a routing layer may retry elsewhere (model still
# loading/warming on THIS worker — another replica may already serve it)
STATE_HEADER = "x-mmlspark-model-state"

_CONTROL_VERBS = ("load", "swap", "unload", "pin", "unpin")
_JSON = {"Content-Type": "application/json"}

_M_DISPATCH_LAT = obs.histogram(
    "mmlspark_modelstore_dispatch_latency_seconds",
    "Per-model ingress arrival to reply", labels=("model",),
)
_M_SHED = obs.counter(
    "mmlspark_modelstore_shed_total",
    "Requests shed 429 by deadline-aware admission control",
    labels=("model",),
)
_M_ERRS = obs.counter(
    "mmlspark_modelstore_handler_errors_total",
    "Handler exceptions turned into 500 batches", labels=("model",),
)
_M_EPOCH_FENCED = obs.counter(
    "mmlspark_elastic_fenced_publications_total",
    "Model load/swap publications rejected because their epoch stamp "
    "was older than the highest seen (zombie-coordinator rollback "
    "refused at the worker's swap path)", labels=("model",),
)
_M_QDEPTH = obs.gauge(
    "mmlspark_modelstore_queue_depth_requests",
    "Requests queued per model awaiting dispatch", labels=("model",),
)


class _ModelQueue:
    """One model's queue + batcher/executor thread pair + service EWMA.

    Continuous batching (``disp.pipeline_depth >= 2``, the default): the
    *batcher* thread admits queued requests into the next dispatch slot
    — deadline shed, ``ModelStore.acquire()`` (the refcount that lets
    hot-swap drain), and the handler's host-side ``prepare`` — while the
    *executor* thread still runs the previous batch's model call. The
    version refcount is held from acquire (batcher) to release
    (executor), so a swap drains both the executing AND the staged batch
    before the old version evicts. ``pipeline_depth=1`` runs everything
    inline on the batcher thread (the pre-rewrite barrier loop)."""

    def __init__(self, disp: "ModelDispatcher", name: str):
        self.disp = disp
        self.name = name
        self.q: deque = deque()
        self.cond = threading.Condition()
        self.dead = False  # set by the reaper; push() then refuses
        self.svc_s = 0.0  # EWMA of one batch's service time (0 = unknown)
        self._m_lat = _M_DISPATCH_LAT.labels(model=name)
        self._m_errs = _M_ERRS.labels(model=name)
        self._m_qdepth = _M_QDEPTH.labels(model=name)
        self._m_srv_lat = _M_SRV_LATENCY.labels(server=disp.server.name)
        self._m_srv_errs = _M_SRV_ERRS.labels(server=disp.server.name)
        self._m_srv_deadline = _M_SRV_DEADLINE.labels(server=disp.server.name)
        self._m_srv_overlap = _M_SRV_OVERLAP.labels(server=disp.server.name)
        self._exec_busy = False
        # double-buffering pays only when the handler has a host-side
        # prepare stage to overlap; plain handlers execute inline on this
        # thread (no cross-thread hop on their latency). Sticky: once a
        # split-handler batch has ridden the handoff, every later batch
        # does too — an inline execute racing a still-staged batch would
        # reorder replies and overlap two versions mid-swap
        self._use_handoff = False
        self.exec_thread: Optional[threading.Thread] = None
        self._handoff: Optional[queue_mod.Queue] = None
        if disp.pipeline_depth > 1:
            self._handoff = queue_mod.Queue(maxsize=disp.pipeline_depth - 1)
            self.exec_thread = threading.Thread(
                target=self._exec_loop,
                name=f"modelstore-execute-{name}", daemon=True,
            )
            self.exec_thread.start()
        self.thread = threading.Thread(
            target=self._loop, name=f"modelstore-dispatch-{name}", daemon=True
        )
        self.thread.start()

    def push(self, req) -> bool:
        """False when this queue was reaped between routing's lookup and
        the push — the request must be answered not-ready, not stranded
        on a queue nothing will ever pop."""
        with self.cond:
            if self.dead:
                return False
            self.q.append(req)
            self._m_qdepth.set(len(self.q))
            self.cond.notify()
            return True

    def depth(self) -> int:
        with self.cond:
            return len(self.q)

    def estimate_s(self) -> float:
        """Queue wait + one service time if a request joined now — the
        admission-control estimate. 0 while no batch has been measured
        (admit everything until the EWMA exists)."""
        if self.svc_s <= 0.0:
            return 0.0
        with self.cond:
            depth = len(self.q)
        batches_ahead = -(-depth // max(self.disp.max_batch_size, 1))
        return (batches_ahead + 1) * self.svc_s

    def _pop_batch(self) -> list:
        max_n = self.disp.max_batch_size
        acc_s = self.disp.max_wait_ms / 1000.0
        if self._use_handoff and not self._exec_busy:
            # accumulation amortizes a BUSY executor; while it is idle,
            # holding the batch open is pure added latency (query.py)
            acc_s = 0.0
        with self.cond:
            if not self.q:
                self.cond.wait(0.25)
            if self.q and acc_s > 0:
                deadline = time.monotonic() + acc_s
                while len(self.q) < max_n:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self.cond.wait(remaining)
            out = []
            while self.q and len(out) < max_n:
                out.append(self.q.popleft())
            if out:
                self._m_qdepth.set(len(self.q))
            return out

    def _reap_if_orphaned(self) -> bool:
        """Exit this batcher when its model was unloaded: otherwise every
        model name ever served leaves an idle 4 Hz-polling thread and a
        live metric series behind (multi-tenant churn). A reload simply
        recreates the queue lazily."""
        disp = self.disp
        if disp.store.serving_state(self.name) is not None:
            return False
        with disp._queues_lock:
            if disp._queues.get(self.name) is not self:
                return True  # a reload already replaced us: just exit
            with self.cond:
                if self.q:
                    return False  # stragglers first; reap on a later pass
                self.dead = True  # a racing push() now refuses
            del disp._queues[self.name]
        for fam in (_M_DISPATCH_LAT, _M_SHED, _M_ERRS, _M_QDEPTH):
            fam.remove(model=self.name)
        return True

    def _shed_expired(self, batch: list) -> list:
        """Deadline propagation's worker half: a request whose (possibly
        gateway-decremented) deadline expired while queued here is dead
        work — shed it 504 before it costs a batch slot. The admission
        estimate sheds *predictably* late requests at routing; this
        catches the ones that became late after admission (a slow batch
        ahead, a hot-swap stall)."""
        disp = self.disp
        now_ns = time.perf_counter_ns()
        live = []
        for r in batch:
            dl_ms = deadline_ms_from(r.headers, disp.default_deadline_ms)
            if dl_ms is not None and (now_ns - r.arrival_ns) / 1e6 > dl_ms:
                disp.deadline_expired += 1
                self._m_srv_deadline.inc()
                disp.server.reply_to(
                    r.id, b'{"error": "deadline expired in queue"}', 504,
                    {SHED_HEADER: "deadline", **_JSON},
                )
            else:
                live.append(r)
        return live

    def _loop(self) -> None:
        disp = self.disp
        while not disp._stop.is_set():
            batch = self._pop_batch()
            if not batch:
                if self._reap_if_orphaned():
                    if self._handoff is not None:
                        self._handoff.put(None)  # executor: exit too
                    return
                continue
            batch = self._shed_expired(batch)
            if not batch:
                continue
            mv = disp.store.acquire(self.name)
            if mv is None:
                # swap/unload raced routing: the version vanished between
                # admission and dispatch — tell the router's 503 story
                disp._reply_not_ready(batch, self.name)
                continue
            # continuous batching: run the handler's host-side prepare on
            # THIS thread while the executor still runs the previous
            # batch's model call — the acquire above already holds the
            # version against a concurrent swap's drain
            split = handler_stages(mv.loaded.handler)
            staged = err = None
            if split is not None:
                try:
                    staged = split[0](batch)
                except Exception as e:  # noqa: BLE001 — a 500 batch
                    err = e
            if self._handoff is not None and (
                self._use_handoff or split is not None
            ):
                self._use_handoff = True
                if self._exec_busy:
                    self._m_srv_overlap.inc()
                self._handoff.put((batch, mv, staged, err))
            else:
                self._execute(batch, mv, staged, err)
        # stopped: nothing queued here gets a handler anymore
        if self._handoff is not None:
            self._handoff.put(None)
        with self.cond:
            leftovers, self.q = list(self.q), deque()
        for r in leftovers:
            disp.server.reply_to(r.id, b"worker stopping", 503)

    def _exec_loop(self) -> None:
        """Executor half: model call + replies + telemetry. Exits on the
        batcher's sentinel so staged batches are never stranded — and,
        as a backstop, when the batcher thread itself is gone (a crashed
        batcher never reaches its sentinel put; blocking forever would
        strand staged work and wedge stop()'s join)."""
        while True:
            try:
                item = self._handoff.get(timeout=0.25)
            except queue_mod.Empty:
                batcher = getattr(self, "thread", None)
                if batcher is not None and not batcher.is_alive():
                    return  # builder dead, queue drained
                continue
            if item is None:
                return
            self._exec_busy = True
            try:
                self._execute(*item)
            finally:
                self._exec_busy = False

    def _execute(self, batch: list, mv, staged, prep_err) -> None:
        disp = self.disp
        split = handler_stages(mv.loaded.handler)
        obs_on = self._m_lat._on
        dispatch_ns = time.perf_counter_ns()
        # pre-minted per-request span AND trace ids: same tree shape
        # as ServingQuery (request span parenting queue + batch
        # spans, itself parented under the gateway's forward span;
        # headerless direct traffic mints its trace ids here)
        req_sids = req_tids = None
        if obs_on:
            req_sids = {r.id: obs.new_span_id() for r in batch}
            req_tids = {
                r.id: r.headers.get(obs.TRACE_HEADER)
                or obs.new_trace_id()
                for r in batch
            }
        t0 = time.perf_counter()
        # stall forensics: a handler that wedges mid-batch (lock, device
        # hang) auto-dumps all-thread stacks; disarmed per batch so an
        # IDLE dispatcher is never a stall (obs/watchdog.py)
        watchdog.tick(f"modelstore.batch.{self.name}")
        try:
            if prep_err is not None:
                raise prep_err
            ctx = (
                obs.span(
                    "modelstore.dispatch",
                    trace_id=req_tids[batch[0].id],
                    parent_id=req_sids[batch[0].id],
                    attrs={"model": self.name, "batch": len(batch)},
                )
                if obs_on
                else contextlib.nullcontext()
            )
            with ctx:
                replies = (
                    split[1](staged) if split is not None
                    else mv.loaded.handler(batch)
                )
        except Exception as e:  # handler crash -> 500s, keep serving
            disp.errors += 1
            self._m_errs.inc()
            self._m_srv_errs.inc()
            msg = f"handler error: {type(e).__name__}: {e}".encode()
            replies = {r.id: (500, msg, {}) for r in batch}
        finally:
            disp.store.release(mv)
            watchdog.disarm(f"modelstore.batch.{self.name}")
        svc = time.perf_counter() - t0
        self.svc_s = svc if self.svc_s <= 0 else (
            0.8 * self.svc_s + 0.2 * svc
        )
        done_ns = time.perf_counter_ns()
        # replies first, telemetry second: this executor thread is the
        # model's pipeline bottleneck — recording before replying
        # would tax every queued request's latency (see query.py).
        # reply_many: one loop wakeup per reactor for the whole batch
        codes = {}
        batch_out = []
        for r in batch:
            code, body, headers = replies.get(
                r.id, (500, b"no reply produced", {})
            )
            batch_out.append((r.id, body, code, headers))
            codes[r.id] = code
        disp.server.reply_many(batch_out)
        for r in batch:
            if obs_on:
                code = codes[r.id]
                sid = req_sids[r.id]
                tid = req_tids[r.id]
                obs.record_span(
                    "serving.request", r.arrival_ns, done_ns,
                    trace_id=tid,
                    span_id=sid,
                    parent_id=r.headers.get(obs.PARENT_HEADER),
                    attrs={"status": code, "model": self.name},
                )
                obs.record_span(
                    "serving.queue", r.arrival_ns, dispatch_ns,
                    trace_id=tid, parent_id=sid,
                )
                lat_s = (done_ns - r.arrival_ns) / 1e9
                self._m_lat.observe(lat_s, trace_id=tid)
                self._m_srv_lat.observe(lat_s, trace_id=tid)
                FLIGHT.record(
                    "ok" if code < 500 else "error",
                    status=code,
                    trace_id=tid,
                    model=self.name,
                    path=r.path,
                    latency_ms=lat_s * 1e3,
                    queue_wait_ms=(dispatch_ns - r.arrival_ns) / 1e6,
                )
            disp._lat.record(done_ns - r.arrival_ns)
        if disp.admission is not None:
            # AIMD signal: worst queue wait in the batch (FIFO: the
            # first request waited longest) + per-request service
            disp.admission.observe(
                (dispatch_ns - batch[0].arrival_ns) / 1e9,
                svc / len(batch),
            )
        disp.batches += 1


class ModelDispatcher:
    """Multi-model dispatch loop between one WorkerServer and a ModelStore.

    Same lifecycle surface as :class:`ServingQuery` (``start`` / ``stop``
    / ``batches`` / ``errors`` / ``latency_quantiles_ms``) so fleet code
    and tests treat them interchangeably."""

    def __init__(
        self,
        server: WorkerServer,
        store: ModelStore,
        default_model: Optional[str] = None,
        max_batch_size: int = 64,
        max_wait_ms: float = 0.0,
        default_deadline_ms: Optional[float] = None,
        admission: Optional[object] = None,
        pipeline_depth: int = 2,
    ):
        self.server = server
        self.store = store
        self.default_model = default_model
        self.max_batch_size = max_batch_size
        self.max_wait_ms = max_wait_ms
        self.default_deadline_ms = default_deadline_ms
        # continuous-batching depth per model queue (>= 2 double-buffers
        # build/execute; 1 = the pre-rewrite barrier loop)
        self.pipeline_depth = max(1, int(pipeline_depth))
        # adaptive-concurrency limit (serving/admission.py): attached to
        # the ingress so sheds happen before routing; fed per-batch by
        # every model queue's wait/service samples
        self.admission = admission
        if admission is not None:
            server.admission = admission
        self._stop = threading.Event()
        self._router: Optional[threading.Thread] = None
        self._queues: dict[str, _ModelQueue] = {}
        self._queues_lock = threading.Lock()
        self.batches = 0
        self.errors = 0
        self.shed = 0
        self.deadline_expired = 0
        self._lat = LatencyRing()
        # epoch fencing on the publication plane: per-model highest
        # coordination epoch seen on a load/swap body. A publication
        # stamped with an OLDER epoch is a zombie coordinator (one that
        # woke after the fleet resharded) trying to roll the serving
        # fleet back — rejected with 409, never applied
        self._model_epochs: dict[str, int] = {}
        self._epoch_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ModelDispatcher":
        self._router = threading.Thread(
            target=self._route_loop, name=f"{self.server.name}-router",
            daemon=True,
        )
        self._router.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._router is not None:
            self._router.join(5.0)
        with self._queues_lock:
            queues = list(self._queues.values())
        for mq in queues:
            with mq.cond:
                mq.cond.notify_all()
            mq.thread.join(5.0)
            if mq.exec_thread is not None:
                mq.exec_thread.join(5.0)

    def latency_quantiles_ms(self) -> dict:
        return self._lat.quantiles_ms()

    # -- routing (router thread: no model work, O(µs) per request) -----------

    def _route_loop(self) -> None:
        while not self._stop.is_set():
            reqs = self.server.get_next_batch(64, timeout_s=0.25)
            for r in reqs:
                if self._stop.is_set():
                    self.server.reply_to(r.id, b"worker stopping", 503)
                    continue
                try:
                    self._route(r)
                except Exception as e:  # noqa: BLE001 — router must survive
                    self.server.reply_to(
                        r.id,
                        json.dumps({"error": f"{type(e).__name__}: {e}"})
                        .encode(),
                        500, _JSON,
                    )
            if reqs:
                self.server.auto_commit()
        # drain whatever the ingress still holds so clients aren't hung
        for r in self.server.get_next_batch(1_000_000, timeout_s=0.0):
            self.server.reply_to(r.id, b"worker stopping", 503)

    def _route(self, r) -> None:
        path = r.path.split("?", 1)[0]
        # a worker registered under a base path receives gateway-forwarded
        # targets like /api/models/m/swap — strip the prefix so the
        # control-plane and health routes match regardless of api_path
        prefix = self.server.api_path.rstrip("/")
        if prefix and path.startswith(prefix):
            path = path[len(prefix):] or "/"
        if path in ("/health", "/healthz") and r.method == "GET":
            self._reply_health(r)
            return
        model = None
        if path == "/models" or path == "/models/":
            self._reply_json(r, self.store.models())
            return
        if path.startswith("/models/"):
            parts = [p for p in path[len("/models/"):].split("/") if p]
            if not parts:
                self._reply_json(r, self.store.models())
                return
            name = parts[0]
            if len(parts) == 2 and parts[1] in _CONTROL_VERBS:
                if r.method != "POST":
                    self._reply_json(
                        r, {"error": "control verbs are POST"}, 400
                    )
                    return
                self._control(r, name, parts[1])
                return
            if len(parts) == 1 and r.method == "GET":
                listing = self.store.models().get(name)
                if listing is None:
                    self._reply_json(
                        r, {"error": f"unknown model {name!r}"}, 404
                    )
                else:
                    self._reply_json(r, {name: listing})
                return
            model = name  # data path: POST /models/<name>[/...]
        if model is None:
            model = r.headers.get(MODEL_HEADER) or self.default_model
        if model is None:
            self._reply_json(
                r,
                {"error": "no model named: set x-mmlspark-model or POST "
                          "/models/<name>"},
                404,
            )
            return
        self._admit(r, model)

    def _admit(self, r, model: str) -> None:
        state = self.store.serving_state(model)
        if state is None:
            # worker-local unknown: another replica may serve this model
            # without advertising it yet (runtime load, heartbeat lag) —
            # the state header lets the gateway retry elsewhere
            self._reply_json(
                r, {"error": f"unknown model {model!r}"}, 404,
                {STATE_HEADER: "unknown", **_JSON},
            )
            return
        if state != READY:
            self._reply_not_ready([r], model, state)
            return
        mq = self._queues.get(model)
        if mq is None:
            with self._queues_lock:
                mq = self._queues.get(model)
                if mq is None:
                    mq = self._queues[model] = _ModelQueue(self, model)
        # deadline-aware shedding: reject NOW when the queue already
        # guarantees a blown deadline — a 429 at ingress beats a reply
        # the client gave up on
        deadline_ms = r.headers.get(DEADLINE_HEADER)
        try:
            deadline_ms = (
                float(deadline_ms) if deadline_ms is not None
                else self.default_deadline_ms
            )
        except ValueError:
            deadline_ms = self.default_deadline_ms
        if deadline_ms is not None:
            waited_s = (time.perf_counter_ns() - r.arrival_ns) / 1e9
            est_s = mq.estimate_s() + waited_s
            if est_s * 1000.0 > deadline_ms:
                self.shed += 1
                _M_SHED.labels(model=model).inc()
                self._reply_json(
                    r,
                    {
                        "error": "deadline unmeetable",
                        "estimate_ms": round(est_s * 1e3, 3),
                        "deadline_ms": deadline_ms,
                    },
                    429, {"Retry-After": "1", **_JSON},
                )
                if _M_SHED._on:
                    # a shed is exactly what a flight-recorder dump should
                    # explain: deadline, estimate and queue wait survive.
                    # Recorded AFTER the reply: a shed auto-dumps the
                    # ring, and that disk write must not stall the router
                    # thread's 429 (nor every other model's routing)
                    # longer than it already has to
                    FLIGHT.record(
                        "shed",
                        status=429,
                        trace_id=r.headers.get(obs.TRACE_HEADER),
                        model=model,
                        path=r.path,
                        queue_wait_ms=waited_s * 1e3,
                        deadline_ms=deadline_ms,
                        detail=f"estimate_ms={round(est_s * 1e3, 3)}",
                    )
                return
        if not mq.push(r):
            # the queue was reaped (model unloaded) between lookup and
            # push: answer rather than strand the request
            self._reply_not_ready([r], model)

    # -- replies -------------------------------------------------------------

    def _reply_json(self, r, obj, code: int = 200,
                    headers: Optional[dict] = None) -> None:
        self.server.reply_to(
            r.id, json.dumps(obj).encode(), code, headers or _JSON
        )

    def _reply_not_ready(self, reqs: list, model: str,
                         state: Optional[str] = None) -> None:
        state = state or self.store.serving_state(model) or "unloaded"
        body = json.dumps(
            {"error": f"model {model!r} not ready", "state": state}
        ).encode()
        for r in reqs:
            # STATE_HEADER marks this 503 as worker-local (the model is
            # loading HERE) — the gateway retries another replica on it
            self.server.reply_to(
                r.id, body, 503, {STATE_HEADER: state, **_JSON}
            )

    def _reply_health(self, r) -> None:
        """Readiness: the default model (or, with no default, any model)
        has a ready serving version. The shape a registry-fronting LB or
        k8s probe consumes — and what fleet.run_worker's warm-before-
        register contract makes true by the time the worker is routable."""
        states = {
            name: {
                "serving": self.store.serving_version(name),
                "state": self.store.serving_state(name),
            }
            for name in self.store.model_names()
        }
        if self.default_model is not None:
            ok = states.get(self.default_model, {}).get("state") == READY
        else:
            ok = any(s["state"] == READY for s in states.values())
        self._reply_json(
            r,
            {"status": "ok" if ok else "loading", "models": states},
            200 if ok else 503,
        )

    # -- control plane (side threads: a load must not stall routing) ---------

    def _control(self, r, name: str, verb: str) -> None:
        def run() -> None:
            try:
                body = json.loads(r.body) if r.body else {}
                if not isinstance(body, dict):
                    raise ValueError("control body must be a JSON object")
                if verb in ("load", "swap") and body.get("epoch") is not None:
                    # epoch fence: the committed training generation
                    # rides the publication as a fencing token — an
                    # epoch older than the highest this worker has seen
                    # is a zombie's rollback and is refused, counted
                    epoch = int(body["epoch"])
                    with self._epoch_lock:
                        high = self._model_epochs.get(name, 0)
                        if epoch < high:
                            fenced = True
                        else:
                            fenced = False
                            self._model_epochs[name] = epoch
                    if fenced:
                        faults.inject("publish.fence", context={
                            "model": name, "epoch": epoch, "highest": high,
                        })
                        _M_EPOCH_FENCED.labels(model=name).inc()
                        self._reply_json(r, {
                            "error": (
                                f"fenced: publication epoch {epoch} is "
                                f"older than highest seen {high}"
                            ),
                            "fenced": True, "highest_epoch": high,
                        }, 409, headers={
                            "Content-Type": "application/json",
                            # survives the gateway hop (distributed.py
                            # preserves it), so a publisher behind the
                            # gateway still sees WHY the 409 happened
                            "x-mmlspark-fenced": str(high),
                        })
                        return
                if verb == "load":
                    spec = body.get("spec")
                    if spec is None:
                        raise ValueError('load needs {"spec": ...}')
                    wait = bool(body.get("wait", True))
                    v = self.store.load(
                        name, spec, version=body.get("version"),
                        wait=wait, pin=bool(body.get("pin", False)),
                        activate=body.get("activate", "auto"),
                    )
                    out, code = {
                        "model": name, "version": v,
                        "state": READY if wait else "loading",
                    }, (200 if wait else 202)
                elif verb == "swap":
                    v = self.store.swap(name, body.get("version"))
                    out, code = {"model": name, "serving": v}, 200
                elif verb == "unload":
                    n = self.store.unload(name, body.get("version"))
                    out, code = {"model": name, "unloaded": n}, 200
                else:  # pin / unpin
                    v = self.store.pin(
                        name, body.get("version"), pinned=(verb == "pin")
                    )
                    out, code = {
                        "model": name, "version": v,
                        "pinned": verb == "pin",
                    }, 200
                self._reply_json(r, out, code)
            except KeyError as e:
                self._reply_json(r, {"error": str(e).strip("'\"")}, 404)
            except HBMBudgetExceeded as e:
                self._reply_json(r, {"error": str(e)}, 507)
            except (ModelStoreError, ValueError, TypeError) as e:
                self._reply_json(r, {"error": str(e)}, 400)
            except Exception as e:  # noqa: BLE001 — loader crashes land here
                self._reply_json(
                    r, {"error": f"{type(e).__name__}: {e}"}, 500
                )

        threading.Thread(
            target=run, name=f"modelstore-ctl-{verb}-{name}", daemon=True
        ).start()
