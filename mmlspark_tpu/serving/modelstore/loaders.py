"""Spec -> :class:`LoadedModel` resolution for the ModelStore.

Understands the fleet CLI's model specs (``echo`` / ``zoo:<name>`` /
``module:pkg.fn``) and adds what the store needs beyond a bare handler:
a device-byte estimate for the residency budget, a warmup that runs one
dummy bucket batch through the model (so the XLA compile happens before
the version turns ``ready``), and a release hook for eviction.

A ``module:`` factory may return either a plain handler (legacy fleet
contract) or a :class:`LoadedModel` directly — the latter is how custom
models report their true byte footprint and warmup shape.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from mmlspark_tpu.serving.modelstore.store import LoadedModel
from mmlspark_tpu.serving.server import CachedRequest


def model_name_from_spec(spec: str) -> str:
    """The model name a spec serves under (fleet worker registration and
    per-model routing): ``echo`` -> ``echo``, ``zoo:ResNet8`` ->
    ``ResNet8``, ``module:pkg.make`` -> ``make``, ``pipeline:/m/churn``
    -> ``churn``, ``vw:/s/vw-online-v000007.npz`` -> ``vw-online``
    (exactly the Publisher's ``-v%06d`` suffix strips so every snapshot
    of one online model registers under one stable name; a hand-named
    ``vw:/s/fraud-v2.npz`` keeps its full ``fraud-v2`` name)."""
    if spec.startswith("zoo:"):
        return spec[len("zoo:"):]
    if spec.startswith("module:"):
        return spec.rsplit(".", 1)[-1]
    if spec.startswith("pipeline:"):
        import os

        return os.path.basename(spec[len("pipeline:"):].rstrip("/")) or "pipeline"
    if spec.startswith("vw:"):
        import os
        import re

        stem = os.path.basename(spec[len("vw:"):])
        stem = stem[: -len(".npz")] if stem.endswith(".npz") else stem
        # exactly the Publisher's -v%06d suffix: a looser \d+ would
        # mangle user-named snapshots like fraud-v2.npz -> "fraud"
        return re.sub(r"-v\d{6}$", "", stem) or "vw"
    if spec.startswith("gbdt:"):
        import os
        import re

        stem = os.path.basename(spec[len("gbdt:"):])
        for ext in (".gbdt.json", ".json"):
            if stem.endswith(ext):
                stem = stem[: -len(ext)]
                break
        # the experiment controller's -r<rung> suffix: every rung model
        # of one trial serves under the trial's stable name
        return re.sub(r"-r\d+$", "", stem) or "gbdt"
    if spec.startswith("artifact:"):
        # ``artifact:<scheme>:<name>@<digest>[@peers]`` serves under the
        # name the delegate grammar would give the named file — digests
        # and peer hints never leak into the serving name
        from mmlspark_tpu.serving.artifacts import parse_spec

        scheme, name, _digest, _urls = parse_spec(spec)
        return model_name_from_spec(f"{scheme}:{name}")
    return spec


def _dummy_request(body: bytes) -> CachedRequest:
    return CachedRequest(
        id="__warmup__", epoch=0, method="POST", path="/", headers={},
        body=body,
    )


def tree_nbytes(obj: Any) -> int:
    """Best-effort device-byte estimate: sum ``nbytes`` over the array
    leaves of a pytree (jax or numpy). 0 when jax is unavailable or the
    object holds no arrays."""
    try:
        import jax

        return int(sum(
            getattr(leaf, "nbytes", 0) or 0
            for leaf in jax.tree_util.tree_leaves(obj)
        ))
    except Exception:  # noqa: BLE001 — accounting is advisory, not load-bearing
        return 0


def _echo_loaded() -> LoadedModel:
    def handler(reqs: list) -> dict:
        out = {}
        for r in reqs:
            try:
                body = json.loads(r.body) if r.body else {}
                out[r.id] = (200, json.dumps({"echo": body}).encode(), {})
            except ValueError as e:
                out[r.id] = (400, json.dumps({"error": str(e)}).encode(), {})
        return out

    def warmup() -> None:
        handler([_dummy_request(b'{"x": 0}')])

    return LoadedModel(handler=handler, nbytes=0, warmup=warmup,
                       meta={"spec": "echo"})


def _zoo_loaded(name: str) -> LoadedModel:
    from mmlspark_tpu.models import ImageFeaturizer

    feat = ImageFeaturizer(
        input_col="image", output_col="features", model_name=name,
    )
    inner = feat._build()
    size = feat.get("image_size") or (
        feat._schema.image_size if feat._schema is not None else 224
    )
    nbytes = tree_nbytes(inner.get("variables"))

    def handler(reqs: list) -> dict:
        out = {}
        imgs, ids = [], []
        for r in reqs:
            try:
                imgs.append(np.asarray(json.loads(r.body)["image"], np.uint8))
                ids.append(r.id)
            except (ValueError, KeyError) as e:
                out[r.id] = (400, json.dumps({"error": str(e)}).encode(), {})
        if imgs:
            feats = inner.apply_batch(np.stack(imgs))
            for rid, f in zip(ids, feats):
                out[rid] = (
                    200,
                    json.dumps(
                        {"features": np.asarray(f).tolist()}
                    ).encode(),
                    {},
                )
        return out

    def warmup() -> None:
        # one dummy batch through the REAL handler: compiles the backbone
        # for the 1-row bucket before the version turns ready
        inner.apply_batch(np.zeros((1, size, size, 3), np.uint8))

    def release() -> None:
        # drop the jit cache + replicated device variables; the reload
        # path is the spec itself
        inner._jit_cache.clear()
        inner._dev_vars = None

    return LoadedModel(
        handler=handler, nbytes=nbytes, warmup=warmup, release=release,
        meta={"spec": f"zoo:{name}", "image_size": size},
    )


def _pipeline_loaded(path: str) -> LoadedModel:
    """``pipeline:<saved-model-dir>`` — serve a compiled pipeline.

    Load: ``core.serialize.load_stage`` on the dir (a saved
    ``PipelineModel``, ``CompiledPipeline`` or any fitted Transformer).
    Compile: PipelineModels go through ``.compile()``; other transformers
    are wrapped in a one-stage CompiledPipeline so the fusable case still
    fuses. Warmup: plan+fuse+partition always; if the dir carries a
    ``warmup.json`` ({column: [values...]}) one transform runs through it
    so the bucket XLA compiles also happen before the version turns ready.
    Byte accounting sums array leaves across the fitted stages' params
    (same jax-tree walk as ``zoo:``), so the HBM budget sees real weights.

    Wire contract (documented in docs/modelstore.md): POST body is one
    JSON row ({column: value}), {"rows": [{column: value}, ...]}, or the
    columnar fast path {"cols": {column: [value, ...]}} — column-major
    arrays decoded ONCE per batch instead of dict-per-row, the
    data-plane shape for throughput clients; the reply carries only the
    pipeline's *output* columns per row. An optional ``"select":
    [column, ...]`` narrows the reply further (a featurize->head
    pipeline's full output echoes every intermediate vector — at
    data-plane rates the reply encode, not the model, becomes the
    bottleneck).

    The handler implements the serving/query.py SplitHandler protocol:
    ``prepare`` (JSON decode + column stacking across the whole
    dispatcher batch) runs on the batcher thread while ``execute`` (ONE
    fused transform at the bucket shape, split back per request) still
    runs the previous batch — so the fused program's device time is the
    only thing on the model queue's critical path.
    """
    import json as _json
    import os

    from mmlspark_tpu.compiler import CompiledPipeline
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.core.pipeline import PipelineModel, load_stage

    stage = load_stage(path)
    if isinstance(stage, CompiledPipeline):
        compiled = stage
    elif isinstance(stage, PipelineModel):
        compiled = stage.compile()
    else:
        compiled = CompiledPipeline(stages=[stage])
    compiled.build()
    nbytes = tree_nbytes([
        {name: s.get(name) for name in type(s).params()}
        for s in compiled.get("stages")
    ])
    out_cols = tuple(dict.fromkeys(
        c for n in compiled.plan.nodes for c in n.writes
    ))
    # an opaque stage (RenameColumn, Explode, Lambda) may produce columns
    # the plan cannot name — declared writes would silently drop them
    has_opaque = any(n.opaque for n in compiled.plan.nodes)

    def _dense(values: list) -> Any:
        """Stack uniform numeric-list columns to dense float64 arrays.
        JSON rows arrive as python lists, which ``_as_column`` keeps as an
        object column — and the fused segments' guards rightly refuse
        object dtype, so without this every serving request (and the
        warmup) would fall back to staged execution. float64 is JSON's
        own number precision; the staged and fused paths round it to f32
        identically. Ragged/non-numeric columns pass through untouched."""
        if values and all(isinstance(v, (list, tuple)) for v in values):
            try:
                return np.stack([np.asarray(v, dtype=np.float64) for v in values])
            except Exception:  # noqa: BLE001 — ragged/non-numeric: object path
                pass
        return values

    def _dense_col(values: Any) -> Any:
        """Decode one column-major JSON column in ONE numpy call: numeric
        scalar columns become f64 vectors, uniform list cells a stacked
        f64 matrix (same precision contract as ``_dense``); anything
        else stays a python list (object column)."""
        if not isinstance(values, list) or not values:
            raise ValueError("each cols entry must be a non-empty list")
        try:
            arr = np.asarray(values, dtype=np.float64)
        except (TypeError, ValueError):
            return values
        if arr.ndim >= 1 and arr.shape[0] == len(values):
            return arr
        return values

    def _score_cols(cols: dict, n_rows: int,
                    select: Any = None) -> list:
        """ONE fused transform over pre-stacked columns, split back into
        row dicts. Every wire form funnels here, so the fused program
        always runs at a dispatcher-batch bucket shape. ``select``
        narrows the reply columns BEFORE the per-row dict/JSON build —
        the encode cost is proportional to what the client asked for."""
        df = DataFrame.from_dict(cols)
        res = compiled.transform(df)
        if has_opaque or not out_cols:
            keep = [c for c in res.columns if c not in cols]
        else:
            keep = [c for c in out_cols if c in res.columns]
        if select is not None:
            keep = [c for c in keep if c in select]
        mats = {c: res[c] for c in keep}
        n = res.count()
        if n != n_rows:
            # a row-dropping stage (drop_na) broke the 1:1 reply
            # correspondence — a 400 beats silently mis-attributed scores
            raise ValueError(
                f"pipeline dropped {n_rows - n} of {n_rows} rows; "
                "per-row replies would misalign"
            )
        return [
            {
                c: (v[i].tolist() if hasattr(v[i], "tolist") else v[i])
                for c, v in mats.items()
            }
            for i in range(n)
        ]

    def _rows_to_cols(rows: list) -> dict:
        # union of keys: first-row keys would silently drop a column only
        # later rows carry; a row missing a key raises (isolated per
        # request by the batch fallback)
        names = list(dict.fromkeys(k for r in rows for k in r.keys()))
        return {k: _dense([r[k] for r in rows]) for k in names}

    def _score_rows(rows: list) -> list:
        return _score_cols(_rows_to_cols(rows), len(rows))

    def _select_of(body: Any) -> Any:
        if not isinstance(body, dict) or "select" not in body:
            return None
        sel = body["select"]
        if not isinstance(sel, list) or not all(
            isinstance(c, str) for c in sel
        ):
            raise ValueError("select must be a list of column names")
        return frozenset(sel)

    def _parse_one(r: Any) -> tuple:
        """-> (body, cols, n_rows, select). ``cols``: column name ->
        stacked array or python list, decoded once — the array fast path
        decodes the columnar body straight to f64 arrays with zero row
        dicts."""
        body = _json.loads(r.body) if r.body else {}
        sel = _select_of(body)
        if isinstance(body, dict) and "cols" in body:
            raw = body["cols"]
            if not isinstance(raw, dict) or not raw:
                raise ValueError("cols must be a non-empty object")
            cols = {k: _dense_col(v) for k, v in raw.items()}
            lens = {len(v) for v in cols.values()}
            if len(lens) != 1:
                raise ValueError(f"ragged cols lengths {sorted(lens)}")
            return body, cols, lens.pop(), sel
        rows = (
            body["rows"]
            if isinstance(body, dict) and "rows" in body else [body]
        )
        if (
            not isinstance(rows, list)
            or not rows
            or not all(isinstance(x, dict) for x in rows)
        ):
            raise ValueError("rows must be a non-empty list of objects")
        return body, _rows_to_cols(rows), len(rows), sel

    def _merge(parsed: list) -> dict:
        """Stack every request's columns into one batch column set. A
        column missing from some request (or shape-mismatched) raises —
        the executor then isolates per request."""
        names = list(dict.fromkeys(
            k for _, _, cols, _, _ in parsed for k in cols
        ))
        merged: dict = {}
        for k in names:
            parts = [cols[k] for _, _, cols, _, _ in parsed]
            if all(isinstance(p, np.ndarray) for p in parts):
                merged[k] = np.concatenate(parts, axis=0)
            else:
                flat: list = []
                for p in parts:
                    flat.extend(p.tolist() if isinstance(p, np.ndarray) else p)
                merged[k] = flat
        return merged

    def _reply(body: Any, scored: list, sel: Any = None) -> tuple:
        if sel is not None:
            scored = [
                {k: v for k, v in row.items() if k in sel}
                for row in scored
            ]
        payload = (
            {"rows": scored}
            if isinstance(body, dict) and ("rows" in body or "cols" in body)
            else scored[0]
        )
        return (200, _json.dumps(payload).encode(), {})

    def _err(e: Exception) -> tuple:
        return (400, _json.dumps({"error": str(e)[:300]}).encode(), {})

    def prepare(reqs: list) -> tuple:
        """Host half (overlaps the previous batch's fused transform):
        parse every request, decode columns once, stack the whole
        dispatcher batch into one column set."""
        out: dict = {}
        parsed: list = []  # (request, body, cols, n_rows, select)
        for r in reqs:
            try:
                body, cols, n, sel = _parse_one(r)
                parsed.append((r, body, cols, n, sel))
            except Exception as e:  # noqa: BLE001 — bad row must not kill the batch
                out[r.id] = _err(e)
        merged = None
        if parsed:
            try:
                merged = _merge(parsed)
            except Exception:  # noqa: BLE001 — executor isolates per request
                merged = None
        return out, parsed, merged

    def execute(staged: tuple) -> dict:
        out, parsed, merged = staged
        if not parsed:
            return out
        # batch-level select: only when EVERY request narrowed its reply
        # can the expensive row-dict build skip the unselected columns;
        # mixed batches build the union and filter per request
        sels = [sel for *_, sel in parsed]
        batch_sel = (
            frozenset().union(*sels) if all(s is not None for s in sels)
            else None
        )
        try:
            if merged is None:
                raise ValueError("batch column merge failed")
            # one fused transform for the whole dispatcher batch (the
            # batching the dispatcher exists to provide), split back by
            # row spans
            scored = _score_cols(
                merged, sum(n for _, _, _, n, _ in parsed), batch_sel
            )
            pos = 0
            for r, body, _cols, n, sel in parsed:
                out[r.id] = _reply(body, scored[pos:pos + n], sel)
                pos += n
        except Exception:  # noqa: BLE001 — isolate the poisoned request
            for r, body, cols, n, sel in parsed:
                try:
                    out[r.id] = _reply(body, _score_cols(cols, n, sel), sel)
                except Exception as e:  # noqa: BLE001
                    out[r.id] = _err(e)
        return out

    from mmlspark_tpu.serving.query import SplitHandler

    handler = SplitHandler(prepare, execute)

    warmup_path = os.path.join(path, "warmup.json")

    def warmup() -> None:
        compiled.build()
        if os.path.exists(warmup_path):
            with open(warmup_path) as f:
                cols = _json.load(f)
            cols = {k: _dense(v) for k, v in cols.items()}
            compiled.transform(DataFrame.from_dict(cols))

    def release() -> None:
        # drop segment jit caches; the reload path is the spec itself
        for seg in compiled.segments:
            cache = getattr(seg, "_jit_cache", None)
            if cache is not None:
                cache.clear()

    return LoadedModel(
        handler=handler, nbytes=nbytes, warmup=warmup, release=release,
        meta={
            "spec": f"pipeline:{path}",
            "stages": [type(s).__name__ for s in compiled.get("stages")],
            "fused_stages": compiled.num_fused_stages,
            "output_columns": list(out_cols),
        },
    )


def _vw_loaded(path: str) -> LoadedModel:
    """``vw:<snapshot.npz>`` — serve an online-published VW linear model
    from device memory (mmlspark_tpu/online/ Publisher artifacts; also
    loadable standalone for warm worker restarts via ``--load``).

    The npz carries ``weights`` (2^num_bits f32) and ``meta`` (JSON:
    num_bits, loss, no_constant, quantile_tau). Wire contract
    (docs/online-learning.md): POST body is one sparse row
    ``{"i": [...], "v": [...]}`` or ``{"rows": [...]}`` of them; the
    reply carries ``margin`` plus ``prediction`` (and ``probability``
    for logistic). Batches pad to 8-row/8-nnz buckets so the compile
    set stays bounded; warmup runs one dummy bucket through the real
    scoring kernel before the version turns ready."""
    import jax.numpy as jnp

    from mmlspark_tpu.vw.learner import LOSS_HINGE, LOSS_LOGISTIC, LOSS_POISSON
    from mmlspark_tpu.vw.sparse import pad_sparse_batch

    with np.load(path, allow_pickle=False) as z:
        weights = np.asarray(z["weights"], np.float32)
        meta = json.loads(bytes(z["meta"]))
    num_bits = int(meta["num_bits"])
    loss = meta.get("loss", "logistic")
    no_constant = bool(meta.get("no_constant", False))
    if weights.shape != (1 << num_bits,):
        raise ValueError(
            f"vw snapshot {path}: weights shape {weights.shape} != "
            f"({1 << num_bits},)"
        )
    state = {"w": jnp.asarray(weights)}

    def _score(rows: list) -> list:
        from mmlspark_tpu.vw.estimators import _append_constant
        from mmlspark_tpu.vw.learner import _predict_margin

        norm = np.empty(len(rows), dtype=object)
        for r, cell in enumerate(rows):
            norm[r] = {"i": cell["i"], "v": cell["v"]}
        idx, val = pad_sparse_batch(norm)
        if not no_constant:
            idx, val = _append_constant(idx, val, num_bits)
        pad = -len(idx) % 8  # 8-row bucket: bounded compile set
        if pad:
            idx = np.pad(idx, ((0, pad), (0, 0)))
            val = np.pad(val, ((0, pad), (0, 0)))
        margins = np.asarray(_predict_margin(
            jnp.asarray(idx, jnp.int32), jnp.asarray(val), state["w"]
        ))[: len(rows)].astype(np.float64)
        out = []
        for m in margins:
            row = {"margin": float(m)}
            if loss in (LOSS_LOGISTIC, LOSS_HINGE):
                row["prediction"] = float(m > 0)
                if loss == LOSS_LOGISTIC:
                    row["probability"] = float(1.0 / (1.0 + np.exp(-m)))
            elif loss == LOSS_POISSON:
                row["prediction"] = float(np.exp(np.clip(m, -30.0, 30.0)))
            else:
                row["prediction"] = float(m)
            out.append(row)
        return out

    def handler(reqs: list) -> dict:
        out = {}
        for r in reqs:
            try:
                body = json.loads(r.body) if r.body else {}
                rows = (
                    body["rows"]
                    if isinstance(body, dict) and "rows" in body else [body]
                )
                if not rows or not all(
                    isinstance(x, dict) and "i" in x and "v" in x
                    for x in rows
                ):
                    raise ValueError(
                        'rows must be sparse objects {"i": [...], "v": [...]}'
                    )
                scored = _score(rows)
                payload = (
                    {"rows": scored}
                    if isinstance(body, dict) and "rows" in body
                    else scored[0]
                )
                out[r.id] = (200, json.dumps(payload).encode(), {})
            except Exception as e:  # noqa: BLE001 — a bad row 400s alone
                out[r.id] = (
                    400, json.dumps({"error": str(e)[:300]}).encode(), {}
                )
        return out

    def warmup() -> None:
        _score([{"i": [0], "v": [0.0]}])

    def release() -> None:
        state["w"] = None

    return LoadedModel(
        handler=handler, nbytes=int(weights.nbytes), warmup=warmup,
        release=release,
        meta={"spec": f"vw:{path}", **meta},
    )


def _gbdt_loaded(path: str) -> LoadedModel:
    """``gbdt:<model.json>`` — serve a trained GBDT booster from its
    portable model string (``Booster.to_model_string`` — what ``fleet
    train --out-model`` writes and the experiment controller publishes
    by digest). Wire contract: POST body is one dense row
    ``{"features": [...]}`` or ``{"rows": [[...], ...]}``; each reply
    row carries the raw ``margin`` plus ``prediction`` (and, for the
    binary objective, ``probability``)."""
    from mmlspark_tpu.models.gbdt.booster import Booster

    with open(path) as f:
        text = f.read()
    state = {"b": Booster.from_model_string(text)}
    objective = state["b"].objective
    n_features = int(getattr(state["b"], "num_features", 0) or 0)

    def _score(rows: list) -> list:
        x = np.asarray(rows, dtype=np.float32)
        if x.ndim != 2:
            raise ValueError("rows must be dense feature vectors")
        margins = np.asarray(state["b"].predict(x), dtype=np.float64)
        out = []
        for m in np.atleast_1d(margins):
            if getattr(m, "ndim", 0):  # multiclass: argmax over scores
                row = {
                    "margin": [float(v) for v in m],
                    "prediction": float(int(np.argmax(m))),
                }
            else:
                row = {"margin": float(m)}
                if objective == "binary":
                    row["prediction"] = float(m > 0)
                    row["probability"] = float(1.0 / (1.0 + np.exp(-m)))
                else:
                    row["prediction"] = float(m)
            out.append(row)
        return out

    def handler(reqs: list) -> dict:
        out = {}
        for r in reqs:
            try:
                body = json.loads(r.body) if r.body else {}
                if isinstance(body, dict) and "rows" in body:
                    scored = _score(body["rows"])
                    payload: Any = {"rows": scored}
                elif isinstance(body, dict) and "features" in body:
                    payload = _score([body["features"]])[0]
                else:
                    raise ValueError(
                        'body must be {"features": [...]} or '
                        '{"rows": [[...], ...]}'
                    )
                out[r.id] = (200, json.dumps(payload).encode(), {})
            except Exception as e:  # noqa: BLE001 — a bad row 400s alone
                out[r.id] = (
                    400, json.dumps({"error": str(e)[:300]}).encode(), {}
                )
        return out

    def warmup() -> None:
        _score([[0.0] * max(1, n_features)])

    def release() -> None:
        state["b"] = None

    return LoadedModel(
        handler=handler, nbytes=len(text), warmup=warmup, release=release,
        meta={"spec": f"gbdt:{path}", "objective": objective},
    )


def build_loaded_model(spec: Any) -> LoadedModel:
    """Resolve a model spec:

    - :class:`LoadedModel` — passed through unchanged;
    - callable            — treated as a bare batch handler;
    - ``"echo"``          — JSON echo (smoke tests / drills);
    - ``"zoo:<name>"``    — ImageFeaturizer on the named zoo backbone,
      with weight-byte accounting and a compile-warmup batch;
    - ``"module:pkg.fn"`` — ``pkg.fn()`` returning a handler OR a
      :class:`LoadedModel`;
    - ``"pipeline:<dir>"`` — a saved PipelineModel/CompiledPipeline dir,
      compiled (plan+fuse+partition) before ready, with jax-tree byte
      accounting over the fitted stages;
    - ``"vw:<snapshot.npz>"`` — an online-published VW linear model
      (mmlspark_tpu/online/ Publisher artifact), scored on device;
    - ``"gbdt:<model.json>"`` — a trained GBDT booster model string
      (``fleet train --out-model`` / experiment-controller winner);
    - ``"artifact:<scheme>:<name>@<sha256>[@peer-url,...]"`` — fetch a
      content-addressed artifact from any advertising peer (hash-
      verified, resumable; serving/artifacts.py), then delegate to
      ``<scheme>:<local path>``.
    """
    if isinstance(spec, LoadedModel):
        return spec
    if callable(spec):
        return LoadedModel(handler=spec)
    if not isinstance(spec, str):
        raise ValueError(f"unsupported model spec {spec!r}")
    if spec == "echo":
        return _echo_loaded()
    if spec.startswith("zoo:"):
        return _zoo_loaded(spec[len("zoo:"):])
    if spec.startswith("pipeline:"):
        return _pipeline_loaded(spec[len("pipeline:"):])
    if spec.startswith("vw:"):
        return _vw_loaded(spec[len("vw:"):])
    if spec.startswith("gbdt:"):
        return _gbdt_loaded(spec[len("gbdt:"):])
    if spec.startswith("artifact:"):
        # content-addressed spec (serving/artifacts.py): fetch the blob
        # by digest (spec-embedded peer hints first, then every
        # registry-advertised peer), hash-verify, then delegate to the
        # ordinary grammar on the verified local copy — so operators can
        # push models to workers without shell access to their disks
        from mmlspark_tpu.serving.artifacts import resolve_spec

        return build_loaded_model(resolve_spec(spec))
    if spec.startswith("module:"):
        import importlib

        mod_name, _, fn_name = spec[len("module:"):].rpartition(".")
        obj = getattr(importlib.import_module(mod_name), fn_name)()
        if isinstance(obj, LoadedModel):
            return obj
        return LoadedModel(handler=obj, meta={"spec": spec})
    raise ValueError(f"unknown model spec {spec!r}")
