"""Spec -> :class:`LoadedModel` resolution for the ModelStore.

Understands the fleet CLI's model specs (``echo`` / ``zoo:<name>`` /
``module:pkg.fn``) and adds what the store needs beyond a bare handler:
a device-byte estimate for the residency budget, a warmup that runs one
dummy bucket batch through the model (so the XLA compile happens before
the version turns ``ready``), and a release hook for eviction.

A ``module:`` factory may return either a plain handler (legacy fleet
contract) or a :class:`LoadedModel` directly — the latter is how custom
models report their true byte footprint and warmup shape.
"""

from __future__ import annotations

import json
from typing import Any

import numpy as np

from mmlspark_tpu.serving.modelstore.store import LoadedModel
from mmlspark_tpu.serving.server import CachedRequest


def model_name_from_spec(spec: str) -> str:
    """The model name a spec serves under (fleet worker registration and
    per-model routing): ``echo`` -> ``echo``, ``zoo:ResNet8`` ->
    ``ResNet8``, ``module:pkg.make`` -> ``make``."""
    if spec.startswith("zoo:"):
        return spec[len("zoo:"):]
    if spec.startswith("module:"):
        return spec.rsplit(".", 1)[-1]
    return spec


def _dummy_request(body: bytes) -> CachedRequest:
    return CachedRequest(
        id="__warmup__", epoch=0, method="POST", path="/", headers={},
        body=body,
    )


def tree_nbytes(obj: Any) -> int:
    """Best-effort device-byte estimate: sum ``nbytes`` over the array
    leaves of a pytree (jax or numpy). 0 when jax is unavailable or the
    object holds no arrays."""
    try:
        import jax

        return int(sum(
            getattr(leaf, "nbytes", 0) or 0
            for leaf in jax.tree_util.tree_leaves(obj)
        ))
    except Exception:  # noqa: BLE001 — accounting is advisory, not load-bearing
        return 0


def _echo_loaded() -> LoadedModel:
    def handler(reqs: list) -> dict:
        out = {}
        for r in reqs:
            try:
                body = json.loads(r.body) if r.body else {}
                out[r.id] = (200, json.dumps({"echo": body}).encode(), {})
            except ValueError as e:
                out[r.id] = (400, json.dumps({"error": str(e)}).encode(), {})
        return out

    def warmup() -> None:
        handler([_dummy_request(b'{"x": 0}')])

    return LoadedModel(handler=handler, nbytes=0, warmup=warmup,
                       meta={"spec": "echo"})


def _zoo_loaded(name: str) -> LoadedModel:
    from mmlspark_tpu.models import ImageFeaturizer

    feat = ImageFeaturizer(
        input_col="image", output_col="features", model_name=name,
    )
    inner = feat._build()
    size = feat.get("image_size") or (
        feat._schema.image_size if feat._schema is not None else 224
    )
    nbytes = tree_nbytes(inner.get("variables"))

    def handler(reqs: list) -> dict:
        out = {}
        imgs, ids = [], []
        for r in reqs:
            try:
                imgs.append(np.asarray(json.loads(r.body)["image"], np.uint8))
                ids.append(r.id)
            except (ValueError, KeyError) as e:
                out[r.id] = (400, json.dumps({"error": str(e)}).encode(), {})
        if imgs:
            feats = inner.apply_batch(np.stack(imgs))
            for rid, f in zip(ids, feats):
                out[rid] = (
                    200,
                    json.dumps(
                        {"features": np.asarray(f).tolist()}
                    ).encode(),
                    {},
                )
        return out

    def warmup() -> None:
        # one dummy batch through the REAL handler: compiles the backbone
        # for the 1-row bucket before the version turns ready
        inner.apply_batch(np.zeros((1, size, size, 3), np.uint8))

    def release() -> None:
        # drop the jit cache + replicated device variables; the reload
        # path is the spec itself
        inner._jit_cache.clear()
        inner._dev_vars = None

    return LoadedModel(
        handler=handler, nbytes=nbytes, warmup=warmup, release=release,
        meta={"spec": f"zoo:{name}", "image_size": size},
    )


def build_loaded_model(spec: Any) -> LoadedModel:
    """Resolve a model spec:

    - :class:`LoadedModel` — passed through unchanged;
    - callable            — treated as a bare batch handler;
    - ``"echo"``          — JSON echo (smoke tests / drills);
    - ``"zoo:<name>"``    — ImageFeaturizer on the named zoo backbone,
      with weight-byte accounting and a compile-warmup batch;
    - ``"module:pkg.fn"`` — ``pkg.fn()`` returning a handler OR a
      :class:`LoadedModel`.
    """
    if isinstance(spec, LoadedModel):
        return spec
    if callable(spec):
        return LoadedModel(handler=spec)
    if not isinstance(spec, str):
        raise ValueError(f"unsupported model spec {spec!r}")
    if spec == "echo":
        return _echo_loaded()
    if spec.startswith("zoo:"):
        return _zoo_loaded(spec[len("zoo:"):])
    if spec.startswith("module:"):
        import importlib

        mod_name, _, fn_name = spec[len("module:"):].rpartition(".")
        obj = getattr(importlib.import_module(mod_name), fn_name)()
        if isinstance(obj, LoadedModel):
            return obj
        return LoadedModel(handler=obj, meta={"spec": spec})
    raise ValueError(f"unknown model spec {spec!r}")
