"""ModelStore: versioned, HBM-budgeted model residency on a serving worker.

The reference bakes one handler into each serving worker at startup; any
weight update means killing the process. Production model servers
(TF-Serving's server-side model management, arxiv 1605.08695) own the
model *lifecycle* instead: named models, integer versions, background
load + warmup off the hot path, an atomic serving alias, and accounting
of what actually lives in accelerator memory. This module is that layer
for the TPU rebuild:

- **Versions** — ``load(name, spec)`` builds version ``n+1`` while
  version ``n`` keeps serving; nothing ever blocks the dispatch path.
- **Warmup before visibility** — a version is ``ready`` only after its
  loader ran and its warmup batch compiled/executed, so the first real
  request never pays a compile (the cold-start fix in fleet.run_worker
  rides this: workers warm up BEFORE registering).
- **Atomic hot-swap** — ``swap`` flips the serving alias under the store
  lock. In-flight batches hold a refcount on the version they resolved,
  so they finish on the old weights; the next batch resolves the new
  ones. Zero requests dropped, by construction (asserted under chaos in
  tests/test_modelstore.py).
- **Budgeted residency** — ``budget_bytes`` caps resident weight bytes.
  Loading past the budget evicts least-recently-used unpinned,
  non-serving, drained versions; a swap's outgoing version auto-evicts
  once its last in-flight batch releases it (unless pinned for instant
  rollback). When nothing evictable remains, the load FAILS with
  :class:`HBMBudgetExceeded` rather than silently thrashing device memory.

Fault points ``modelstore.load`` / ``modelstore.swap`` (core/faults.py)
fire at the top of the respective operations: an injected delay
simulates a slow deserialize/flip (the hot-swap chaos test drives
traffic through one), an injected error a failed load/swap.

Metrics (docs/observability.md): ``mmlspark_modelstore_resident_bytes``
/ ``_resident_models_count`` gauges, ``_loads_total`` / ``_swaps_total``
/ ``_evictions_total`` counters, ``_load_seconds`` / ``_warmup_seconds``
histograms.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults

_M_RESIDENT = obs.gauge(
    "mmlspark_modelstore_resident_bytes",
    "Model weight bytes currently resident in device memory",
)
_M_RESIDENT_N = obs.gauge(
    "mmlspark_modelstore_resident_models_count",
    "Model versions currently resident (warming or ready)",
)
_M_REFS = obs.gauge(
    "mmlspark_modelstore_version_refs_count",
    "In-flight batch references held on model versions (acquire minus "
    "release). MUST drain to zero after traffic stops — a stuck "
    "refcount pins swapped-out versions forever; the invariant "
    "checker's drain law (chaos/invariants.py)",
)
_M_LOADS = obs.counter(
    "mmlspark_modelstore_loads_total",
    "Model versions loaded to ready", labels=("model",),
)
_M_LOAD_FAILS = obs.counter(
    "mmlspark_modelstore_load_failures_total",
    "Model version loads that failed", labels=("model",),
)
_M_SWAPS = obs.counter(
    "mmlspark_modelstore_swaps_total",
    "Serving-alias flips to a new version", labels=("model",),
)
_M_EVICTIONS = obs.counter(
    "mmlspark_modelstore_evictions_total",
    "Versions evicted from device memory (budget LRU or post-swap drain)",
    labels=("model",),
)
_M_LOAD_S = obs.histogram(
    "mmlspark_modelstore_load_seconds",
    "Deserialize+build wall time per version", labels=("model",),
)
_M_WARMUP_S = obs.histogram(
    "mmlspark_modelstore_warmup_seconds",
    "Warmup (dummy bucket batch incl. compile) wall time per version",
    labels=("model",),
)

# version lifecycle states (listed in GET /models)
LOADING = "loading"
WARMING = "warming"
READY = "ready"
FAILED = "failed"
EVICTED = "evicted"


class ModelStoreError(Exception):
    """Invalid lifecycle operation (unknown version, swap to non-ready...)."""


class HBMBudgetExceeded(ModelStoreError):
    """The residency budget cannot fit the new version even after evicting
    every eligible (unpinned, non-serving, drained) resident version."""


@dataclass
class LoadedModel:
    """What a loader returns: the batch handler plus residency hooks.

    ``handler``  — ``list[CachedRequest] -> dict[id, (code, body, hdrs)]``,
    the same contract as :class:`~mmlspark_tpu.serving.query.ServingQuery`.
    ``nbytes``   — device bytes this model's weights occupy (best effort;
    0 for weightless handlers like ``echo``). ``warmup`` — run one dummy
    bucket batch through the model so the XLA compile happens off the hot
    path. ``release`` — drop device residency (called at eviction; the
    default is dropping the Python references so the arrays free)."""

    handler: Callable[[list], dict]
    nbytes: int = 0
    warmup: Optional[Callable[[], None]] = None
    release: Optional[Callable[[], None]] = None
    meta: dict = field(default_factory=dict)


class ModelVersion:
    """One (name, version) entry. Mutable fields are guarded by the owning
    store's lock; ``inflight`` counts batches currently executing on this
    version (the hot-swap drain barrier)."""

    __slots__ = (
        "name", "version", "spec", "state", "error", "pinned", "loaded",
        "nbytes", "inflight", "retiring", "resident", "last_used",
        "loaded_at", "unloaded",
    )

    def __init__(self, name: str, version: int, spec: Any):
        self.name = name
        self.version = version
        self.spec = spec
        self.state = LOADING
        self.error: Optional[str] = None
        self.pinned = False
        self.loaded: Optional[LoadedModel] = None
        self.nbytes = 0
        self.inflight = 0
        self.retiring = False
        self.resident = False
        self.last_used = 0.0
        self.loaded_at = 0.0
        # tombstone: unload() of an in-progress (loading/warming) version
        # cannot stop its loader thread, so it marks the version instead;
        # the loader checks the mark and cleans up rather than turning the
        # orphan resident/serving
        self.unloaded = False

    def describe(self) -> dict:
        return {
            "version": self.version,
            "state": self.state,
            "nbytes": self.nbytes,
            "pinned": self.pinned,
            "inflight": self.inflight,
            "error": self.error,
            "spec": self.spec if isinstance(self.spec, str) else None,
        }


class ModelStore:
    """Thread-safe model registry + residency manager for one worker
    process. ``loader`` maps a spec to a :class:`LoadedModel` (default:
    :func:`~mmlspark_tpu.serving.modelstore.loaders.build_loaded_model`,
    which understands the fleet's ``echo`` / ``zoo:`` / ``module:`` specs
    and passes :class:`LoadedModel` instances through)."""

    # dead (evicted/failed) version entries kept per model for
    # post-mortem visibility in GET /models; older tombstones are pruned
    # at the next load so long-lived hot-swapping workers stay bounded
    KEEP_DEAD_VERSIONS = 8

    def __init__(
        self,
        budget_bytes: Optional[int] = None,
        loader: Optional[Callable[[Any], LoadedModel]] = None,
    ):
        if loader is None:
            from mmlspark_tpu.serving.modelstore.loaders import (
                build_loaded_model,
            )

            loader = build_loaded_model
        self._loader = loader
        self.budget_bytes = budget_bytes
        self._lock = threading.RLock()
        self._models: dict[str, dict[int, ModelVersion]] = {}
        self._alias: dict[str, int] = {}
        self._resident_bytes = 0
        self._resident_count = 0
        self._refs_total = 0  # acquire minus release, store-wide

    # -- introspection -------------------------------------------------------

    def model_names(self) -> list:
        with self._lock:
            return sorted(self._models)

    def serving_version(self, name: str) -> Optional[int]:
        with self._lock:
            return self._alias.get(name)

    def serving_state(self, name: str) -> Optional[str]:
        """None = unknown model; else the state a data-path request would
        see: ``ready`` when the alias points at a ready version, otherwise
        the most advanced version's state (what /health and the 503
        ``x-mmlspark-model-state`` header report)."""
        with self._lock:
            vers = self._models.get(name)
            if not vers:
                return None
            v = self._alias.get(name)
            if v is not None and v in vers and vers[v].state == READY:
                return READY
            for mv in sorted(vers.values(), key=lambda m: -m.version):
                if mv.state in (LOADING, WARMING):
                    return mv.state
            return next(iter(vers.values())).state

    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident_bytes

    def models(self) -> dict:
        """The GET /models listing shape."""
        with self._lock:
            return {
                name: {
                    "serving": self._alias.get(name),
                    "versions": [
                        vers[v].describe() for v in sorted(vers)
                    ],
                }
                for name, vers in self._models.items()
            }

    # -- residency accounting (call under lock) ------------------------------

    def _set_resident(self, mv: ModelVersion, resident: bool) -> None:
        if resident and not mv.resident:
            mv.resident = True
            self._resident_bytes += mv.nbytes
            self._resident_count += 1
        elif not resident and mv.resident:
            mv.resident = False
            self._resident_bytes -= mv.nbytes
            self._resident_count -= 1
        _M_RESIDENT.set(self._resident_bytes)
        _M_RESIDENT_N.set(self._resident_count)

    def _evict_locked(self, mv: ModelVersion) -> None:
        """Drop a version's device residency. Caller holds the lock and has
        checked eligibility (not serving, drained)."""
        loaded, mv.loaded = mv.loaded, None
        mv.state = EVICTED
        mv.retiring = False
        self._set_resident(mv, False)
        _M_EVICTIONS.labels(model=mv.name).inc()
        if loaded is not None and loaded.release is not None:
            try:
                loaded.release()
            except Exception:  # noqa: BLE001 — eviction must not wedge the store
                pass

    def _ensure_budget_locked(self, needed: int, protect: ModelVersion) -> None:
        """Evict LRU eligible versions until ``needed`` more bytes fit.
        Eligible: READY (a warming version's load thread is still using
        the weights — evicting it would brick the version), resident, not
        pinned, not the serving alias, drained, and not the version being
        loaded."""
        if self.budget_bytes is None:
            return
        while self._resident_bytes + needed > self.budget_bytes:
            candidates = [
                mv
                for name, vers in self._models.items()
                for mv in vers.values()
                if mv.resident
                and mv.state == READY
                and mv is not protect
                and not mv.pinned
                and mv.inflight == 0
                and self._alias.get(name) != mv.version
            ]
            if not candidates:
                raise HBMBudgetExceeded(
                    f"cannot fit {needed} bytes: {self._resident_bytes} "
                    f"resident of {self.budget_bytes} budget and no "
                    "evictable (unpinned, non-serving, drained) version"
                )
            self._evict_locked(min(candidates, key=lambda m: m.last_used))

    # -- lifecycle -----------------------------------------------------------

    def load(
        self,
        name: str,
        spec: Any,
        version: Optional[int] = None,
        wait: bool = True,
        pin: bool = False,
        activate: str = "auto",
    ) -> int:
        """Load ``spec`` as a new version of ``name``. Returns the version
        number immediately when ``wait=False`` (the load+warmup runs on a
        background thread; progress is visible in :meth:`models`), else
        after the version is ready (raising on failure).

        ``activate``: ``"auto"`` aliases the version only when the model
        has no serving version yet (first load serves immediately; later
        loads wait for an explicit :meth:`swap`); ``"always"`` flips the
        alias as soon as the version is ready; ``"never"`` never does."""
        if activate not in ("auto", "always", "never"):
            raise ValueError(f"unknown activate mode {activate!r}")
        with self._lock:
            vers = self._models.setdefault(name, {})
            if version is None:
                version = max(vers) + 1 if vers else 1
            existing = vers.get(version)
            if existing is not None and existing.state not in (FAILED, EVICTED):
                raise ModelStoreError(
                    f"{name} v{version} already exists ({existing.state})"
                )
            mv = ModelVersion(name, version, spec)
            mv.pinned = pin
            vers[version] = mv
            # bounded version history: a worker hot-swapping for months
            # must not grow the listing (and every swap/serving_state
            # scan) with dead tombstones forever — keep the newest few
            dead = sorted(
                v for v, m in vers.items()
                if m.state in (FAILED, EVICTED) and not m.pinned
            )
            for v in dead[:-self.KEEP_DEAD_VERSIONS or None]:
                del vers[v]
        if wait:
            self._do_load(mv, activate)
        else:
            threading.Thread(
                target=self._do_load_quiet, args=(mv, activate),
                name=f"modelstore-load-{name}-v{version}", daemon=True,
            ).start()
        return version

    def _do_load_quiet(self, mv: ModelVersion, activate: str) -> None:
        try:
            self._do_load(mv, activate)
        except Exception:  # noqa: BLE001 — state FAILED carries the error
            pass

    @staticmethod
    def _release_quietly(loaded: Optional[LoadedModel]) -> None:
        if loaded is not None and loaded.release is not None:
            try:
                loaded.release()
            except Exception:  # noqa: BLE001 — cleanup is best effort
                pass

    def _do_load(self, mv: ModelVersion, activate: str) -> None:
        t0 = time.perf_counter()
        loaded: Optional[LoadedModel] = None
        try:
            # fault point modelstore.load: an injected delay is a slow
            # deserialize (the background path must keep serving through
            # it); an injected error a corrupt artifact
            faults.inject(
                "modelstore.load",
                context={"model": mv.name, "version": mv.version},
            )
            loaded = self._loader(mv.spec)
            if not isinstance(loaded, LoadedModel):
                raise TypeError(
                    f"loader returned {type(loaded).__name__}, "
                    "expected LoadedModel"
                )
            with self._lock:
                if mv.unloaded:
                    mv.state = EVICTED
                else:
                    mv.nbytes = int(loaded.nbytes or 0)
                    self._ensure_budget_locked(mv.nbytes, protect=mv)
                    mv.loaded = loaded
                    mv.state = WARMING
                    self._set_resident(mv, True)
            if mv.state == EVICTED:  # unloaded while the loader ran
                self._release_quietly(loaded)
                return
            _M_LOAD_S.labels(model=mv.name).observe(time.perf_counter() - t0)
            if loaded.warmup is not None:
                w0 = time.perf_counter()
                loaded.warmup()
                _M_WARMUP_S.labels(model=mv.name).observe(
                    time.perf_counter() - w0
                )
            with self._lock:
                if mv.unloaded or mv.state != WARMING:
                    # unloaded while warming: do not resurrect the version
                    # as READY or recreate the alias of a deleted model —
                    # release the residency this thread took instead
                    if mv.resident:
                        self._set_resident(mv, False)
                    mv.loaded = None
                    mv.state = EVICTED
                else:
                    mv.state = READY
                    mv.loaded_at = mv.last_used = time.monotonic()
                    if activate == "always" or (
                        activate == "auto" and mv.name not in self._alias
                    ):
                        self._alias[mv.name] = mv.version
            if mv.state == EVICTED:
                self._release_quietly(loaded)
                return
            _M_LOADS.labels(model=mv.name).inc()
        except Exception as e:
            with self._lock:
                mv.error = f"{type(e).__name__}: {e}"
                if mv.resident:
                    self._set_resident(mv, False)
                mv.loaded = None
                mv.state = FAILED
            # the loader may have put weights on device before the
            # failure (budget rejection, warmup crash): release them like
            # the eviction path would, don't rely on GC
            self._release_quietly(loaded)
            _M_LOAD_FAILS.labels(model=mv.name).inc()
            raise

    def swap(self, name: str, version: Optional[int] = None) -> int:
        """Atomically flip the serving alias of ``name`` to ``version``
        (default: the newest ready non-serving version). In-flight batches
        drain on the old version; once drained it is evicted unless
        pinned (pin the old version first for instant rollback)."""
        # fault point modelstore.swap: fires BEFORE the flip, so an
        # injected delay stalls only the control operation — traffic keeps
        # serving the old version (the zero-downtime property under test)
        faults.inject("modelstore.swap", context={"model": name})
        retire: Optional[ModelVersion] = None
        with self._lock:
            vers = self._models.get(name)
            if not vers:
                raise KeyError(f"unknown model {name!r}")
            cur = self._alias.get(name)
            if version is None:
                ready = [
                    v for v, mv in vers.items()
                    if mv.state == READY and v != cur
                ]
                if not ready:
                    raise ModelStoreError(
                        f"{name}: no ready non-serving version to swap to"
                    )
                version = max(ready)
            mv = vers.get(version)
            if mv is None:
                raise KeyError(f"unknown version {name} v{version}")
            if version == cur:
                return version
            mv.retiring = False  # a rollback target is no longer outgoing
            if mv.state != READY:
                raise ModelStoreError(
                    f"cannot swap {name} to v{version}: state {mv.state}"
                )
            self._alias[name] = version
            mv.last_used = time.monotonic()
            if cur is not None:
                old = vers.get(cur)
                if old is not None:
                    # retiring marks the version as swap-displaced; a
                    # pinned one stays resident (instant rollback) until
                    # unpinned, then goes
                    old.retiring = True
                    if old.inflight == 0 and old.resident and not old.pinned:
                        retire = old
            _M_SWAPS.labels(model=name).inc()
            if retire is not None:
                self._evict_locked(retire)
        return version

    def unload(self, name: str, version: Optional[int] = None) -> int:
        """Remove a version (or, with ``version=None``, the whole model
        incl. its serving alias). Returns the number of versions removed.
        In-flight batches finish — they hold their own reference — but no
        new batch resolves an unloaded version."""
        with self._lock:
            vers = self._models.get(name)
            if not vers:
                raise KeyError(f"unknown model {name!r}")
            doomed = (
                list(vers.values()) if version is None
                else [vers[version]] if version in vers
                else []
            )
            if not doomed:
                raise KeyError(f"unknown version {name} v{version}")
            for mv in doomed:
                if self._alias.get(name) == mv.version:
                    self._alias.pop(name, None)
                del vers[mv.version]
                mv.unloaded = True
                if mv.state in (LOADING, WARMING):
                    # the loader thread is still using the weights (a
                    # mid-warmup release would crash the warmup); it sees
                    # the tombstone and releases residency itself
                    continue
                if mv.resident:
                    if mv.inflight > 0:
                        # the last release() drops the residency (the
                        # version object keeps its own byte accounting;
                        # it no longer appears in the listing)
                        mv.pinned = False
                        mv.retiring = True
                    else:
                        self._evict_locked(mv)
            if version is None or not vers:
                self._models.pop(name, None)
                self._alias.pop(name, None)
            return len(doomed)

    def pin(self, name: str, version: Optional[int] = None,
            pinned: bool = True) -> int:
        """Pin (exempt from eviction — budget LRU and post-swap retire
        alike) or unpin a version; default: the serving version."""
        with self._lock:
            vers = self._models.get(name)
            if not vers:
                raise KeyError(f"unknown model {name!r}")
            if version is None:
                version = self._alias.get(name)
                if version is None:
                    raise ModelStoreError(f"{name}: no serving version to pin")
            mv = vers.get(version)
            if mv is None:
                raise KeyError(f"unknown version {name} v{version}")
            mv.pinned = pinned
            if not pinned and mv.retiring and mv.inflight == 0 and mv.resident:
                self._evict_locked(mv)
            return version

    # -- dispatch-path resolution (hot path) ---------------------------------

    def acquire(self, name: str) -> Optional[ModelVersion]:
        """Resolve the serving version and take an in-flight reference on
        it. Returns None when the model has no ready serving version. The
        caller MUST :meth:`release` after its batch completes — that
        reference is what lets a swapped-out version drain before
        eviction."""
        with self._lock:
            v = self._alias.get(name)
            if v is None:
                return None
            mv = self._models.get(name, {}).get(v)
            if mv is None or mv.state != READY or mv.loaded is None:
                return None
            mv.inflight += 1
            mv.last_used = time.monotonic()
            self._refs_total += 1
            if _M_REFS._on:
                _M_REFS.set(self._refs_total)
            return mv

    def release(self, mv: ModelVersion) -> None:
        with self._lock:
            mv.inflight -= 1
            self._refs_total -= 1
            if _M_REFS._on:
                _M_REFS.set(self._refs_total)
            if (
                mv.retiring and mv.inflight <= 0 and mv.resident
                and not mv.pinned
            ):
                self._evict_locked(mv)
