"""Distributed serving: N WorkerServers behind ONE gateway endpoint.

The reference runs one HTTP source per executor with the driver
aggregating ServiceInfos and fronting them with a load balancer
(DistributedHTTPSource.scala:26-130; deployment modes in
docs/mmlspark-serving.md:93-160). The TPU rebuild keeps the per-worker
WorkerServer/ServingQuery pair unchanged and adds:

- :class:`BackendPool` — the live-worker roster with round-robin pick and
  failure cooldown;
- :class:`ServingGateway` — a front door (itself a WorkerServer, so the
  epoch/history/replay machinery guards the client-facing queue) whose
  dispatcher threads forward each request to a backend worker and reply on
  the originating socket;
- cross-worker recovery: a request forwarded to a worker that dies
  mid-flight is re-dispatched to ANOTHER worker — the uncommitted-epoch
  replay of HTTPSourceV2.scala:470-487, landing on a different worker, so
  a worker crash loses zero accepted requests;
- :class:`DriverRegistry` discovery: pass ``registry_url`` and the pool
  refreshes from the roster, picking up workers that (re)register.
"""

from __future__ import annotations

import contextlib
import http.client
import json
import logging
import queue as queue_mod
import select
import socket
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.obs.flightrec import FLIGHT
from mmlspark_tpu.serving.admission import (
    DEADLINE_HEADER,
    RETRY_BUDGET_HEADER,
    SHED_HEADER,
    deadline_ms_from,
)
from mmlspark_tpu.serving.server import ServiceInfo, WorkerServer

log = logging.getLogger("mmlspark_tpu.serving")

_M_GW_FORWARDED = obs.counter(
    "mmlspark_gateway_requests_total",
    "Requests successfully forwarded and answered",
)
_M_GW_RETRIES = obs.counter(
    "mmlspark_gateway_retries_total",
    "Cross-worker re-dispatch attempts after a backend failure",
)
_M_GW_FAILED = obs.counter(
    "mmlspark_gateway_failures_total",
    "Requests the gateway answered with an error", labels=("reason",),
)
_M_GW_LATENCY = obs.histogram(
    "mmlspark_gateway_request_latency_seconds",
    "Gateway ingress arrival to reply (includes queue wait + retries)",
)
_M_GW_BACKENDS = obs.gauge(
    "mmlspark_gateway_backends_count", "Live backends in the pool",
)
_M_BE_REQS = obs.counter(
    "mmlspark_gateway_backend_requests_total",
    "Successful forwards per backend", labels=("backend",),
)
_M_BE_ERRS = obs.counter(
    "mmlspark_gateway_backend_errors_total",
    "Reported failures per backend", labels=("backend",),
)
_M_BE_EVICT = obs.counter(
    "mmlspark_gateway_backend_evictions_total",
    "Breaker-open events per backend (kept under the pre-breaker name "
    "so eviction dashboards keep working)", labels=("backend",),
)
_M_BE_BACKPRESSURE = obs.counter(
    "mmlspark_gateway_backend_backpressure_total",
    "429 sheds per backend (load shedding, classified as backpressure "
    "rather than failure)", labels=("backend",),
)
_M_BREAKER_STATE = obs.gauge(
    "mmlspark_gateway_breaker_state",
    "Per-backend circuit-breaker state (0=closed, 1=open, 2=half-open)",
    labels=("backend",),
)
_M_BREAKER_TRANSITIONS = obs.counter(
    "mmlspark_gateway_breaker_transitions_total",
    "Breaker state transitions", labels=("backend", "state"),
)
_M_RETRY_BUDGET_RATIO = obs.gauge(
    "mmlspark_gateway_retry_budget_remaining_ratio",
    "Fraction of the retry token bucket still available (1 = untouched)",
)
_M_RETRY_BUDGET_EXHAUSTED = obs.counter(
    "mmlspark_gateway_retry_budget_exhausted_total",
    "Re-dispatches refused because the retry budget was spent",
)
_M_HEDGES = obs.counter(
    "mmlspark_gateway_hedges_total",
    "Hedge requests fired (tail-latency duplicates)",
)
_M_HEDGE_WINS = obs.counter(
    "mmlspark_gateway_hedge_wins_total",
    "Requests answered by the hedge before the primary",
)
_M_CONN_REUSE = obs.counter(
    "mmlspark_gateway_conn_reuse_total",
    "Forwards sent on an already-open pooled worker connection",
)
_M_CONN_OPENED = obs.counter(
    "mmlspark_gateway_conn_opened_total",
    "Fresh worker connections opened (pool miss, stale replacement, "
    "or hedge-pool growth)",
)
_M_HEDGE_POOL = obs.gauge(
    "mmlspark_gateway_hedge_pool_connections_count",
    "Idle pooled connections reserved for hedged attempts",
)


# -- zero-re-parse wire client ------------------------------------------------

_WIRE_COUNT_LOCK = threading.Lock()


class WireConn:
    """Minimal HTTP/1.1 keep-alive client connection on a raw socket —
    the gateway's forwarding primitive.

    ``http.client`` re-serializes a header dict and runs a stateful
    feed-parser over every response; at data-plane rates that work IS the
    gateway. Here the request goes out as one ``sendall`` of
    pre-computed bytes (method line + the request's static header block
    + per-attempt lines, built once in ``_forward``), and the reply is
    parsed with a single splitting pass over the head — the raw body
    bytes are relayed to the client untouched.

    ``open_count()`` tracks live connections process-wide so tests can
    pin the no-socket-leak property of the pools.
    """

    _open = 0

    __slots__ = ("host", "port", "sock", "_buf", "_closed", "last_resp_bytes")

    def __init__(self, host: str, port: int, timeout: float):
        self.host = host
        self.port = port
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""
        self._closed = False
        self.last_resp_bytes = 0  # bytes of the in-progress response seen
        with _WIRE_COUNT_LOCK:
            WireConn._open += 1
        if _M_CONN_OPENED._on:
            _M_CONN_OPENED.inc()

    @classmethod
    def open_count(cls) -> int:
        with _WIRE_COUNT_LOCK:
            return cls._open

    def send(self, data: bytes) -> None:
        self.sock.sendall(data)

    def read_response(self) -> "WireResponse":
        """One response, one splitting pass: read to the blank line,
        split the head once, then read exactly Content-Length body
        bytes. Raises OSError subclasses (``socket.timeout`` IS
        ``TimeoutError``, so the at-most-once post-send logic sees the
        same exception shape as before)."""
        self.last_resp_bytes = len(self._buf)
        buf = self._buf
        while True:
            i = buf.find(b"\r\n\r\n")
            if i >= 0:
                break
            if len(buf) > 65536:
                raise ConnectionError("response head too large")
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionResetError("connection closed mid-response")
            buf += chunk
            self.last_resp_bytes = len(buf)
        head, rest = buf[:i], buf[i + 4:]
        lines = head.split(b"\r\n")
        parts = lines[0].split(None, 2)
        if len(parts) < 2:
            raise ConnectionError(f"torn status line {lines[0]!r}")
        try:
            status = int(parts[1])
        except ValueError:
            raise ConnectionError(
                f"non-numeric status {parts[1]!r}"
            ) from None
        hdrs: dict = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(b":")
            hdrs[k.strip().lower().decode("latin1")] = (
                v.strip().decode("latin1")
            )
        try:
            n = int(hdrs.get("content-length") or 0)
        except ValueError:
            raise ConnectionError("bad Content-Length") from None
        if len(rest) < n:
            out = [rest]
            got = len(rest)
            while got < n:
                chunk = self.sock.recv(min(65536, n - got))
                if not chunk:
                    raise ConnectionResetError("connection closed mid-body")
                out.append(chunk)
                got += len(chunk)
            rest = b"".join(out)
        body, self._buf = rest[:n], rest[n:]
        will_close = hdrs.get("connection", "keep-alive").lower() == "close"
        return WireResponse(status, hdrs, body, will_close)

    def alive(self) -> bool:
        """Is this idle pooled connection still usable? A dead worker's
        FIN (or any unread stray bytes) makes the socket readable —
        reusing it would turn 'worker stopped between requests' from a
        safe pre-send connect-refused into a send-then-hang 504.
        poll(), not select(): the gateway ingress holds an fd per
        client, so pooled fds routinely exceed select's FD_SETSIZE
        under load."""
        if self._closed:
            return False
        try:
            p = select.poll()
            p.register(self.sock, select.POLLIN)
            return not p.poll(0)
        except (OSError, ValueError):
            return False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with _WIRE_COUNT_LOCK:
            WireConn._open -= 1
        try:
            self.sock.close()
        except OSError:
            pass


class WireResponse:
    """The parsed reply: status + lowercase header dict + raw body bytes.
    ``getheader`` mirrors http.client's accessor so the routing logic
    reads unchanged."""

    __slots__ = ("status", "headers", "body", "will_close")

    def __init__(self, status: int, headers: dict, body: bytes,
                 will_close: bool):
        self.status = status
        self.headers = headers
        self.body = body
        self.will_close = will_close

    def getheader(self, name: str, default: Optional[str] = None):
        return self.headers.get(name.lower(), default)


def _head_bytes(method: str, target: str, host_line: bytes,
                static_block: bytes, extra: dict, nbody: int) -> bytes:
    """Assemble one request's head: the method line and per-attempt
    headers wrap the request's pre-computed static block — nothing is
    re-serialized per attempt except what actually changed (remaining
    deadline, parent span)."""
    parts = [
        f"{method} {target} HTTP/1.1\r\n".encode("latin1"),
        host_line,
        static_block,
    ]
    for k, v in extra.items():
        parts.append(f"{k}: {v}\r\n".encode("latin1"))
    parts.append(f"Content-Length: {nbody}\r\n\r\n".encode("latin1"))
    return b"".join(parts)


class HedgeConnPool:
    """Small shared side pool of :class:`WireConn` per backend for hedged
    attempts — hedges used to open (and leak under bursts, until GC) a
    fresh ``HTTPConnection`` per try. Check-out/check-in under one lock;
    a connection whose response wasn't fully consumed (the cancelled
    loser) is closed, never pooled."""

    def __init__(self, timeout: float, per_backend: int = 4):
        self._timeout = timeout
        self._cap = per_backend
        self._lock = threading.Lock()
        self._idle: dict = {}  # (host, port) -> [WireConn]

    def get(self, b: "Backend") -> tuple:
        key = (b.host, b.port)
        with self._lock:
            idle = self._idle.get(key)
            while idle:
                conn = idle.pop()
                self._update_gauge_locked()
                if conn.alive():
                    return conn, True
                conn.close()
        return WireConn(b.host, b.port, self._timeout), False

    def put(self, b: "Backend", conn: WireConn) -> None:
        key = (b.host, b.port)
        with self._lock:
            idle = self._idle.setdefault(key, [])
            if len(idle) < self._cap and not conn._closed:
                idle.append(conn)
                self._update_gauge_locked()
                return
        conn.close()

    def prune(self, members: list) -> None:
        """Drop pooled connections to backends no longer rostered."""
        live = {(m.host, m.port) for m in members}
        with self._lock:
            for key in [k for k in self._idle if k not in live]:
                for conn in self._idle.pop(key):
                    conn.close()
            self._update_gauge_locked()

    def idle_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._idle.values())

    def close_all(self) -> None:
        with self._lock:
            for conns in self._idle.values():
                for conn in conns:
                    conn.close()
            self._idle.clear()
            self._update_gauge_locked()

    def _update_gauge_locked(self) -> None:
        if _M_HEDGE_POOL._on:
            _M_HEDGE_POOL.set(sum(len(v) for v in self._idle.values()))


# -- circuit breaker ---------------------------------------------------------

BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN = 0, 1, 2
BREAKER_STATE_NAMES = {
    BREAKER_CLOSED: "closed",
    BREAKER_OPEN: "open",
    BREAKER_HALF_OPEN: "half_open",
}


class CircuitBreaker:
    """Per-backend closed -> open -> half-open state machine.

    Opens after ``open_after`` consecutive failures OR when the error
    rate over a sliding ``rate_window_s`` window crosses
    ``rate_threshold`` (with at least ``rate_min_volume`` outcomes — a
    1-for-1 sample must not open anything). While open, the backend is
    skipped entirely; after the open period (``cooldown_s``, doubled per
    consecutive open up to ``max_open_s``) ONE probe request is admitted
    (half-open). The probe's success closes the breaker; its failure
    re-opens with a longer period. ``open_after=0`` disables opening —
    the static-pool setting, where cooldown alone rate-limits attempts.

    Not self-locking: :class:`BackendPool` drives it under the pool lock.
    """

    def __init__(
        self,
        open_after: int = 3,
        cooldown_s: float = 5.0,
        rate_threshold: float = 0.5,
        rate_window_s: float = 30.0,
        rate_min_volume: int = 10,
        max_open_s: float = 60.0,
    ):
        self.open_after = open_after
        self.cooldown_s = cooldown_s
        self.rate_threshold = rate_threshold
        self.rate_window_s = rate_window_s
        self.rate_min_volume = rate_min_volume
        self.max_open_s = max_open_s
        self.state = BREAKER_CLOSED
        self.fails = 0          # consecutive failures
        self.opened_at = 0.0
        self.opens_in_a_row = 0  # exponential open-period backoff
        self.probe_inflight = False
        # (ts, ok) outcomes; maxlen bounds memory even at rates where the
        # time prune in _prune() lags (the rate check then covers the most
        # recent 4096 outcomes within the window, which is plenty of volume)
        self._window: deque = deque(maxlen=4096)

    def _prune(self, now: float) -> None:
        w = self._window
        while w and now - w[0][0] > self.rate_window_s:
            w.popleft()

    def _rate_trips(self, now: float) -> bool:
        self._prune(now)
        w = self._window
        if len(w) < self.rate_min_volume:
            return False
        errs = sum(1 for _, ok in w if not ok)
        return errs / len(w) >= self.rate_threshold

    def open_for_s(self) -> float:
        return min(
            self.cooldown_s * (2 ** max(0, self.opens_in_a_row - 1)),
            self.max_open_s,
        )

    def record_ok(self, now: float) -> Optional[int]:
        """Returns the new state on a transition, else None."""
        self._prune(now)  # the success path must not grow the window forever
        self._window.append((now, True))
        self.fails = 0
        self.probe_inflight = False
        if self.state != BREAKER_CLOSED:
            self.state = BREAKER_CLOSED
            self.opens_in_a_row = 0
            return BREAKER_CLOSED
        return None

    def record_failure(self, now: float) -> Optional[int]:
        self._prune(now)
        self._window.append((now, False))
        self.fails += 1
        self.probe_inflight = False
        if self.state == BREAKER_HALF_OPEN:
            # the probe failed: back to open, with a longer period
            self.state = BREAKER_OPEN
            self.opened_at = now
            self.opens_in_a_row += 1
            return BREAKER_OPEN
        if (
            self.state == BREAKER_CLOSED
            and self.open_after
            and (self.fails >= self.open_after or self._rate_trips(now))
        ):
            self.state = BREAKER_OPEN
            self.opened_at = now
            self.opens_in_a_row += 1
            return BREAKER_OPEN
        return None

    def allow(self, now: float) -> bool:
        """May a request be routed to this backend right now? Open ->
        half-open happens here (time-based), admitting exactly one
        probe until its outcome is reported."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN:
            if now - self.opened_at >= self.open_for_s():
                self.state = BREAKER_HALF_OPEN
                self.probe_inflight = True
                return True
            return False
        # half-open: one probe at a time
        if not self.probe_inflight:
            self.probe_inflight = True
            return True
        return False

    def reset(self) -> None:
        """Back to closed with a clean slate (a re-registered backend is
        a new process — its predecessor's failures prove nothing)."""
        self.state = BREAKER_CLOSED
        self.fails = 0
        self.opens_in_a_row = 0
        self.probe_inflight = False
        self._window.clear()


class RetryBudget:
    """Token bucket capping re-dispatch volume at ``ratio`` of recent
    request volume (plus ``min_reserve`` so a cold gateway can still
    retry at all). The containment property: under a brownout where
    every request fails once, retries add at most ~``ratio`` extra
    load instead of multiplying the storm by the attempt cap."""

    def __init__(self, ratio: float = 0.2, window_s: float = 10.0,
                 min_reserve: int = 3):
        self.ratio = ratio
        self.window_s = window_s
        self.min_reserve = min_reserve
        self._lock = threading.Lock()
        self._requests: deque = deque()
        self._retries: deque = deque()
        self.exhausted = 0

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._requests and self._requests[0] < horizon:
            self._requests.popleft()
        while self._retries and self._retries[0] < horizon:
            self._retries.popleft()

    def _allowance(self) -> float:
        return self.ratio * len(self._requests) + self.min_reserve

    def note_request(self) -> None:
        now = time.monotonic()
        with self._lock:
            self._requests.append(now)
            self._prune(now)
            self._update_gauge()

    def try_spend(self) -> bool:
        """One retry/hedge token, or False (the caller fails fast)."""
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            if len(self._retries) >= self._allowance():
                self.exhausted += 1
                _M_RETRY_BUDGET_EXHAUSTED.inc()
                self._update_gauge()
                return False
            self._retries.append(now)
            self._update_gauge()
            return True

    def remaining_ratio(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            allowed = self._allowance()
            if allowed <= 0.0:  # zero-token config: nothing to remain
                return 0.0
            return max(0.0, 1.0 - len(self._retries) / allowed)

    def _update_gauge(self) -> None:
        allowed = self._allowance()
        _M_RETRY_BUDGET_RATIO.set(
            0.0 if allowed <= 0.0
            else round(max(0.0, 1.0 - len(self._retries) / allowed), 4)
        )


@dataclass(frozen=True)
class Backend:
    host: str
    port: int
    path: str = "/"

    @staticmethod
    def from_info(info: dict) -> "Backend":
        # prefer the public (forwarded) endpoint when the worker fronted
        # itself with an ssh tunnel
        return Backend(
            host=info.get("forwarded_host") or info["host"],
            port=int(info.get("forwarded_port") or info["port"]),
            path=info.get("path") or "/",
        )


class BackendPool:
    """Round-robin roster with per-backend circuit breakers.

    Failure containment is a closed -> open -> half-open
    :class:`CircuitBreaker` per backend (replacing the earlier binary
    evict/revive logic): a backend that fails ``evict_after`` consecutive
    times — or whose windowed error rate crosses the breaker threshold —
    is OPEN and skipped entirely; after the open period one probe is
    admitted, and its success closes the breaker. A roster refresh
    carrying a **newer boot stamp** (the worker's per-process
    ``ServiceInfo.boot``, constant across heartbeats) resets the breaker
    immediately (the worker actually restarted — its predecessor's
    failures prove nothing about the new process).

    Sub-threshold failures still set a ``cooldown_s`` cooldown that
    deprioritizes (but doesn't exclude) the backend; ``next()`` falls
    back to a cooled-down backend rather than refusing traffic.

    ``evict_after=0`` disables breaker opens — the right setting for a
    STATIC pool (constructor list; no registry to announce a restart),
    where cooldown alone rate-limits attempts on a down worker. Static
    backends are pinned: ``refresh`` merges them with the roster.
    """

    def __init__(
        self, backends: Optional[list] = None, cooldown_s: float = 5.0,
        evict_after: int = 3, models: Optional[dict] = None,
        breaker_rate_threshold: float = 0.5,
        breaker_rate_window_s: float = 30.0,
        breaker_rate_min_volume: int = 10,
    ):
        self._lock = threading.Lock()
        self._static: list = list(backends or ())
        self._backends: list = list(self._static)
        self._cooldown: dict = {}
        self._breakers: dict = {}  # backend -> CircuitBreaker
        self._stamps: dict = {}    # backend -> latest roster stamp
        self._breaker_stamps: dict = {}  # backend -> stamp when it opened
        self._svc_ewma: dict = {}  # backend -> EWMA service seconds
        # backend -> frozenset of advertised model names (ModelStore
        # workers); a backend with no entry serves any model as far as
        # routing knows. Constructor-provided entries belong to static
        # backends, which never appear in a registry roster — refresh()
        # must keep them rather than replace the map wholesale
        self._static_models: dict = dict(models or {})
        self._models: dict = dict(self._static_models)
        self._rr = 0
        self.cooldown_s = cooldown_s
        self.evict_after = evict_after
        self._breaker_rate = (
            breaker_rate_threshold, breaker_rate_window_s,
            breaker_rate_min_volume,
        )
        # per-backend pre-resolved label children: labels() does set
        # comparisons per call — too slow for the per-request report_ok
        self._m_by_backend: dict = {}
        _M_GW_BACKENDS.set(len(self._backends))

    def _metrics_for(self, b: Backend) -> tuple:
        m = self._m_by_backend.get(b)
        if m is None:
            addr = f"{b.host}:{b.port}"
            m = self._m_by_backend[b] = (
                _M_BE_REQS.labels(backend=addr),
                _M_BE_ERRS.labels(backend=addr),
                _M_BE_EVICT.labels(backend=addr),
                _M_BREAKER_STATE.labels(backend=addr),
                _M_BE_BACKPRESSURE.labels(backend=addr),
            )
            m[3].set(BREAKER_CLOSED)
        return m

    def _breaker_for(self, b: Backend) -> CircuitBreaker:
        br = self._breakers.get(b)
        if br is None:
            rate, window, volume = self._breaker_rate
            br = self._breakers[b] = CircuitBreaker(
                open_after=(
                    0 if b in self._static else self.evict_after
                ),
                cooldown_s=self.cooldown_s,
                rate_threshold=rate, rate_window_s=window,
                rate_min_volume=volume,
            )
        return br

    def _note_transition(self, b: Backend, state: Optional[int]) -> None:
        if state is None:
            return
        m = self._metrics_for(b)
        m[3].set(state)
        _M_BREAKER_TRANSITIONS.labels(
            backend=f"{b.host}:{b.port}", state=BREAKER_STATE_NAMES[state]
        ).inc()
        if state == BREAKER_OPEN:
            m[2].inc()  # the eviction counter's successor event
            log.warning(
                "gateway: breaker OPEN for backend %s:%s", b.host, b.port
            )

    def refresh(self, backends: list, stamps: Optional[dict] = None,
                models: Optional[dict] = None) -> None:
        with self._lock:
            self._stamps = dict(stamps or {})
            if models is not None:
                self._models = {**self._static_models, **models}
            live = self._static + [
                b for b in backends if b not in self._static
            ]
            for b in live:
                br = self._breakers.get(b)
                if br is not None and br.state != BREAKER_CLOSED:
                    opened_stamp = self._breaker_stamps.get(b, 0.0)
                    if self._stamps.get(b, 0.0) > opened_stamp:
                        # the worker re-registered since the breaker
                        # opened: a NEW process — close immediately
                        br.reset()
                        self._note_transition(b, BREAKER_CLOSED)
                        self._cooldown.pop(b, None)
            self._backends = live
            self._cooldown = {
                b: t for b, t in self._cooldown.items() if b in self._backends
            }
            # series lifecycle: a fleet of ephemeral-port workers mints a
            # new backend label per restart — drop the metric children of
            # backends that left the roster, or scrape output and gateway
            # memory grow forever (counter resets are rate()-safe)
            for b in [x for x in self._m_by_backend if x not in live]:
                del self._m_by_backend[b]
                addr = f"{b.host}:{b.port}"
                for fam in (_M_BE_REQS, _M_BE_ERRS, _M_BE_EVICT,
                            _M_BREAKER_STATE, _M_BE_BACKPRESSURE):
                    fam.remove(backend=addr)
            for b in [x for x in self._breakers if x not in live]:
                del self._breakers[b]
                self._breaker_stamps.pop(b, None)
                self._svc_ewma.pop(b, None)
            for b in [x for x in self._models if x not in live]:
                del self._models[b]
            _M_GW_BACKENDS.set(self._routable_locked())

    def _routable_locked(self) -> int:
        now = time.monotonic()
        n = 0
        for b in self._backends:
            br = self._breakers.get(b)
            if br is None or br.state != BREAKER_OPEN or (
                now - br.opened_at >= br.open_for_s()
            ):
                n += 1
        return n

    def size(self) -> int:
        """Routable backends: roster members whose breaker would admit
        traffic right now (closed, half-open, or open-period elapsed)."""
        with self._lock:
            return self._routable_locked()

    def members(self) -> list:
        """Snapshot of the rostered backends (for cache pruning)."""
        with self._lock:
            return list(self._backends)

    def breaker_states(self) -> dict:
        """{'host:port': 'closed'|'open'|'half_open'} — /health payload
        and ``fleet top``'s BREAKER column source."""
        with self._lock:
            return {
                f"{b.host}:{b.port}": BREAKER_STATE_NAMES[
                    self._breakers[b].state
                    if b in self._breakers else BREAKER_CLOSED
                ]
                for b in self._backends
            }

    def svc_ewma_s(self, b: Backend) -> float:
        """EWMA service time of successful forwards to ``b`` (0 while
        unmeasured) — the deadline check's 'can this backend even answer
        in time' estimate."""
        with self._lock:
            return self._svc_ewma.get(b, 0.0)

    def next(self, exclude: Optional[set] = None,
             model: Optional[str] = None) -> Optional[Backend]:
        """The next routable backend, skipping open-breaker, cooled-down
        and ``exclude``d ones; falls back to a cooled-down backend rather
        than none (it may have recovered — better one retry than a
        refused request). An open breaker whose open period elapsed
        admits ONE half-open probe here.

        ``model``: prefer backends advertising that model name; when no
        advertiser is available the pick falls back to the whole pool
        (backends that advertise nothing are assumed to serve anything —
        pre-ModelStore workers)."""
        with self._lock:
            b = self._next_locked(exclude or set(), model)
            if b is None and model is not None:
                b = self._next_locked(exclude or set(), None)
            return b

    def _next_locked(self, exclude: set, model: Optional[str]):
        now = time.monotonic()
        n = len(self._backends)
        fallback = None
        for i in range(n):
            b = self._backends[(self._rr + i) % n]
            if b in exclude:
                continue
            if model is not None:
                advertised = self._models.get(b)
                if advertised is not None and model not in advertised:
                    continue
            br = self._breakers.get(b)
            if br is not None and br.state != BREAKER_CLOSED:
                was = br.state
                if not br.allow(now):
                    continue  # open: no traffic, not even as fallback
                if was == BREAKER_OPEN and br.state == BREAKER_HALF_OPEN:
                    # a re-admitted probe slot (report_abandoned returned
                    # it with the breaker already half-open) is NOT a new
                    # transition — count only the open -> half-open edge
                    self._note_transition(b, BREAKER_HALF_OPEN)
                self._rr = (self._rr + i + 1) % n
                return b  # the half-open probe
            if self._cooldown.get(b, 0.0) > now:
                fallback = fallback or b
                continue
            self._rr = (self._rr + i + 1) % n
            return b
        return fallback

    def report_failure(self, b: Backend) -> None:
        """A connection-level failure (refused, reset, timeout, torn
        response) — the breaker's signal. NOT for 429 sheds: those are
        :meth:`report_backpressure` (a shedding replica is alive and
        correct; evicting it shrinks the pool exactly when capacity is
        lowest)."""
        self._metrics_for(b)[1].inc()
        with self._lock:
            self._cooldown[b] = time.monotonic() + self.cooldown_s
            br = self._breaker_for(b)
            was_closed = br.state == BREAKER_CLOSED
            transition = br.record_failure(time.monotonic())
            if transition == BREAKER_OPEN and was_closed:
                self._breaker_stamps[b] = self._stamps.get(b, 0.0)
            if transition is not None:
                self._note_transition(b, transition)
                _M_GW_BACKENDS.set(self._routable_locked())

    def report_ok(self, b: Backend, elapsed_s: Optional[float] = None) -> None:
        self._metrics_for(b)[0].inc()
        with self._lock:
            self._cooldown.pop(b, None)
            br = self._breakers.get(b)
            if br is not None:
                transition = br.record_ok(time.monotonic())
                if transition is not None:
                    self._note_transition(b, transition)
                    _M_GW_BACKENDS.set(self._routable_locked())
            if elapsed_s is not None:
                prev = self._svc_ewma.get(b)
                self._svc_ewma[b] = (
                    elapsed_s if prev is None
                    else 0.8 * prev + 0.2 * elapsed_s
                )

    def report_backpressure(self, b: Backend) -> None:
        """The backend answered 429 (admission shed): it is alive and
        protecting itself — close a half-open breaker, clear the failure
        streak, but record nothing that could open one."""
        self._metrics_for(b)[4].inc()
        with self._lock:
            br = self._breakers.get(b)
            if br is not None:
                transition = br.record_ok(time.monotonic())
                if transition is not None:
                    self._note_transition(b, transition)
                    _M_GW_BACKENDS.set(self._routable_locked())

    def report_abandoned(self, b: Backend) -> None:
        """``next()`` admitted ``b`` but no outcome will ever be reported
        (deadline fast-fail, unfired hedge, cancelled loser, post-send
        timeout with no blame). If ``b`` held the half-open probe slot,
        return it — otherwise the breaker waits forever for a probe
        outcome that never comes and the backend stays unroutable."""
        with self._lock:
            br = self._breakers.get(b)
            if br is not None and br.state == BREAKER_HALF_OPEN:
                br.probe_inflight = False


class ServingGateway:
    """One client-facing endpoint dispatching onto N serving workers.

    ``workers``: static list of :class:`ServiceInfo`/dict/:class:`Backend`;
    and/or ``registry_url``: a :class:`DriverRegistry` endpoint polled
    every ``refresh_s`` so late-registering or restarted workers join the
    pool without a gateway restart.

    Delivery semantics: failures BEFORE the request body is delivered
    (connect refused/reset, write error) always re-dispatch to another
    worker — the worker cannot have started executing. A timeout AFTER the
    body was sent means the worker may be mid-execution (first-hit compile,
    heavy batch); by default that request fails with 504 instead of being
    executed a second time elsewhere (at-most-once for non-idempotent
    POSTs). Set ``retry_after_send=True`` for idempotent handlers to get
    at-least-once re-dispatch on post-send timeouts too."""

    # hop-by-hop headers that must not be forwarded verbatim
    _SKIP_HEADERS = {"connection", "content-length", "host", "keep-alive"}

    def __init__(
        self,
        workers: Optional[list] = None,
        registry_url: Optional[str] = None,
        service_name: str = "serving",
        host: str = "127.0.0.1",
        port: int = 0,
        num_dispatchers: int = 4,
        request_timeout_s: float = 10.0,
        refresh_s: float = 1.0,
        cooldown_s: float = 5.0,
        max_attempts: Optional[int] = None,
        evict_after: Optional[int] = None,
        retry_after_send: bool = False,
        hedge_ms: Optional[float] = None,
        retry_budget_ratio: float = 0.2,
        retry_budget_window_s: float = 10.0,
        retry_budget_min: int = 3,
        num_reactors: int = 1,
        header_deadline_s: Optional[float] = 30.0,
    ):
        """``hedge_ms``: tail-latency hedging — a request still pending
        after this many ms is duplicated to a second backend, first
        answer wins, the loser is cancelled. ``hedge_ms=0`` derives the
        delay from the observed forward-latency p95 (re-estimated as
        traffic flows). Hedges duplicate execution post-send, so enable
        it only for idempotent handlers; every hedge spends a retry-
        budget token, so hedging can never amplify a brownout.

        ``retry_budget_*``: a token bucket capping re-dispatches (and
        hedges) at ``ratio`` of the request volume over ``window_s``
        (plus ``min`` reserve tokens). An exhausted budget fails fast
        with ``x-mmlspark-retry-budget: exhausted`` instead of retrying
        a storm into the floor."""
        self.service_name = service_name
        self._ingress = WorkerServer(
            host=host, port=port, name=f"{service_name}-gateway",
            num_reactors=num_reactors,
            # slowloris defense at the front door (serving/server.py):
            # a dripped head is shed 408 at this deadline
            header_deadline_s=header_deadline_s,
        )
        if evict_after is None:
            # eviction only makes sense with a registry: its refresh is the
            # revival path (re-registration). A static pool would lose a
            # briefly-down worker FOREVER, so it relies on cooldown alone.
            evict_after = 3 if registry_url else 0
        static_models = {
            self._as_backend(w): frozenset(w.models)
            for w in (workers or ())
            if isinstance(w, ServiceInfo) and w.models
        }
        self._pool = BackendPool(
            [self._as_backend(w) for w in (workers or ())],
            cooldown_s=cooldown_s,
            evict_after=evict_after,
            models=static_models,
        )
        # registry HA (ROADMAP 5c): accept one URL, a comma-separated
        # list, or a sequence — roster refreshes fail over to the next
        # live registry, so the control plane survives a registry death
        # the way the data plane already survives a worker's
        from mmlspark_tpu.serving.fleet import split_registry_urls

        self._registry_urls = split_registry_urls(registry_url)
        self._reg_idx = 0  # last-known-good registry, tried first
        self._refresh_s = refresh_s
        self._timeout = request_timeout_s
        self._num_dispatchers = num_dispatchers
        self._max_attempts = max_attempts
        self._retry_after_send = retry_after_send
        self._threads: list = []
        self._stop = threading.Event()
        self._draining = False
        # per-dispatcher-thread persistent connections: the worker server
        # speaks HTTP/1.1 keep-alive, so reusing the TCP connection drops
        # the per-request handshake from the gateway overhead. The flat
        # registry mirrors every cached conn so stop() can close them
        # promptly (thread-local caches are unreachable from stop; a
        # GC'd socket also never decrements WireConn.open_count)
        self._conns = threading.local()
        self._conn_registry: set = set()
        self._conn_registry_lock = threading.Lock()
        # hedged attempts ride a small shared side pool instead of a
        # fresh connection per try (they run on short-lived helper
        # threads, so the per-thread cache can't serve them)
        self._hedge_pool = HedgeConnPool(request_timeout_s)
        self.forwarded = 0
        self.retried = 0
        self.failed = 0
        self.hedged = 0
        self.hedge_wins = 0
        self._hedge_ms = hedge_ms
        self._retry_budget = RetryBudget(
            ratio=retry_budget_ratio, window_s=retry_budget_window_s,
            min_reserve=retry_budget_min,
        )
        # forward-latency reservoir for the auto-derived (hedge_ms=0)
        # hedge delay: p95 of recent successful forwards. Locked: the
        # dispatcher threads record concurrently, and sorting a deque
        # another thread is appending to raises RuntimeError
        self._fwd_lat_ns: deque = deque(maxlen=512)
        self._fwd_lat_lock = threading.Lock()
        self._fwd_lat_count = 0
        self._hedge_auto_ms = 50.0  # until measured
        # optional in-process SLO engine (fleet.run_gateway attaches one);
        # owned here so stop() tears it down with the dispatchers
        self.slo_engine: Any = None

    @staticmethod
    def _as_backend(w) -> Backend:
        if isinstance(w, Backend):
            return w
        if isinstance(w, ServiceInfo):
            return Backend(
                host=w.forwarded_host or w.host,
                port=int(w.forwarded_port or w.port),
                path=w.path,
            )
        return Backend.from_info(dict(w))

    @property
    def pool(self) -> BackendPool:
        return self._pool

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> ServiceInfo:
        if self._registry_urls:
            self._refresh_once()
            t = threading.Thread(
                target=self._refresh_loop, name="gateway-refresh", daemon=True
            )
            t.start()
            self._threads.append(t)
        info = self._ingress.start()
        for i in range(self._num_dispatchers):
            t = threading.Thread(
                target=self._dispatch_loop, name=f"gateway-dispatch-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        return info

    def stop(self) -> None:
        # order matters: dispatchers drain and 503 the queue while the
        # ingress can still deliver replies; only then does the ingress
        # close client sockets
        if self.slo_engine is not None:
            self.slo_engine.stop()
        self._stop.set()
        for t in self._threads:
            t.join(5.0)
        self._ingress.stop()
        self._hedge_pool.close_all()
        # dispatchers are joined: their thread-local caches are idle —
        # close every pooled worker connection now (FIN at stop time,
        # not at GC time; keeps WireConn.open_count honest)
        with self._conn_registry_lock:
            conns, self._conn_registry = list(self._conn_registry), set()
        for conn in conns:
            conn.close()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown for fleet rolls: flip ``/health`` to 503 (so a
        load balancer stops routing here), keep dispatching until every
        ACCEPTED request has been answered, then :meth:`stop`. Returns True
        when fully drained, False when ``timeout_s`` expired with requests
        still in flight (they get 503'd by stop()'s queue drain)."""
        self._draining = True
        deadline = time.monotonic() + timeout_s
        drained = False
        while time.monotonic() < deadline:
            if self._ingress.pending() == 0 and self._ingress.inflight() == 0:
                drained = True
                break
            time.sleep(0.02)
        self.stop()
        return drained

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def url(self) -> str:
        return f"http://{self._ingress.host}:{self._ingress.port}/"

    # -- registry discovery ---------------------------------------------------

    def _refresh_once(self) -> None:
        from mmlspark_tpu.io.clients import send_request
        from mmlspark_tpu.io.http_schema import HTTPRequestData

        roster = None
        n = len(self._registry_urls)
        # start at the last-known-good registry, fail over to the next
        # live one (workers heartbeat to ALL registries, so any live
        # roster is authoritative)
        for i in range(n):
            k = (self._reg_idx + i) % n
            url = self._registry_urls[k]
            try:
                resp = send_request(HTTPRequestData(url, "GET"), timeout=5.0)
                if resp["status_code"] != 200:
                    raise ConnectionError(f"status {resp['status_code']}")
                roster = json.loads(resp["entity"])
                if k != self._reg_idx:
                    log.warning(
                        "gateway: registry failed over to %s", url
                    )
                    self._reg_idx = k
                break
            except Exception as e:  # noqa: BLE001 — discovery must never crash
                log.warning(
                    "gateway: registry refresh via %s failed: %s", url, e
                )
        if roster is None:
            return
        infos = roster.get(self.service_name, [])
        if infos:
            self._pool.refresh(
                [Backend.from_info(i) for i in infos],
                # restart detection keys on the worker's per-process
                # "boot" stamp, NOT the registry "ts": heartbeats bump
                # ts every beat, so a wedged-but-heartbeating worker
                # would reset its own open breaker within one refresh.
                # Pre-boot-stamp workers (no field) map to 0.0 — never
                # "newer", so their breakers recover only through the
                # half-open probe, which is the safe degradation
                stamps={
                    Backend.from_info(i): float(i.get("boot") or 0.0)
                    for i in infos
                },
                models={
                    Backend.from_info(i): frozenset(i["models"])
                    for i in infos
                    if i.get("models")
                },
            )
            # hedge connections to departed backends are dead weight
            self._hedge_pool.prune(self._pool.members())

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self._refresh_s):
            self._refresh_once()

    # -- dispatch -------------------------------------------------------------

    def _reply_health(self, req) -> None:
        """``/health``: answered by the gateway itself, never forwarded.
        200 only when routable (live backends, not draining) — the shape a
        load balancer / k8s readiness probe consumes during a fleet roll."""
        n = self._pool.size()
        status = (
            "draining" if self._draining
            else "ok" if n > 0
            else "no_backends"
        )
        body = json.dumps(
            {
                "status": status,
                "backends": n,
                "pending": self._ingress.pending(),
                "forwarded": self.forwarded,
                "retried": self.retried,
                "failed": self.failed,
                "hedged": self.hedged,
                "breakers": self._pool.breaker_states(),
                "retry_budget_remaining": round(
                    self._retry_budget.remaining_ratio(), 4
                ),
            }
        ).encode()
        self._ingress.reply_to(
            req.id, body, 200 if status == "ok" else 503,
            {"Content-Type": "application/json"},
        )

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            reqs = self._ingress.get_next_batch(max_n=16, timeout_s=0.2)
            for r in reqs:
                if self._stop.is_set():
                    # a popped request must still get an answer
                    self._ingress.reply_to(r.id, b"gateway stopping", 503)
                    continue
                if r.path.split("?", 1)[0] in ("/health", "/healthz"):
                    self._reply_health(r)
                    continue
                self._forward(r)
            if reqs:
                # prune the ingress replay history behind the answered
                # requests: the gateway's recovery story is cross-worker
                # re-dispatch, not epoch replay, and without this commit
                # every request ever accepted (incl. each LB /health
                # probe) stays in _history forever — an unbounded leak
                self._ingress.auto_commit()
        # drain: answer whatever is still queued so clients aren't hung
        # (stop() joins dispatchers BEFORE closing the ingress, so these
        # replies still reach their sockets)
        for r in self._ingress.get_next_batch(max_n=1_000_000, timeout_s=0.0):
            self._ingress.reply_to(r.id, b"gateway stopping", 503)

    def _conn_for(self, b) -> tuple:
        """(conn, cached): this dispatcher thread's persistent
        :class:`WireConn` to backend ``b``, or a fresh one."""
        cache = getattr(self._conns, "by_backend", None)
        if cache is None:
            cache = self._conns.by_backend = {}
        # prune connections to backends no longer in the pool (registry
        # churn: workers restarting on new ports would otherwise leak a
        # CLOSE_WAIT fd per dispatcher thread per departed backend)
        if len(cache) > self._pool.size():
            live = {(m.host, m.port) for m in self._pool.members()}
            for key in [k for k in cache if k not in live]:
                dropped = cache.pop(key)
                dropped.close()
                with self._conn_registry_lock:
                    self._conn_registry.discard(dropped)
        key = (b.host, b.port)
        conn = cache.get(key)
        if conn is not None:
            if conn.alive():
                if _M_CONN_REUSE._on:
                    _M_CONN_REUSE.inc()
                return conn, True
            self._drop_conn(b)
        conn = WireConn(b.host, b.port, self._timeout)
        cache[key] = conn
        with self._conn_registry_lock:
            self._conn_registry.add(conn)
        return conn, False

    def _drop_conn(self, b) -> None:
        cache = getattr(self._conns, "by_backend", None)
        conn = cache.pop((b.host, b.port), None) if cache else None
        if conn is not None:
            conn.close()
            with self._conn_registry_lock:
                self._conn_registry.discard(conn)

    # stash key for the pre-minted gateway.request span id (_forward sets
    # it; _reply records the span under it so forward spans, minted
    # earlier, already parent correctly). Lowercased like real headers
    # but never forwarded (_SKIP-independent: the forward header dict is
    # built before the stash lands).
    _ROOT_SPAN_KEY = "x-mmlspark-gateway-root-span"

    def _reply(self, req, body: bytes, code: int,
               headers: Optional[dict] = None) -> None:
        """Answer the client and close out the request's gateway metrics
        (ingress arrival -> reply, including queue wait and retries)."""
        self._ingress.reply_to(req.id, body, code, headers)
        if _M_GW_LATENCY._on:
            done_ns = time.perf_counter_ns()
            tid = req.headers.get(obs.TRACE_HEADER)
            lat_s = (done_ns - req.arrival_ns) / 1e9
            # exemplar: a p99 gateway bucket names a real, fetchable trace
            _M_GW_LATENCY.observe(lat_s, trace_id=tid)
            obs.record_span(
                "gateway.request", req.arrival_ns, done_ns,
                trace_id=tid,
                span_id=req.headers.get(self._ROOT_SPAN_KEY),
                parent_id=req.headers.get(obs.PARENT_HEADER),
                attrs={"status": code},
            )
            FLIGHT.record(
                "ok" if code < 500 else "error",
                status=code,
                trace_id=tid,
                model=req.headers.get("x-mmlspark-model"),
                path=req.path,
                latency_ms=lat_s * 1e3,
            )

    @staticmethod
    def _model_of(req) -> Optional[str]:
        """The model a request targets (``x-mmlspark-model`` header or a
        ``/models/<name>`` path) — the routing key for model-aware backend
        selection. None = unrouted (any backend)."""
        model = req.headers.get("x-mmlspark-model")
        if model:
            return model
        path = req.path.split("?", 1)[0]
        if path.startswith("/models/"):
            parts = [p for p in path[len("/models/"):].split("/") if p]
            if parts:
                return parts[0]
        return None

    def _target_for(self, req, b) -> str:
        """Preserve the request's own path (the /models/<name> data and
        control routes must survive the hop); a worker registered under
        a base path gets it prefixed."""
        return (
            req.path if b.path in ("", "/")
            else b.path.rstrip("/") + (
                req.path if req.path.startswith("/") else "/" + req.path
            )
        )

    def _fail(self, req, reason: str, code: int, body: bytes,
              headers: Optional[dict] = None) -> None:
        self.failed += 1
        _M_GW_FAILED.labels(reason=reason).inc()
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        self._reply(req, body, code, hdrs)

    @staticmethod
    def _remaining_ms(req, deadline_ms: Optional[float]) -> Optional[float]:
        """What is left of the client's deadline after the time this
        request already burned at the gateway (queue wait + connects +
        prior attempts — everything since ingress arrival)."""
        if deadline_ms is None:
            return None
        return deadline_ms - (time.perf_counter_ns() - req.arrival_ns) / 1e6

    def _note_fwd_latency(self, elapsed_s: float) -> None:
        with self._fwd_lat_lock:
            lat = self._fwd_lat_ns
            lat.append(elapsed_s)
            self._fwd_lat_count += 1
            # re-derive every 32 OBSERVATIONS (len(lat) stalls at maxlen,
            # so a len-based stride would sort on every call once full)
            if self._hedge_ms == 0 and self._fwd_lat_count % 32 == 0:
                arr = sorted(lat)
                self._hedge_auto_ms = max(
                    1.0, arr[min(len(arr) - 1, int(len(arr) * 0.95))] * 1e3
                )

    def _forward(self, req) -> None:
        attempts = self._max_attempts or max(2, self._pool.size() + 1)
        tried: set = set()
        model = self._model_of(req)
        not_ready = None  # last worker-local model-loading 503, if any
        backpressured = None  # last 429 shed, relayed when nothing admits
        headers = {
            k: v for k, v in req.headers.items()
            if k.lower() not in self._SKIP_HEADERS
        }
        # trace propagation: continue the client's trace id if it sent
        # one, else mint one here — the worker reads this header and its
        # spans join the same trace (docs/observability.md)
        trace_id = req.headers.get(obs.TRACE_HEADER) or obs.new_trace_id()
        headers[obs.TRACE_HEADER] = trace_id
        req.headers[obs.TRACE_HEADER] = trace_id
        # zero-re-parse forwarding: the client's headers serialize ONCE
        # per request; each attempt prepends only the method line and the
        # headers that genuinely vary per hop (remaining deadline, parent
        # span id, Host)
        static_block = "".join(
            f"{k}: {v}\r\n" for k, v in headers.items()
        ).encode("latin1")
        # pre-mint the gateway.request span id (recorded at _reply time):
        # each forward span parents under it NOW, and the worker parents
        # under the forward span via PARENT_HEADER — the assembled tree
        # has real edges across all three layers
        root_sid = obs.new_span_id()
        req.headers[self._ROOT_SPAN_KEY] = root_sid
        self._retry_budget.note_request()
        deadline_ms = deadline_ms_from(req.headers)
        if self._hedge_ms is not None:
            replied, hedge_tried, h_not_ready, h_shed = self._forward_hedged(
                req, headers, model, trace_id, root_sid, deadline_ms
            )
            if replied:
                return
            if hedge_tried:
                # the hedged attempts concluded without a good answer
                # (failures, sheds, model-not-ready): continuing is a
                # retry and pays the retry budget like any other
                # re-dispatch. The stashed worker answers seed the relay
                # fallbacks below so a shed still relays as a 429 (not a
                # budget 503) when nothing better admits.
                not_ready, backpressured = h_not_ready, h_shed
                if not self._retry_budget.try_spend():
                    if not_ready is None and backpressured is None:
                        self._fail(
                            req, "retry_budget", 503,
                            b'{"error": "retry budget exhausted"}',
                            {RETRY_BUDGET_HEADER: "exhausted"},
                        )
                        return
                    # the budget refused the re-dispatch but the fleet is
                    # alive (it shed / is still loading): skip straight
                    # to relaying the worker's own answer
                    attempts = 0
                else:
                    tried |= hedge_tried
                    self.retried += 1
                    _M_GW_RETRIES.inc()
        for attempt in range(attempts):
            extra: dict = {}  # per-attempt headers (deadline, parent span)
            remaining_ms = self._remaining_ms(req, deadline_ms)
            if remaining_ms is not None and remaining_ms <= 0:
                # the budget is already burned (dead backend attempts,
                # queue wait): answering 504 now beats forwarding a
                # request whose client has given up
                self._fail(
                    req, "deadline", 504,
                    b'{"error": "deadline expired at gateway"}',
                )
                return
            b = self._pool.next(exclude=tried, model=model)
            if b is None:
                break
            if remaining_ms is not None:
                if tried or attempt:
                    # retrying: don't bother when the leftover budget
                    # cannot even cover this backend's typical service
                    # time — fail fast instead of a doomed forward
                    ewma_ms = self._pool.svc_ewma_s(b) * 1e3
                    if ewma_ms > 0.0 and remaining_ms < ewma_ms:
                        # b was admitted (possibly as the half-open probe)
                        # but never contacted — give the slot back
                        self._pool.report_abandoned(b)
                        self._fail(
                            req, "deadline", 504,
                            b'{"error": "remaining deadline below backend '
                            b'service time"}',
                        )
                        return
                # true deadline propagation: the worker sees what is
                # LEFT, not the client's original budget
                extra[DEADLINE_HEADER] = f"{remaining_ms:.1f}"
            target = self._target_for(req, b)
            sent = False
            read_started = False
            t_attempt = time.perf_counter()
            try:
                # fault point gateway.forward: an injected OSError here is
                # indistinguishable from a worker that died before the
                # request was delivered — exercises the re-dispatch path
                faults.inject(
                    "gateway.forward",
                    context={"backend": (b.host, b.port), "attempt": attempt},
                )
                fwd_ctx = (
                    obs.span(
                        "gateway.forward", trace_id=trace_id,
                        parent_id=root_sid,
                        attrs={
                            "backend": f"{b.host}:{b.port}",
                            "attempt": attempt,
                        },
                    )
                    if _M_GW_LATENCY._on
                    else contextlib.nullcontext()
                )
                with fwd_ctx as fsp:
                    # the worker parents its spans under THIS hop's span
                    # (fsp is None only when telemetry is disabled)
                    if fsp is not None:
                        extra[obs.PARENT_HEADER] = fsp.span_id
                    conn, cached = self._conn_for(b)
                    data = _head_bytes(
                        req.method, target,
                        f"Host: {b.host}:{b.port}\r\n".encode("latin1"),
                        static_block, extra, len(req.body),
                    ) + req.body
                    # sendall returning means the body was fully flushed;
                    # an exception DURING it leaves an incomplete body the
                    # worker will never execute (Content-Length mismatch)
                    # — safe to re-dispatch
                    try:
                        conn.send(data)
                    except (OSError, http.client.HTTPException):
                        if not cached:
                            raise
                        # a kept-alive connection the worker has since
                        # closed is a connection-staleness failure, not a
                        # worker failure: retry ONCE on a fresh connection
                        # before blaming the backend
                        self._drop_conn(b)
                        conn, cached = self._conn_for(b)
                        conn.send(data)
                    sent = True
                    # fault point gateway.response: an injected TimeoutError
                    # here is a worker hanging mid-execution after the body
                    # was delivered — exercises the at-most-once 504 path
                    faults.inject(
                        "gateway.response",
                        context={"backend": (b.host, b.port), "attempt": attempt},
                    )
                    read_started = True
                    try:
                        resp = conn.read_response()
                    except OSError as e:
                        if (
                            cached
                            and conn.last_resp_bytes == 0
                            and not isinstance(e, TimeoutError)
                        ):
                            # the OTHER stale-keep-alive shape: the worker
                            # closed the idle connection while our bytes
                            # were in flight — zero response bytes + a
                            # closed/reset socket. One transparent retry
                            # on a fresh connection, not a backend
                            # failure (a genuinely dead worker fails the
                            # reconnect and takes the normal blame path)
                            self._drop_conn(b)
                            conn, cached = self._conn_for(b)
                            conn.send(data)
                            resp = conn.read_response()
                        else:
                            raise
                    body = resp.body
                if resp.will_close:
                    self._drop_conn(b)
            except (OSError, http.client.HTTPException) as e:
                self._drop_conn(b)
                timed_out_after_send = sent and isinstance(e, TimeoutError)
                # a response that STARTED and then died (reset/close with
                # partial bytes seen) proves the worker executed the
                # request — only the reply was torn on the wire. Like the
                # post-send timeout, re-dispatching would double-process
                # a non-idempotent POST; unlike it, the evidence here is
                # positive (bytes arrived), so this holds even for
                # non-timeout errors (a chaos proxy's truncate-then-RST
                # mid-frame, a dying NIC). retry_after_send opts
                # idempotent handlers back into re-dispatch.
                truncated_response = (
                    read_started and conn.last_resp_bytes > 0
                    and not isinstance(e, TimeoutError)
                )
                if timed_out_after_send and not self._retry_after_send:
                    # the worker may be mid-execution (slow, not dead):
                    # re-dispatching would double-process a non-idempotent
                    # POST, and cooling down a healthy-but-slow worker
                    # would starve the pool — fail this request instead.
                    # No outcome is reported against b, so a half-open
                    # probe slot it held must be returned explicitly
                    self._pool.report_abandoned(b)
                    self._fail(
                        req, "post_send_timeout", 504,
                        b'{"error": "worker timed out after request was '
                        b'sent"}',
                    )
                    return
                if truncated_response and not self._retry_after_send:
                    # the connection-level failure is still real evidence
                    # against the path — count it (repeats open the
                    # breaker and traffic routes around the torn link)
                    self._pool.report_failure(b)
                    self._fail(
                        req, "truncated_response", 502,
                        b'{"error": "worker response truncated after '
                        b'execution"}',
                    )
                    return
                # the cross-worker replay: this worker is down or died
                # before sending any reply byte (refused connect, or a
                # zero-byte failure — the truncated_response guard above
                # already intercepted half-written responses unless
                # retry_after_send opted in); cool it down and
                # re-dispatch elsewhere — IF the retry budget still has
                # tokens. An exhausted budget
                # fails fast: under a brownout, every request retrying
                # its full attempt tab multiplies the offered load
                # exactly when capacity is lowest
                tried.add(b)
                self._pool.report_failure(b)
                if not self._retry_budget.try_spend():
                    self._fail(
                        req, "retry_budget", 503,
                        b'{"error": "backend failed and retry budget '
                        b'exhausted"}',
                        {RETRY_BUDGET_HEADER: "exhausted"},
                    )
                    return
                self.retried += 1
                _M_GW_RETRIES.inc()
                continue
            elapsed_s = time.perf_counter() - t_attempt
            if resp.status == 429 and resp.getheader(SHED_HEADER):
                # the replica is load-shedding (admission control), not
                # failing: classify as backpressure — cooling it down or
                # opening its breaker would shrink the pool under
                # overload, the exact wrong direction. Another replica
                # may have headroom, so re-dispatch (against the retry
                # budget); when nothing admits, relay the shed
                self._pool.report_backpressure(b)
                if backpressured is None:
                    backpressured = (
                        body, resp.getheader("Content-Type"),
                        resp.getheader("Retry-After"),
                    )
                if attempt + 1 < attempts and self._retry_budget.try_spend():
                    tried.add(b)
                    self.retried += 1
                    _M_GW_RETRIES.inc()
                    continue
                break
            self._pool.report_ok(b, elapsed_s=elapsed_s)
            self._note_fwd_latency(elapsed_s)
            if (
                resp.status in (503, 404)
                and resp.getheader("x-mmlspark-model-state")
                and attempt + 1 < attempts
            ):
                # worker-local unavailability, not a dead worker: THIS
                # replica is still loading/warming the model (mid-swap or
                # cold start) or doesn't know it at all (heartbeat lag) —
                # another replica may already serve it, so re-dispatch
                # without cooling the healthy backend down. When every
                # candidate declines, relay a loading 503 over an
                # unknown 404 (the model provably exists somewhere)
                if not_ready is None or resp.status == 503:
                    not_ready = (
                        resp.status, body, resp.getheader("Content-Type"),
                        resp.getheader("x-mmlspark-model-state"),
                    )
                tried.add(b)
                self.retried += 1
                _M_GW_RETRIES.inc()
                continue
            self.forwarded += 1
            _M_GW_FORWARDED.inc()
            out_headers = {}
            ct = resp.getheader("Content-Type")
            if ct:
                out_headers["Content-Type"] = ct
            # epoch-fence rejections (modelstore dispatch 409) carry the
            # highest-seen epoch; preserve it across the hop so a
            # publisher behind the gateway learns the winning epoch
            # instead of a bare 409
            fenced = resp.getheader("x-mmlspark-fenced")
            if fenced:
                out_headers["x-mmlspark-fenced"] = fenced
            self._reply(req, body, resp.status, out_headers)
            return
        if not_ready is not None:
            # every candidate said "model still loading here": relay the
            # worker's own 503 (clients with a retrying handler back off)
            status, body, ct, model_state = not_ready
            self.failed += 1
            _M_GW_FAILED.labels(reason="model_not_ready").inc()
            hdrs = {"x-mmlspark-model-state": model_state}
            if ct:
                hdrs["Content-Type"] = ct
            self._reply(req, body, status, hdrs)
            return
        if backpressured is not None:
            # every candidate (or the retry budget) declined: relay the
            # worker's own 429 so the client's Retry-After backoff kicks
            # in — the fleet is alive, just at capacity
            body, ct, retry_after = backpressured
            self.failed += 1
            _M_GW_FAILED.labels(reason="backpressure").inc()
            hdrs = {SHED_HEADER: "admission"}
            if ct:
                hdrs["Content-Type"] = ct
            if retry_after:
                hdrs["Retry-After"] = retry_after
            self._reply(req, body, 429, hdrs)
            return
        self.failed += 1
        _M_GW_FAILED.labels(reason="no_backends").inc()
        self._reply(
            req, b'{"error": "no live serving workers"}', 503,
            {"Content-Type": "application/json"},
        )

    # -- tail hedging ---------------------------------------------------------

    def _forward_hedged(self, req, headers: dict, model, trace_id,
                        root_sid, deadline_ms) -> tuple:
        """Hedged dispatch: send to one backend; if no answer within the
        hedge delay, duplicate to a second backend (spending a retry-
        budget token; fault point ``gateway.hedge`` fires as it launches)
        and take whichever answers first, cancelling the loser by
        closing its socket.

        First *good* answer wins: a 429 shed or a model-state 503/404 is
        classified (backpressure / not-ready), stashed while the other
        attempt may still answer, and relayed — counted as
        ``failed{backpressure|model_not_ready}`` — only when nothing
        better arrives.

        Returns ``(replied, tried_backends, not_ready, backpressured)``:
        ``replied=True`` means the client was answered here; otherwise
        ``tried_backends`` (every attempt with a concluded outcome —
        failed, shed, or model-not-ready) seeds the standard retry
        loop's exclusion set, and the stashed ``not_ready`` /
        ``backpressured`` worker answers seed its relay fallbacks.
        Hedged attempts ride the gateway's shared :class:`HedgeConnPool`
        (they run on short-lived helper threads, so the per-dispatcher
        keep-alive cache can't serve them): a clean winner's connection
        returns to the pool, a cancelled loser's is closed — a hedge
        burst can never leak sockets (pinned by test)."""
        if self._pool.size() < 2:
            return False, set(), None, None  # nothing to hedge against
        b1 = self._pool.next(model=model)
        if b1 is None:
            return False, set(), None, None
        remaining_ms = self._remaining_ms(req, deadline_ms)
        if remaining_ms is not None:
            if remaining_ms <= 0:
                # b1 was admitted (possibly as the half-open probe) but
                # never contacted — give the slot back before failing
                self._pool.report_abandoned(b1)
                self._fail(
                    req, "deadline", 504,
                    b'{"error": "deadline expired at gateway"}',
                )
                return True, set(), None, None
            headers = dict(headers)
            headers[DEADLINE_HEADER] = f"{remaining_ms:.1f}"
        static_block = "".join(
            f"{k}: {v}\r\n" for k, v in headers.items()
        ).encode("latin1")
        results: Any = queue_mod.Queue()
        # tag -> in-flight WireConn; whoever pops an entry disposes it
        # (the attempt thread pools/closes it after a full read, the
        # cancel sweep closes whatever is still blocked reading)
        conns: dict = {}

        def attempt(tag: str, b) -> None:
            t0 = time.perf_counter()
            try:
                faults.inject(
                    "gateway.forward",
                    context={"backend": (b.host, b.port), "attempt": tag},
                )
                conn, cached = self._hedge_pool.get(b)
                conns[tag] = conn
                extra: dict = {}
                ctx = (
                    obs.span(
                        "gateway.forward", trace_id=trace_id,
                        parent_id=root_sid,
                        attrs={"backend": f"{b.host}:{b.port}",
                               "attempt": tag},
                    )
                    if _M_GW_LATENCY._on
                    else contextlib.nullcontext()
                )
                with ctx as fsp:
                    if fsp is not None:
                        extra[obs.PARENT_HEADER] = fsp.span_id
                    data = _head_bytes(
                        req.method, self._target_for(req, b),
                        f"Host: {b.host}:{b.port}\r\n".encode("latin1"),
                        static_block, extra, len(req.body),
                    ) + req.body
                    try:
                        conn.send(data)
                    except OSError:
                        if not cached:
                            raise
                        # stale pooled hedge connection: one transparent
                        # retry on a fresh one, same as the main path
                        if conns.pop(tag, None) is conn:
                            conn.close()
                        conn = WireConn(b.host, b.port, self._timeout)
                        conns[tag] = conn
                        conn.send(data)
                    faults.inject(
                        "gateway.response",
                        context={"backend": (b.host, b.port),
                                 "attempt": tag},
                    )
                    try:
                        resp = conn.read_response()
                    except OSError as e:
                        if (
                            cached
                            and conn.last_resp_bytes == 0
                            and not isinstance(e, TimeoutError)
                        ):
                            # read-side stale keep-alive (same shape the
                            # main path retries): the pooled conn's FIN
                            # landed after alive() — one transparent
                            # retry, not a report_failure against a
                            # healthy backend
                            if conns.pop(tag, None) is conn:
                                conn.close()
                            conn = WireConn(b.host, b.port, self._timeout)
                            conns[tag] = conn
                            conn.send(data)
                            resp = conn.read_response()
                        else:
                            raise
                if conns.pop(tag, None) is conn:
                    # the response was fully consumed: the connection is
                    # clean — back to the side pool for the next hedge
                    if resp.will_close:
                        conn.close()
                    else:
                        self._hedge_pool.put(b, conn)
                results.put(
                    (tag, b, resp, resp.body, time.perf_counter() - t0, None)
                )
            except Exception as e:  # noqa: BLE001 — relayed via the queue
                stale = conns.pop(tag, None)
                if stale is not None:
                    stale.close()
                results.put(
                    (tag, b, None, None, time.perf_counter() - t0, e)
                )

        threading.Thread(
            target=attempt, args=("primary", b1), daemon=True,
        ).start()
        launched = {"primary": b1}
        hedge_s = (
            (self._hedge_ms if self._hedge_ms else self._hedge_auto_ms)
            / 1e3
        )
        first = None
        try:
            first = results.get(timeout=hedge_s)
        except queue_mod.Empty:
            # still pending past the hedge delay: fire the duplicate
            b2 = self._pool.next(exclude={b1}, model=model)
            if b2 is not None and self._retry_budget.try_spend():
                try:
                    faults.inject(
                        "gateway.hedge",
                        context={"backend": (b2.host, b2.port)},
                    )
                    self.hedged += 1
                    _M_HEDGES.inc()
                    threading.Thread(
                        target=attempt, args=("hedge", b2), daemon=True,
                    ).start()
                    launched["hedge"] = b2
                except Exception:  # injected fault: hedge suppressed
                    self._pool.report_abandoned(b2)
            elif b2 is not None:
                # admitted by next() but the retry budget refused the
                # hedge: b2 never sees the request — return its slot
                self._pool.report_abandoned(b2)
        failed: set = set()
        reported: set = set()  # backends whose outcome reached the pool
        backpressured = None  # stashed 429 shed: (body, ct, retry_after)
        not_ready = None  # stashed model-state reply: (status, body, ct, st)
        concluded = 0  # attempts with a terminal outcome
        replied = False
        end_t = time.monotonic() + self._timeout + 5.0
        while concluded < len(launched):
            if first is None:
                try:
                    first = results.get(
                        timeout=max(0.05, end_t - time.monotonic())
                    )
                except queue_mod.Empty:
                    break  # every remaining attempt is hung
            tag, b, resp, body, elapsed, err = first
            first = None
            concluded += 1
            if err is not None or resp is None:
                failed.add(b)
                reported.add(b)
                self._pool.report_failure(b)
                continue  # the other attempt may still answer
            if resp.status == 429 and resp.getheader(SHED_HEADER):
                # the replica is load-shedding, not failing: backpressure,
                # never a winner while the other attempt may still answer
                # — stash the shed for relay when nothing better arrives
                reported.add(b)
                self._pool.report_backpressure(b)
                if backpressured is None:
                    backpressured = (
                        body, resp.getheader("Content-Type"),
                        resp.getheader("Retry-After"),
                    )
                continue
            model_state = resp.getheader("x-mmlspark-model-state")
            if resp.status in (503, 404) and model_state:
                # healthy worker, model still loading/unknown HERE: the
                # other attempt may already serve it — stash and wait
                # (prefer a loading 503 over an unknown 404)
                reported.add(b)
                self._pool.report_ok(b, elapsed_s=elapsed)
                if not_ready is None or resp.status == 503:
                    not_ready = (
                        resp.status, body,
                        resp.getheader("Content-Type"), model_state,
                    )
                continue
            # first good answer wins
            reported.add(b)
            self._pool.report_ok(b, elapsed_s=elapsed)
            self._note_fwd_latency(elapsed)
            if tag == "hedge":
                self.hedge_wins += 1
                _M_HEDGE_WINS.inc()
            self.forwarded += 1
            _M_GW_FORWARDED.inc()
            out_headers = {}
            ct = resp.getheader("Content-Type")
            if ct:
                out_headers["Content-Type"] = ct
            self._reply(req, body, resp.status, out_headers)
            replied = True
            break
        # cancel whatever is still in flight (the loser's blocked read
        # raises when its socket closes; its queued result is ignored
        # and never reported against the backend) — and return the
        # half-open probe slot of any attempt that got no outcome report,
        # or its breaker waits forever for a probe that never concludes.
        # Cleanly-concluded attempts already disposed of their own
        # connections (pool return), so only the still-reading losers
        # remain here — closed, never pooled
        for tag in list(conns):
            loser = conns.pop(tag, None)
            if loser is not None:
                with contextlib.suppress(OSError):
                    loser.close()
        for b in launched.values():
            if b not in reported:
                self._pool.report_abandoned(b)
        if replied:
            return True, failed, None, None
        if concluded == len(launched) and (
            failed or not_ready is not None or backpressured is not None
        ):
            # every attempt concluded without a good answer — genuine
            # failures, 429 sheds, or model-not-ready: hand off to the
            # standard retry loop so ANOTHER replica gets a chance
            # (relaying a fast shed or loading-503 here would skip the
            # non-hedged loop's cross-replica retry). The stashes ride
            # along so the loop can still relay the worker's own answer
            # when nothing else admits.
            return False, set(reported), not_ready, backpressured
        if not_ready is not None:
            # every attempt said "model still loading here": relay the
            # worker's own answer (with its model-state evidence) and
            # count it as the failure it is, not a forward
            status, body, ct, model_state = not_ready
            self.failed += 1
            _M_GW_FAILED.labels(reason="model_not_ready").inc()
            hdrs = {"x-mmlspark-model-state": model_state}
            if ct:
                hdrs["Content-Type"] = ct
            self._reply(req, body, status, hdrs)
        elif backpressured is not None:
            # every attempt shed (or failed): relay the 429 so the
            # client's Retry-After backoff kicks in — the fleet is
            # alive, just at capacity
            body, ct, retry_after = backpressured
            self.failed += 1
            _M_GW_FAILED.labels(reason="backpressure").inc()
            hdrs = {SHED_HEADER: "admission"}
            if ct:
                hdrs["Content-Type"] = ct
            if retry_after:
                hdrs["Retry-After"] = retry_after
            self._reply(req, body, 429, hdrs)
        else:
            self._fail(
                req, "post_send_timeout", 504,
                b'{"error": "hedged attempts timed out"}',
            )
        return True, failed, None, None
