"""Distributed serving: N WorkerServers behind ONE gateway endpoint.

The reference runs one HTTP source per executor with the driver
aggregating ServiceInfos and fronting them with a load balancer
(DistributedHTTPSource.scala:26-130; deployment modes in
docs/mmlspark-serving.md:93-160). The TPU rebuild keeps the per-worker
WorkerServer/ServingQuery pair unchanged and adds:

- :class:`BackendPool` — the live-worker roster with round-robin pick and
  failure cooldown;
- :class:`ServingGateway` — a front door (itself a WorkerServer, so the
  epoch/history/replay machinery guards the client-facing queue) whose
  dispatcher threads forward each request to a backend worker and reply on
  the originating socket;
- cross-worker recovery: a request forwarded to a worker that dies
  mid-flight is re-dispatched to ANOTHER worker — the uncommitted-epoch
  replay of HTTPSourceV2.scala:470-487, landing on a different worker, so
  a worker crash loses zero accepted requests;
- :class:`DriverRegistry` discovery: pass ``registry_url`` and the pool
  refreshes from the roster, picking up workers that (re)register.
"""

from __future__ import annotations

import contextlib
import http.client
import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.obs.flightrec import FLIGHT
from mmlspark_tpu.serving.server import ServiceInfo, WorkerServer

log = logging.getLogger("mmlspark_tpu.serving")

_M_GW_FORWARDED = obs.counter(
    "mmlspark_gateway_requests_total",
    "Requests successfully forwarded and answered",
)
_M_GW_RETRIES = obs.counter(
    "mmlspark_gateway_retries_total",
    "Cross-worker re-dispatch attempts after a backend failure",
)
_M_GW_FAILED = obs.counter(
    "mmlspark_gateway_failures_total",
    "Requests the gateway answered with an error", labels=("reason",),
)
_M_GW_LATENCY = obs.histogram(
    "mmlspark_gateway_request_latency_seconds",
    "Gateway ingress arrival to reply (includes queue wait + retries)",
)
_M_GW_BACKENDS = obs.gauge(
    "mmlspark_gateway_backends_count", "Live backends in the pool",
)
_M_BE_REQS = obs.counter(
    "mmlspark_gateway_backend_requests_total",
    "Successful forwards per backend", labels=("backend",),
)
_M_BE_ERRS = obs.counter(
    "mmlspark_gateway_backend_errors_total",
    "Reported failures per backend", labels=("backend",),
)
_M_BE_EVICT = obs.counter(
    "mmlspark_gateway_backend_evictions_total",
    "DEAD-mark evictions per backend", labels=("backend",),
)


@dataclass(frozen=True)
class Backend:
    host: str
    port: int
    path: str = "/"

    @staticmethod
    def from_info(info: dict) -> "Backend":
        # prefer the public (forwarded) endpoint when the worker fronted
        # itself with an ssh tunnel
        return Backend(
            host=info.get("forwarded_host") or info["host"],
            port=int(info.get("forwarded_port") or info["port"]),
            path=info.get("path") or "/",
        )


class BackendPool:
    """Round-robin roster with failure cooldown + dead-entry eviction.

    A worker that fails ``evict_after`` consecutive times is marked DEAD:
    registry refreshes skip it until its registration timestamp changes
    (i.e. the worker actually re-registered) — a crashed worker's stale
    ephemeral-port entry cannot keep adding failed-connect latency forever.
    ``evict_after=0`` disables eviction — the right setting for a STATIC
    pool (no registry refresh would ever revive an evicted backend);
    cooldown alone then rate-limits attempts on a down worker, and
    ``next()``'s cooled-down fallback lets it rejoin when it recovers.

    Statically configured backends (the constructor list) are pinned:
    ``refresh`` merges them with the roster instead of replacing them.
    """

    def __init__(
        self, backends: Optional[list] = None, cooldown_s: float = 5.0,
        evict_after: int = 3, models: Optional[dict] = None,
    ):
        self._lock = threading.Lock()
        self._static: list = list(backends or ())
        self._backends: list = list(self._static)
        self._cooldown: dict = {}
        self._fails: dict = {}
        self._dead: dict = {}    # backend -> roster stamp at eviction
        self._stamps: dict = {}  # backend -> latest roster stamp
        # backend -> frozenset of advertised model names (ModelStore
        # workers); a backend with no entry serves any model as far as
        # routing knows. Constructor-provided entries belong to static
        # backends, which never appear in a registry roster — refresh()
        # must keep them rather than replace the map wholesale
        self._static_models: dict = dict(models or {})
        self._models: dict = dict(self._static_models)
        self._rr = 0
        self.cooldown_s = cooldown_s
        self.evict_after = evict_after
        # per-backend pre-resolved label children: labels() does set
        # comparisons per call — too slow for the per-request report_ok
        self._m_by_backend: dict = {}
        _M_GW_BACKENDS.set(len(self._backends))

    def _metrics_for(self, b: Backend) -> tuple:
        m = self._m_by_backend.get(b)
        if m is None:
            addr = f"{b.host}:{b.port}"
            m = self._m_by_backend[b] = (
                _M_BE_REQS.labels(backend=addr),
                _M_BE_ERRS.labels(backend=addr),
                _M_BE_EVICT.labels(backend=addr),
            )
        return m

    def refresh(self, backends: list, stamps: Optional[dict] = None,
                models: Optional[dict] = None) -> None:
        with self._lock:
            self._stamps = dict(stamps or {})
            if models is not None:
                self._models = {**self._static_models, **models}
            live = []
            for b in self._static + [
                b for b in backends if b not in self._static
            ]:
                dead_at = self._dead.get(b)
                if dead_at is not None:
                    if self._stamps.get(b, 0.0) > dead_at:
                        # re-registered since eviction: give it another life
                        del self._dead[b]
                        self._fails.pop(b, None)
                    else:
                        continue
                live.append(b)
            self._backends = live
            self._cooldown = {
                b: t for b, t in self._cooldown.items() if b in self._backends
            }
            # series lifecycle: a fleet of ephemeral-port workers mints a
            # new backend label per restart — drop the metric children of
            # backends that left the roster, or scrape output and gateway
            # memory grow forever (counter resets are rate()-safe)
            for b in [x for x in self._m_by_backend if x not in live]:
                del self._m_by_backend[b]
                addr = f"{b.host}:{b.port}"
                for fam in (_M_BE_REQS, _M_BE_ERRS, _M_BE_EVICT):
                    fam.remove(backend=addr)
            for b in [x for x in self._models if x not in live]:
                del self._models[b]
            _M_GW_BACKENDS.set(len(self._backends))

    def size(self) -> int:
        with self._lock:
            return len(self._backends)

    def members(self) -> list:
        """Snapshot of the live backends (for cache pruning)."""
        with self._lock:
            return list(self._backends)

    def next(self, exclude: Optional[set] = None,
             model: Optional[str] = None) -> Optional[Backend]:
        """The next live backend, skipping cooled-down and ``exclude``d
        ones; falls back to a cooled-down backend rather than none (it may
        have recovered — better one retry than a refused request).

        ``model``: prefer backends advertising that model name; when no
        advertiser is available the pick falls back to the whole pool
        (backends that advertise nothing are assumed to serve anything —
        pre-ModelStore workers)."""
        with self._lock:
            b = self._next_locked(exclude or set(), model)
            if b is None and model is not None:
                b = self._next_locked(exclude or set(), None)
            return b

    def _next_locked(self, exclude: set, model: Optional[str]):
        now = time.monotonic()
        n = len(self._backends)
        fallback = None
        for i in range(n):
            b = self._backends[(self._rr + i) % n]
            if b in exclude:
                continue
            if model is not None:
                advertised = self._models.get(b)
                if advertised is not None and model not in advertised:
                    continue
            if self._cooldown.get(b, 0.0) > now:
                fallback = fallback or b
                continue
            self._rr = (self._rr + i + 1) % n
            return b
        return fallback

    def report_failure(self, b: Backend) -> None:
        self._metrics_for(b)[1].inc()
        with self._lock:
            self._cooldown[b] = time.monotonic() + self.cooldown_s
            self._fails[b] = self._fails.get(b, 0) + 1
            if (
                self.evict_after
                and self._fails[b] >= self.evict_after
                and b not in self._static  # static backends only cool down
            ):
                self._dead[b] = self._stamps.get(b, 0.0)
                self._backends = [x for x in self._backends if x != b]
                self._metrics_for(b)[2].inc()
                _M_GW_BACKENDS.set(len(self._backends))

    def report_ok(self, b: Backend) -> None:
        self._metrics_for(b)[0].inc()
        with self._lock:
            self._cooldown.pop(b, None)
            self._fails.pop(b, None)


class ServingGateway:
    """One client-facing endpoint dispatching onto N serving workers.

    ``workers``: static list of :class:`ServiceInfo`/dict/:class:`Backend`;
    and/or ``registry_url``: a :class:`DriverRegistry` endpoint polled
    every ``refresh_s`` so late-registering or restarted workers join the
    pool without a gateway restart.

    Delivery semantics: failures BEFORE the request body is delivered
    (connect refused/reset, write error) always re-dispatch to another
    worker — the worker cannot have started executing. A timeout AFTER the
    body was sent means the worker may be mid-execution (first-hit compile,
    heavy batch); by default that request fails with 504 instead of being
    executed a second time elsewhere (at-most-once for non-idempotent
    POSTs). Set ``retry_after_send=True`` for idempotent handlers to get
    at-least-once re-dispatch on post-send timeouts too."""

    # hop-by-hop headers that must not be forwarded verbatim
    _SKIP_HEADERS = {"connection", "content-length", "host", "keep-alive"}

    def __init__(
        self,
        workers: Optional[list] = None,
        registry_url: Optional[str] = None,
        service_name: str = "serving",
        host: str = "127.0.0.1",
        port: int = 0,
        num_dispatchers: int = 4,
        request_timeout_s: float = 10.0,
        refresh_s: float = 1.0,
        cooldown_s: float = 5.0,
        max_attempts: Optional[int] = None,
        evict_after: Optional[int] = None,
        retry_after_send: bool = False,
    ):
        self.service_name = service_name
        self._ingress = WorkerServer(
            host=host, port=port, name=f"{service_name}-gateway"
        )
        if evict_after is None:
            # eviction only makes sense with a registry: its refresh is the
            # revival path (re-registration). A static pool would lose a
            # briefly-down worker FOREVER, so it relies on cooldown alone.
            evict_after = 3 if registry_url else 0
        static_models = {
            self._as_backend(w): frozenset(w.models)
            for w in (workers or ())
            if isinstance(w, ServiceInfo) and w.models
        }
        self._pool = BackendPool(
            [self._as_backend(w) for w in (workers or ())],
            cooldown_s=cooldown_s,
            evict_after=evict_after,
            models=static_models,
        )
        self._registry_url = registry_url
        self._refresh_s = refresh_s
        self._timeout = request_timeout_s
        self._num_dispatchers = num_dispatchers
        self._max_attempts = max_attempts
        self._retry_after_send = retry_after_send
        self._threads: list = []
        self._stop = threading.Event()
        self._draining = False
        # per-dispatcher-thread persistent connections: the worker server
        # speaks HTTP/1.1 keep-alive, so reusing the TCP connection drops
        # the per-request handshake from the gateway overhead
        self._conns = threading.local()
        self.forwarded = 0
        self.retried = 0
        self.failed = 0
        # optional in-process SLO engine (fleet.run_gateway attaches one);
        # owned here so stop() tears it down with the dispatchers
        self.slo_engine: Any = None

    @staticmethod
    def _as_backend(w) -> Backend:
        if isinstance(w, Backend):
            return w
        if isinstance(w, ServiceInfo):
            return Backend(
                host=w.forwarded_host or w.host,
                port=int(w.forwarded_port or w.port),
                path=w.path,
            )
        return Backend.from_info(dict(w))

    @property
    def pool(self) -> BackendPool:
        return self._pool

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> ServiceInfo:
        if self._registry_url:
            self._refresh_once()
            t = threading.Thread(
                target=self._refresh_loop, name="gateway-refresh", daemon=True
            )
            t.start()
            self._threads.append(t)
        info = self._ingress.start()
        for i in range(self._num_dispatchers):
            t = threading.Thread(
                target=self._dispatch_loop, name=f"gateway-dispatch-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        return info

    def stop(self) -> None:
        # order matters: dispatchers drain and 503 the queue while the
        # ingress can still deliver replies; only then does the ingress
        # close client sockets
        if self.slo_engine is not None:
            self.slo_engine.stop()
        self._stop.set()
        for t in self._threads:
            t.join(5.0)
        self._ingress.stop()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown for fleet rolls: flip ``/health`` to 503 (so a
        load balancer stops routing here), keep dispatching until every
        ACCEPTED request has been answered, then :meth:`stop`. Returns True
        when fully drained, False when ``timeout_s`` expired with requests
        still in flight (they get 503'd by stop()'s queue drain)."""
        self._draining = True
        deadline = time.monotonic() + timeout_s
        drained = False
        while time.monotonic() < deadline:
            if self._ingress.pending() == 0 and self._ingress.inflight() == 0:
                drained = True
                break
            time.sleep(0.02)
        self.stop()
        return drained

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def url(self) -> str:
        return f"http://{self._ingress.host}:{self._ingress.port}/"

    # -- registry discovery ---------------------------------------------------

    def _refresh_once(self) -> None:
        from mmlspark_tpu.io.clients import send_request
        from mmlspark_tpu.io.http_schema import HTTPRequestData

        try:
            resp = send_request(
                HTTPRequestData(self._registry_url, "GET"), timeout=5.0
            )
            roster = json.loads(resp["entity"])
        except Exception as e:  # noqa: BLE001 — discovery must never crash
            log.warning("gateway: registry refresh failed: %s", e)
            return
        infos = roster.get(self.service_name, [])
        if infos:
            self._pool.refresh(
                [Backend.from_info(i) for i in infos],
                stamps={
                    Backend.from_info(i): float(i.get("ts") or 0.0)
                    for i in infos
                },
                models={
                    Backend.from_info(i): frozenset(i["models"])
                    for i in infos
                    if i.get("models")
                },
            )

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self._refresh_s):
            self._refresh_once()

    # -- dispatch -------------------------------------------------------------

    def _reply_health(self, req) -> None:
        """``/health``: answered by the gateway itself, never forwarded.
        200 only when routable (live backends, not draining) — the shape a
        load balancer / k8s readiness probe consumes during a fleet roll."""
        n = self._pool.size()
        status = (
            "draining" if self._draining
            else "ok" if n > 0
            else "no_backends"
        )
        body = json.dumps(
            {
                "status": status,
                "backends": n,
                "pending": self._ingress.pending(),
                "forwarded": self.forwarded,
                "retried": self.retried,
                "failed": self.failed,
            }
        ).encode()
        self._ingress.reply_to(
            req.id, body, 200 if status == "ok" else 503,
            {"Content-Type": "application/json"},
        )

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            reqs = self._ingress.get_next_batch(max_n=16, timeout_s=0.2)
            for r in reqs:
                if self._stop.is_set():
                    # a popped request must still get an answer
                    self._ingress.reply_to(r.id, b"gateway stopping", 503)
                    continue
                if r.path.split("?", 1)[0] in ("/health", "/healthz"):
                    self._reply_health(r)
                    continue
                self._forward(r)
            if reqs:
                # prune the ingress replay history behind the answered
                # requests: the gateway's recovery story is cross-worker
                # re-dispatch, not epoch replay, and without this commit
                # every request ever accepted (incl. each LB /health
                # probe) stays in _history forever — an unbounded leak
                self._ingress.auto_commit()
        # drain: answer whatever is still queued so clients aren't hung
        # (stop() joins dispatchers BEFORE closing the ingress, so these
        # replies still reach their sockets)
        for r in self._ingress.get_next_batch(max_n=1_000_000, timeout_s=0.0):
            self._ingress.reply_to(r.id, b"gateway stopping", 503)

    @staticmethod
    def _conn_alive(conn) -> bool:
        """Is an idle pooled connection still usable? A dead worker's FIN
        (or any unread stray bytes) makes the socket readable — reusing
        it would turn 'worker stopped between requests' from a safe
        pre-send connect-refused into a send-then-hang 504. poll(), not
        select(): the gateway ingress holds an fd per client, so pooled
        fds routinely exceed select's FD_SETSIZE under load."""
        import select

        sock = getattr(conn, "sock", None)
        if sock is None:
            return False
        try:
            p = select.poll()
            p.register(sock, select.POLLIN)
            return not p.poll(0)
        except (OSError, ValueError):
            return False

    def _conn_for(self, b) -> tuple:
        """(conn, cached): this dispatcher thread's persistent connection
        to backend ``b``, or a fresh one."""
        cache = getattr(self._conns, "by_backend", None)
        if cache is None:
            cache = self._conns.by_backend = {}
        # prune connections to backends no longer in the pool (registry
        # churn: workers restarting on new ports would otherwise leak a
        # CLOSE_WAIT fd per dispatcher thread per departed backend)
        if len(cache) > self._pool.size():
            live = {(m.host, m.port) for m in self._pool.members()}
            for key in [k for k in cache if k not in live]:
                try:
                    cache.pop(key).close()
                except OSError:
                    pass
        key = (b.host, b.port)
        conn = cache.get(key)
        if conn is not None:
            if self._conn_alive(conn):
                return conn, True
            self._drop_conn(b)
        conn = http.client.HTTPConnection(b.host, b.port, timeout=self._timeout)
        cache[key] = conn
        return conn, False

    def _drop_conn(self, b) -> None:
        cache = getattr(self._conns, "by_backend", None)
        conn = cache.pop((b.host, b.port), None) if cache else None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    # stash key for the pre-minted gateway.request span id (_forward sets
    # it; _reply records the span under it so forward spans, minted
    # earlier, already parent correctly). Lowercased like real headers
    # but never forwarded (_SKIP-independent: the forward header dict is
    # built before the stash lands).
    _ROOT_SPAN_KEY = "x-mmlspark-gateway-root-span"

    def _reply(self, req, body: bytes, code: int,
               headers: Optional[dict] = None) -> None:
        """Answer the client and close out the request's gateway metrics
        (ingress arrival -> reply, including queue wait and retries)."""
        self._ingress.reply_to(req.id, body, code, headers)
        if _M_GW_LATENCY._on:
            done_ns = time.perf_counter_ns()
            tid = req.headers.get(obs.TRACE_HEADER)
            lat_s = (done_ns - req.arrival_ns) / 1e9
            # exemplar: a p99 gateway bucket names a real, fetchable trace
            _M_GW_LATENCY.observe(lat_s, trace_id=tid)
            obs.record_span(
                "gateway.request", req.arrival_ns, done_ns,
                trace_id=tid,
                span_id=req.headers.get(self._ROOT_SPAN_KEY),
                parent_id=req.headers.get(obs.PARENT_HEADER),
                attrs={"status": code},
            )
            FLIGHT.record(
                "ok" if code < 500 else "error",
                status=code,
                trace_id=tid,
                model=req.headers.get("x-mmlspark-model"),
                path=req.path,
                latency_ms=lat_s * 1e3,
            )

    @staticmethod
    def _model_of(req) -> Optional[str]:
        """The model a request targets (``x-mmlspark-model`` header or a
        ``/models/<name>`` path) — the routing key for model-aware backend
        selection. None = unrouted (any backend)."""
        model = req.headers.get("x-mmlspark-model")
        if model:
            return model
        path = req.path.split("?", 1)[0]
        if path.startswith("/models/"):
            parts = [p for p in path[len("/models/"):].split("/") if p]
            if parts:
                return parts[0]
        return None

    def _forward(self, req) -> None:
        attempts = self._max_attempts or max(2, self._pool.size() + 1)
        tried: set = set()
        model = self._model_of(req)
        not_ready = None  # last worker-local model-loading 503, if any
        headers = {
            k: v for k, v in req.headers.items()
            if k.lower() not in self._SKIP_HEADERS
        }
        # trace propagation: continue the client's trace id if it sent
        # one, else mint one here — the worker reads this header and its
        # spans join the same trace (docs/observability.md)
        trace_id = req.headers.get(obs.TRACE_HEADER) or obs.new_trace_id()
        headers[obs.TRACE_HEADER] = trace_id
        req.headers[obs.TRACE_HEADER] = trace_id
        # pre-mint the gateway.request span id (recorded at _reply time):
        # each forward span parents under it NOW, and the worker parents
        # under the forward span via PARENT_HEADER — the assembled tree
        # has real edges across all three layers
        root_sid = obs.new_span_id()
        req.headers[self._ROOT_SPAN_KEY] = root_sid
        for attempt in range(attempts):
            b = self._pool.next(exclude=tried, model=model)
            if b is None:
                break
            # preserve the request's own path (the /models/<name> data and
            # control routes must survive the hop); a worker registered
            # under a base path gets it prefixed
            target = (
                req.path if b.path in ("", "/")
                else b.path.rstrip("/") + (
                    req.path if req.path.startswith("/") else "/" + req.path
                )
            )
            sent = False
            try:
                # fault point gateway.forward: an injected OSError here is
                # indistinguishable from a worker that died before the
                # request was delivered — exercises the re-dispatch path
                faults.inject(
                    "gateway.forward",
                    context={"backend": (b.host, b.port), "attempt": attempt},
                )
                fwd_ctx = (
                    obs.span(
                        "gateway.forward", trace_id=trace_id,
                        parent_id=root_sid,
                        attrs={
                            "backend": f"{b.host}:{b.port}",
                            "attempt": attempt,
                        },
                    )
                    if _M_GW_LATENCY._on
                    else contextlib.nullcontext()
                )
                with fwd_ctx as fsp:
                    # the worker parents its spans under THIS hop's span
                    # (fsp is None only when telemetry is disabled)
                    if fsp is not None:
                        headers[obs.PARENT_HEADER] = fsp.span_id
                    conn, cached = self._conn_for(b)
                    # request() returning means the body was fully flushed;
                    # an exception DURING it leaves an incomplete body the
                    # worker will never execute (Content-Length mismatch) —
                    # safe to re-dispatch
                    try:
                        conn.request(
                            req.method, target, body=req.body, headers=headers
                        )
                    except (OSError, http.client.HTTPException):
                        if not cached:
                            raise
                        # a kept-alive connection the worker has since
                        # closed is a connection-staleness failure, not a
                        # worker failure: retry ONCE on a fresh connection
                        # before blaming the backend
                        self._drop_conn(b)
                        conn, _ = self._conn_for(b)
                        conn.request(
                            req.method, target, body=req.body, headers=headers
                        )
                    sent = True
                    # fault point gateway.response: an injected TimeoutError
                    # here is a worker hanging mid-execution after the body
                    # was delivered — exercises the at-most-once 504 path
                    faults.inject(
                        "gateway.response",
                        context={"backend": (b.host, b.port), "attempt": attempt},
                    )
                    resp = conn.getresponse()
                    body = resp.read()
                if resp.will_close:
                    self._drop_conn(b)
            except (OSError, http.client.HTTPException) as e:
                self._drop_conn(b)
                timed_out_after_send = sent and isinstance(e, TimeoutError)
                if timed_out_after_send and not self._retry_after_send:
                    # the worker may be mid-execution (slow, not dead):
                    # re-dispatching would double-process a non-idempotent
                    # POST, and cooling down a healthy-but-slow worker
                    # would starve the pool — fail this request instead
                    self.failed += 1
                    _M_GW_FAILED.labels(reason="post_send_timeout").inc()
                    self._reply(
                        req,
                        b'{"error": "worker timed out after request was sent"}',
                        504, {"Content-Type": "application/json"},
                    )
                    return
                # the cross-worker replay: this worker is down or died
                # mid-request (refused connect OR a half-written response
                # — IncompleteRead/BadStatusLine are HTTPException, not
                # OSError); cool it down and re-dispatch elsewhere
                tried.add(b)
                self._pool.report_failure(b)
                self.retried += 1
                _M_GW_RETRIES.inc()
                continue
            self._pool.report_ok(b)
            if (
                resp.status in (503, 404)
                and resp.getheader("x-mmlspark-model-state")
                and attempt + 1 < attempts
            ):
                # worker-local unavailability, not a dead worker: THIS
                # replica is still loading/warming the model (mid-swap or
                # cold start) or doesn't know it at all (heartbeat lag) —
                # another replica may already serve it, so re-dispatch
                # without cooling the healthy backend down. When every
                # candidate declines, relay a loading 503 over an
                # unknown 404 (the model provably exists somewhere)
                if not_ready is None or resp.status == 503:
                    not_ready = (
                        resp.status, body, resp.getheader("Content-Type"),
                    )
                tried.add(b)
                self.retried += 1
                _M_GW_RETRIES.inc()
                continue
            self.forwarded += 1
            _M_GW_FORWARDED.inc()
            out_headers = {}
            ct = resp.getheader("Content-Type")
            if ct:
                out_headers["Content-Type"] = ct
            self._reply(req, body, resp.status, out_headers)
            return
        if not_ready is not None:
            # every candidate said "model still loading here": relay the
            # worker's own 503 (clients with a retrying handler back off)
            status, body, ct = not_ready
            self.failed += 1
            _M_GW_FAILED.labels(reason="model_not_ready").inc()
            self._reply(
                req, body, status,
                {"Content-Type": ct} if ct else None,
            )
            return
        self.failed += 1
        _M_GW_FAILED.labels(reason="no_backends").inc()
        self._reply(
            req, b'{"error": "no live serving workers"}', 503,
            {"Content-Type": "application/json"},
        )
