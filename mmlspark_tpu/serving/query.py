"""ServingQuery: the dispatch loop between a WorkerServer and a model.

Continuous mode mirrors the reference's ContinuousReader path
(HTTPSourceV2.scala:52-69, 693-706): a dispatcher thread drains whatever is
queued (bounded by ``max_batch_size``; ``max_wait_ms`` optionally holds the
batch open for stragglers — 0 dispatches immediately), runs the handler,
and replies — latency is ingress + one XLA call. Micro-batch mode advances an epoch on a timer and
processes whole epochs (getBatch/addBatch semantics), committing each after
its replies are sent.

Continuous **batching** (the throughput rewrite): with
``pipeline_depth >= 2`` (the default) continuous mode runs as a
two-stage pipeline — a *builder* thread admits queued requests into the
next dispatch slot (pop + deadline shed + the handler's host-side
``prepare``: JSON decode, column stacking, bucket padding) while an
*executor* thread runs the previous batch's ``execute`` (the XLA call)
and replies. Batch N+1's arrays are built while batch N computes, so
the dispatch loop stops paying host parse time on the device's critical
path. Handlers that expose the :class:`SplitHandler` protocol
(``prepare(reqs) -> staged`` + ``execute(staged) -> replies``) overlap
fully; plain ``handler(reqs)`` callables still pipeline the queue pop
and deadline shed. ``pipeline_depth=1`` keeps the classic
barrier-per-batch loop; results are bit-identical either way — only
the overlap changes (pinned by tests/test_throughput.py).

TPU detail that matters: handlers built by :func:`serve_transformer` pad
every batch to a power-of-two bucket so the jitted model compiles once per
bucket instead of once per request count.
"""

from __future__ import annotations

import contextlib
import json
import queue as queue_mod
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from mmlspark_tpu import obs
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.obs.flightrec import FLIGHT
from mmlspark_tpu.serving.admission import SHED_HEADER, deadline_ms_from
from mmlspark_tpu.serving.server import CachedRequest, WorkerServer
from mmlspark_tpu.serving.udfs import make_reply, request_to_json

# handler: list[CachedRequest] -> dict[id, (code, body_bytes, headers)]
Handler = Callable[[list], dict]

_M_LATENCY = obs.histogram(
    "mmlspark_serving_request_latency_seconds",
    "End-to-end request latency (ingress arrival to reply)",
    labels=("server",),
)
_M_HANDLER_ERRS = obs.counter(
    "mmlspark_serving_handler_errors_total",
    "Handler exceptions turned into 500 batches", labels=("server",),
)
_M_DEADLINE_EXPIRED = obs.counter(
    "mmlspark_serving_deadline_expired_total",
    "Requests shed because their deadline expired while queued",
    labels=("server",),
)
_M_OVERLAP = obs.counter(
    "mmlspark_serving_overlap_batches_total",
    "Batches whose host-side build overlapped a still-executing batch "
    "(continuous batching at work)", labels=("server",),
)


class SplitHandler:
    """A batch handler split into a host-side ``prepare`` (JSON decode,
    array stacking, bucket padding) and a device-side ``execute`` (the
    model call producing the reply dict). The continuous batcher runs
    ``prepare`` for batch N+1 while batch N's ``execute`` is still on
    the device; calling the object directly runs both back to back, so
    a :class:`SplitHandler` is a drop-in plain handler everywhere else.

    Any object with callable ``prepare``/``execute`` attributes
    participates — the loaders' handler classes don't need to inherit.
    """

    __slots__ = ("prepare", "execute")

    def __init__(self, prepare: Callable, execute: Callable):
        self.prepare = prepare
        self.execute = execute

    def __call__(self, reqs: list) -> dict:
        return self.execute(self.prepare(reqs))


def handler_stages(handler: Any) -> Optional[tuple]:
    """The (prepare, execute) split of ``handler``, or None for a plain
    callable (which then runs whole inside the executor stage)."""
    prepare = getattr(handler, "prepare", None)
    execute = getattr(handler, "execute", None)
    if callable(prepare) and callable(execute):
        return prepare, execute
    return None


class LatencyRing:
    """Fixed-capacity ring of end-to-end latencies (ns) with quantile
    readout — shared by :class:`ServingQuery` and the modelstore's
    :class:`~mmlspark_tpu.serving.modelstore.ModelDispatcher` (whose
    per-model batcher threads record concurrently, hence the lock)."""

    def __init__(self, cap: int = 4096):
        self._buf: list = []
        self._cap = cap
        self._count = 0
        self._lock = threading.Lock()

    def record(self, latency_ns: int) -> None:
        with self._lock:
            if len(self._buf) < self._cap:
                self._buf.append(latency_ns)
            else:
                self._buf[self._count % self._cap] = latency_ns
            self._count += 1

    def quantiles_ms(self) -> dict:
        with self._lock:
            buf = list(self._buf)
        if not buf:
            return {}
        arr = np.asarray(buf, dtype=np.float64) / 1e6
        return {
            "p50": float(np.percentile(arr, 50)),
            "p90": float(np.percentile(arr, 90)),
            "p99": float(np.percentile(arr, 99)),
            "n": int(arr.size),
        }


class ServingQuery:
    def __init__(
        self,
        server: WorkerServer,
        handler: Handler,
        mode: str = "continuous",
        max_batch_size: int = 64,
        max_wait_ms: float = 0.0,
        epoch_interval_ms: float = 100.0,
        admission: Optional[Any] = None,
        default_deadline_ms: Optional[float] = None,
        pipeline_depth: int = 2,
    ):
        """``admission``: an
        :class:`~mmlspark_tpu.serving.admission.AdmissionController` —
        attached to the server's ingress (429 shed beyond the adaptive
        in-flight limit) and fed queue-wait/service samples per batch.
        ``default_deadline_ms``: deadline applied to requests carrying no
        ``x-mmlspark-deadline-ms`` header; work whose deadline expired
        while queued is shed 504 without running the handler.
        ``pipeline_depth``: continuous-batching depth (module docstring);
        ``>= 2`` double-buffers build/execute, ``1`` is the classic
        barrier-per-batch loop."""
        if mode not in ("continuous", "microbatch"):
            raise ValueError(f"unknown serving mode {mode!r}")
        self.server = server
        self.handler = handler
        self.mode = mode
        self.max_batch_size = max_batch_size
        self.max_wait_ms = max_wait_ms
        self.epoch_interval_ms = epoch_interval_ms
        self.admission = admission
        self.default_deadline_ms = default_deadline_ms
        self.pipeline_depth = max(1, int(pipeline_depth))
        if admission is not None:
            server.admission = admission
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._exec_thread: Optional[threading.Thread] = None
        # builder -> executor handoff: bounded so admission stays coupled
        # to actual progress (depth-1 staged batches at most)
        self._handoff: queue_mod.Queue = queue_mod.Queue(
            maxsize=self.pipeline_depth - 1 or 1
        )
        self._exec_busy = False
        self._lat = LatencyRing()
        self.batches = 0
        self.errors = 0
        self.deadline_expired = 0
        self.overlapped = 0
        self._m_latency = _M_LATENCY.labels(server=server.name)
        self._m_handler_errs = _M_HANDLER_ERRS.labels(server=server.name)
        self._m_deadline = _M_DEADLINE_EXPIRED.labels(server=server.name)
        self._m_overlap = _M_OVERLAP.labels(server=server.name)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingQuery":
        self._thread = threading.Thread(
            target=self._loop, name=f"{self.server.name}-dispatch", daemon=True
        )
        if (
            self.mode == "continuous"
            and self.pipeline_depth > 1
            and handler_stages(self.handler) is not None
        ):
            # double-buffering exists to overlap a handler's host-side
            # prepare with the previous batch's device execute; a plain
            # handler has no prepare stage to overlap, so the handoff
            # hop would be pure cross-thread scheduling cost on its
            # latency — those keep the classic single-thread loop
            self._exec_thread = threading.Thread(
                target=self._exec_loop, name=f"{self.server.name}-execute",
                daemon=True,
            )
            self._exec_thread.start()
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
        if self._exec_thread is not None:
            self._exec_thread.join(5.0)

    def await_termination(self, timeout_s: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout_s)

    # -- dispatch ------------------------------------------------------------

    def _loop(self) -> None:
        next_epoch_t = time.monotonic() + self.epoch_interval_ms / 1000.0
        while not self._stop.is_set():
            if self.mode == "microbatch":
                # wait out the epoch interval, then process the whole epoch
                now = time.monotonic()
                if now < next_epoch_t:
                    time.sleep(min(next_epoch_t - now, 0.05))
                    continue
                next_epoch_t = time.monotonic() + self.epoch_interval_ms / 1000.0
                epoch = self.server.epoch
                self.server.new_epoch()
                while True:
                    chunk = self.server.get_next_batch(
                        self.max_batch_size, timeout_s=0.0
                    )
                    if not chunk:
                        break
                    self._process(chunk)  # honor max_batch_size per XLA call
                self.server.commit(epoch)
            else:
                # idle wait is long (bounds stop() responsiveness only —
                # enqueue notifies the condition, so arrival latency doesn't
                # depend on it); max_wait_ms governs batch accumulation once
                # the first request is in. Continuous-batching refinement:
                # accumulation exists to amortize a BUSY executor — while
                # it is idle, holding the batch open is pure added latency,
                # so dispatch immediately and let the next batch form
                # behind the running one
                accumulate_s = self.max_wait_ms / 1000.0
                if self._exec_thread is not None and not self._exec_busy:
                    accumulate_s = 0.0
                reqs = self.server.get_next_batch(
                    self.max_batch_size, timeout_s=0.25,
                    accumulate_s=accumulate_s,
                )
                if not reqs:
                    continue
                if self._exec_thread is not None:
                    self._build(reqs)
                else:
                    self._process(reqs)
                self.server.auto_commit()

    # -- continuous batching (builder + executor threads) ---------------------

    def _build(self, reqs: list) -> None:
        """Builder half of the continuous-batch pipeline: shed expired
        work at the admission point, run the handler's host-side
        ``prepare`` (when it has one), and hand the staged batch to the
        executor — all while the previous batch may still be executing."""
        reqs = self._shed_expired(reqs)
        if not reqs:
            return
        split = handler_stages(self.handler)
        staged = err = None
        if split is not None:
            try:
                staged = split[0](reqs)
            except Exception as e:  # noqa: BLE001 — surfaces as a 500 batch
                err = e
        if self._exec_busy:
            # evidence the double-buffer is overlapping: this batch's
            # arrays were built while the previous batch computed
            self.overlapped += 1
            if self._m_overlap._on:
                self._m_overlap.inc()
        self._handoff.put((reqs, staged, err))

    def _exec_loop(self) -> None:
        while True:
            try:
                item = self._handoff.get(timeout=0.25)
            except queue_mod.Empty:
                # exit only once the BUILDER is gone too: a builder
                # mid-put while we observe an empty queue must not
                # strand its staged batch unanswered
                if self._stop.is_set() and not (
                    self._thread is not None and self._thread.is_alive()
                ):
                    return
                continue
            self._exec_busy = True
            try:
                self._execute(*item)
            finally:
                self._exec_busy = False

    def _shed_expired(self, reqs: list) -> list:
        """Drop requests whose deadline already expired while they sat in
        the queue: the client gave up — running the handler for them
        burns a batch slot on a reply nobody reads, exactly when the
        queue is longest. Replies 504 so a gateway relays the expiry
        rather than retrying it."""
        now_ns = time.perf_counter_ns()
        live = []
        for r in reqs:
            dl_ms = deadline_ms_from(r.headers, self.default_deadline_ms)
            if dl_ms is not None and (now_ns - r.arrival_ns) / 1e6 > dl_ms:
                self.deadline_expired += 1
                self._m_deadline.inc()
                self.server.reply_to(
                    r.id, b'{"error": "deadline expired in queue"}', 504,
                    {"Content-Type": "application/json",
                     SHED_HEADER: "deadline"},
                )
            else:
                live.append(r)
        return live

    def _process(self, reqs: list) -> None:
        """Barrier path (microbatch mode / ``pipeline_depth=1``): build
        and execute inline — same stages as the pipelined path, zero
        overlap."""
        reqs = self._shed_expired(reqs)
        if not reqs:
            return
        split = handler_stages(self.handler)
        staged = err = None
        if split is not None:
            try:
                staged = split[0](reqs)
            except Exception as e:  # noqa: BLE001 — surfaces as a 500 batch
                err = e
        self._execute(reqs, staged, err)

    def _execute(self, reqs: list, staged: Any, prep_err: Any) -> None:
        obs_on = self._m_latency._on
        dispatch_ns = time.perf_counter_ns()  # ~= execute-slot time
        # per-request span AND trace ids are minted BEFORE dispatch so
        # the batch span can parent under the first request's span in the
        # first request's trace (headerless direct traffic mints here) —
        # the collector then renders queue wait and model time as
        # children of the request, under the gateway's forward span
        # (PARENT_HEADER) when there is one
        req_sids = req_tids = None
        if obs_on:
            req_sids = {r.id: obs.new_span_id() for r in reqs}
            req_tids = {
                r.id: r.headers.get(obs.TRACE_HEADER) or obs.new_trace_id()
                for r in reqs
            }
        split = handler_stages(self.handler)
        try:
            if prep_err is not None:
                raise prep_err
            # the dispatch span wraps the model call, so inside a
            # jax.profiler capture the XLA dispatch nests under it; the
            # trace id continues from the gateway's stamped header
            ctx = (
                obs.span(
                    "serving.dispatch",
                    trace_id=req_tids[reqs[0].id],
                    parent_id=req_sids[reqs[0].id],
                    attrs={"batch": len(reqs)},
                )
                if obs_on
                else contextlib.nullcontext()
            )
            with ctx:
                replies = (
                    split[1](staged) if split is not None
                    else self.handler(reqs)
                )
        except Exception as e:  # handler crash -> 500s, keep serving
            self.errors += 1
            self._m_handler_errs.inc()
            msg = f"handler error: {type(e).__name__}: {e}".encode()
            replies = {r.id: (500, msg, {}) for r in reqs}
        done_ns = time.perf_counter_ns()
        # two passes: every reply goes out BEFORE any telemetry is
        # recorded. The dispatcher thread is the pipeline bottleneck
        # under concurrency — recording first would add its cost to every
        # queued request's latency, recording after overlaps it with the
        # clients' own processing. On the pipelined (split-handler) path
        # reply_many batches the whole batch's replies into one loop
        # wakeup per reactor; the plain-handler barrier path keeps
        # per-reply scheduling — its batch replies landing in lockstep
        # would phase-align keep-alive clients' next requests against
        # the accumulation window and tax light-load p50 for no
        # throughput gain (that path has no build/execute overlap to
        # feed anyway)
        codes = {}
        batch_out = []
        for r in reqs:
            code, body, headers = replies.get(
                r.id, (500, b"no reply produced", {})
            )
            batch_out.append((r.id, body, code, headers))
            codes[r.id] = code
        if self._exec_thread is not None:
            self.server.reply_many(batch_out)
        else:
            for rid, body, code, headers in batch_out:
                self.server.reply_to(rid, body, code, headers)
        for r in reqs:
            if obs_on:
                code = codes[r.id]
                sid = req_sids[r.id]
                tid = req_tids[r.id]
                obs.record_span(
                    "serving.request", r.arrival_ns, done_ns,
                    trace_id=tid,
                    span_id=sid,
                    parent_id=r.headers.get(obs.PARENT_HEADER),
                    attrs={"status": code},
                )
                obs.record_span(
                    "serving.queue", r.arrival_ns, dispatch_ns,
                    trace_id=tid, parent_id=sid,
                )
                lat_s = (done_ns - r.arrival_ns) / 1e9
                # exemplar: the p99 bucket remembers a real trace id
                self._m_latency.observe(lat_s, trace_id=tid)
                FLIGHT.record(
                    "ok" if code < 500 else "error",
                    status=code,
                    trace_id=tid,
                    path=r.path,
                    latency_ms=lat_s * 1e3,
                    queue_wait_ms=(dispatch_ns - r.arrival_ns) / 1e6,
                )
            self._lat.record(done_ns - r.arrival_ns)
        if self.admission is not None:
            # AIMD signal: the batch's worst queue wait (reqs are FIFO,
            # so the first request waited longest) + per-request service
            self.admission.observe(
                (dispatch_ns - reqs[0].arrival_ns) / 1e9,
                (done_ns - dispatch_ns) / 1e9 / len(reqs),
            )
        self.batches += 1

    # -- stats ---------------------------------------------------------------

    def latency_quantiles_ms(self) -> dict:
        return self._lat.quantiles_ms()


# --------------------------------------------------------------------------


def _bucket(n: int, cap: Optional[int] = None) -> int:
    """Next power of two >= ``n``, capped at the next power of two >=
    ``cap``. The cap bounds the set of distinct padded shapes a handler
    can produce — and with it the number of XLA compiles — to
    ``log2(cap) + 1`` buckets regardless of what batch sizes arrive."""
    b = 1
    while b < n:
        b *= 2
    if cap is not None:
        c = 1
        while c < cap:
            c *= 2
        b = min(b, c)
    return b


def serve_transformer(
    transformer: Any,
    input_col: str,
    output_col: str,
    server: Optional[WorkerServer] = None,
    host: str = "127.0.0.1",
    port: int = 0,
    api_path: str = "/",
    mode: str = "continuous",
    max_batch_size: int = 64,
    max_wait_ms: float = 0.0,
    epoch_interval_ms: float = 100.0,
    name: str = "serving",
) -> ServingQuery:
    """Serve a fitted Transformer (or plain ``fn(np.ndarray)->np.ndarray``):
    JSON request bodies become ``input_col`` rows, the transformer runs on a
    bucket-padded batch, ``output_col`` values return as JSON replies.

    Returns a started :class:`ServingQuery`; ``q.server.port`` is the bound
    port. This is the ``spark.readStream.continuousServer()`` +
    ``makeReply`` one-liner of the reference (IOImplicits).
    """
    srv = server or WorkerServer(host=host, port=port, api_path=api_path, name=name)
    if srv.port == 0:
        srv.start()

    is_transformer = hasattr(transformer, "transform")
    from mmlspark_tpu.serving.server import _M_BATCH

    m_bucket = _M_BATCH.labels(server=f"{srv.name}/buckets")

    def prepare(reqs: list) -> tuple:
        """Host-side build (runs on the batcher thread while the previous
        batch executes): JSON decode, per-request validation, shape
        grouping, stacking and bucket padding — everything but the model
        call."""
        vals = [request_to_json(r) for r in reqs]
        bad = {
            r.id: (400, b"invalid or empty JSON body", {})
            for r, v in zip(reqs, vals) if v is None
        }
        live = [(r, v) for r, v in zip(reqs, vals) if v is not None]
        # per-request validation: one malformed request must not poison the
        # batch for well-formed concurrent clients. Non-numeric bodies 400;
        # remaining requests are grouped by feature shape and each group
        # runs as its own fixed-shape batch, so a group the model rejects
        # errors alone.
        groups: dict = {}
        for r, v in live:
            try:
                arr = np.asarray(v, dtype=np.float32)
            except (TypeError, ValueError):
                bad[r.id] = (400, b"non-numeric request body", {})
                continue
            groups.setdefault(arr.shape, []).append((r, arr))
        staged = []
        cap_b = _bucket(max_batch_size)
        for group in groups.values():
            # bucket capped at the next power of two >= max_batch_size:
            # oversized groups (a caller handing the handler more than the
            # query's pop limit) are split into cap-sized chunks, so the
            # padded-shape set — and with it the compile count — is
            # bounded at log2(cap)+1 buckets no matter what arrives.
            # Chosen buckets land in the batch-size histogram under
            # "<name>/buckets", next to the raw ingress batch sizes
            for start in range(0, len(group), cap_b):
                items = group[start:start + cap_b]
                n = len(items)
                x = np.stack([a for _, a in items])
                b = _bucket(n, cap=max_batch_size)
                if m_bucket._on:
                    m_bucket.observe(b)
                if b > n:  # fixed-shape batch: pad, run, slice
                    pad = np.repeat(x[:1], b - n, axis=0)
                    x = np.concatenate([x, pad], axis=0)
                staged.append((items, x, n))
        return bad, staged

    def execute(staged: tuple) -> dict:
        """Device-side half: one model call per fixed-shape group."""
        bad, groups = staged
        replies = dict(bad)
        for items, x, n in groups:
            try:
                if is_transformer:
                    df = DataFrame([{input_col: x}])
                    out = transformer.transform(df)[output_col][:n]
                else:
                    out = np.asarray(transformer(x))[:n]
            except Exception as e:
                msg = (
                    f"model rejected input: {type(e).__name__}: {e}"
                ).encode()
                for r, _ in items:
                    replies[r.id] = (400, msg, {})
                continue
            for (r, _), o in zip(items, out):
                code, body, headers = make_reply(o)
                replies[r.id] = (code, body, headers)
        return replies

    handler = SplitHandler(prepare, execute)

    return ServingQuery(
        srv, handler, mode=mode, max_batch_size=max_batch_size,
        max_wait_ms=max_wait_ms, epoch_interval_ms=epoch_interval_ms,
    ).start()
