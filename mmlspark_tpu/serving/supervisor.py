"""Fleet supervisor: restart crashed/wedged local workers automatically.

The last open loop in the self-healing story: breakers and retry budgets
(serving/distributed.py) contain a dead worker's blast radius, admission
control (serving/admission.py) keeps the survivors meeting deadlines —
but the dead worker itself stayed dead until an operator noticed.
``fleet supervise`` closes that loop for locally-managed workers:

    python -m mmlspark_tpu.serving.fleet supervise \
        --registry http://registry:9090/ \
        --worker "--model echo --port 9101 --load resnet=zoo:ResNet8_Digits" \
        --worker "--model echo --port 9102"

Each ``--worker`` charge is one ``fleet worker`` process the supervisor
spawns and watches. A charge is restarted when

- its **process exits** (crash, OOM-kill, preemption), or
- it is **wedged**: ``wedge_after`` consecutive ``GET /health`` probes
  fail or time out while the process is still running (an event loop
  stuck behind a blocked thread answers nothing — exactly the state a
  process poll cannot see). Wedged charges are killed first.

Restarts re-issue the charge's full original argv — including its
``--load name=spec`` flags — so a restarted ModelStore worker loads and
warms the same models BEFORE re-registering (the fleet worker's
warm-before-register ordering), and the roster heals without operator
action. Restart pacing is capped exponential backoff
(``backoff_s * 2^(streak-1)``, capped at ``backoff_max_s``); the streak
resets once a charge stays up ``stable_s``, so a crash-loop cannot spin
a hot respawn loop while a one-off crash restarts almost immediately.

Fault point ``supervisor.restart`` fires as each restart is about to
spawn: an injected error suppresses that restart attempt (retried next
tick — chaos for "the scheduler refused"), ``delay_s`` stalls it.

The supervisor is observable like every other fleet role: it runs a
minimal ingress serving ``GET /metrics`` (``mmlspark_supervisor_*``
gauges/counters) and heartbeat-registers under
``<service-name>-supervisor`` so ``fleet top`` finds it on the roster
and surfaces its status in the header line.
"""

from __future__ import annotations

import shlex
import subprocess
import sys
import threading
import time
from typing import Any, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults

_M_CHARGES = obs.gauge(
    "mmlspark_supervisor_charges_count",
    "Worker processes under supervision",
)
_M_UP = obs.gauge(
    "mmlspark_supervisor_charges_up_count",
    "Supervised workers currently running (process alive, not wedged)",
)
_M_RESTARTS = obs.counter(
    "mmlspark_supervisor_restarts_total",
    "Worker restarts by the supervisor", labels=("worker", "reason"),
)
_M_PROBE_FAILS = obs.counter(
    "mmlspark_supervisor_probe_failures_total",
    "Failed /health probes against supervised workers", labels=("worker",),
)
_M_BACKOFF = obs.counter(
    "mmlspark_supervisor_backoff_seconds_total",
    "Cumulative restart-backoff delay imposed on crash-looping workers",
)
_M_FENCED_RESPAWNS = obs.counter(
    "mmlspark_supervisor_fenced_respawns_total",
    "Respawns deferred because the charge's gang incumbent is alive in "
    "the majority registry view", labels=("worker",),
)


class WorkerCharge:
    """One supervised worker: the argv to (re)spawn and how to probe it.

    ``argv`` is the FULL command line (``sys.executable -m ... worker
    ...``) — re-running it verbatim is what brings ``--load`` models back
    warm. ``health_url`` is probed when set; a charge without one (e.g.
    an ephemeral ``--port 0`` worker whose address changes per restart)
    is supervised on process liveness alone."""

    def __init__(self, argv: list, name: str,
                 health_url: Optional[str] = None,
                 gang_member: Optional[str] = None,
                 gang_service: Optional[str] = None):
        self.argv = list(argv)
        self.name = name
        self.health_url = health_url
        # gang identity of a training charge: when set, a respawn is
        # FENCED while a live roster entry under <gang_service>-gang
        # still advertises this member name on a majority of the
        # configured registries — a partitioned-but-alive incumbent must
        # not gain a same-name twin (split-brain via supervisor grow-back)
        self.gang_member = gang_member
        self.gang_service = gang_service
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.streak = 0            # consecutive fast deaths (backoff input)
        self.started_at = 0.0
        self.restart_due = 0.0     # monotonic ts the next spawn may happen
        self.probe_fails = 0       # consecutive failed health probes
        self.healthy_once = False  # has /health ever answered this spawn?
        self.last_reason = ""

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


def _probe_health(url: str, timeout_s: float) -> str:
    """One ``/health`` probe -> ``"ok"`` | ``"refused"`` | ``"silent"``.

    The distinction matters: a WEDGED worker (stuck event loop) still
    ACCEPTS connections — its listen backlog answers the handshake —
    and then never replies (``silent``). A connect that is REFUSED
    means there is no listener at all: the worker is booting, or mid
    graceful-drain (``pause_accepting`` closed the listener while
    in-flight requests finish). Killing a draining worker as "wedged"
    would drop exactly the requests the drain exists to protect, so
    the caller weighs ``refused`` far more leniently than ``silent``."""
    import http.client
    import urllib.parse

    u = urllib.parse.urlparse(url)
    try:
        c = http.client.HTTPConnection(
            u.hostname, u.port or 80, timeout=timeout_s
        )
        try:
            c.request("GET", u.path or "/health")
            resp = c.getresponse()
            resp.read()
            # ANY answer is an alive worker — 503 is warming, 429 is
            # shedding (alive and protecting itself; killing it would
            # shrink the fleet under overload, the exact wrong
            # direction). Only no-answer-at-all counts as wedged.
            return "ok"
        finally:
            c.close()
    except ConnectionRefusedError:
        return "refused"
    except Exception:  # noqa: BLE001 — any transport failure = probe miss
        return "silent"


def spawn_from_template(template: str) -> Any:
    """``--spawn-cmd`` -> a spawn callable: placement becomes pluggable,
    so restarts and autoscale-outs can land on REMOTE hosts (SSH- or
    k8s-shaped) instead of only this machine.

    The template is shell-split once; a bare ``{argv}`` token splices
    the charge's argv as separate arguments (local exec-style wrappers —
    ``nice``, ``kubectl run … --``), while ``{argv}`` embedded in a
    larger token substitutes the SHELL-QUOTED command line — the form a
    remote shell needs, because ssh joins its arguments with plain
    spaces and the far side word-splits them. Note shell quoting: in
    ``'ssh h "{argv}"'`` the inner quotes are consumed by shlex, leaving
    a bare token again — give the remote form surrounding text, e.g.
    ``exec {argv}``. Without any ``{argv}`` the argv is appended.
    Examples::

        --spawn-cmd 'kubectl run trainer --image=mmlspark -- {argv}'
        --spawn-cmd "ssh worker-7 'exec {argv}'"

    The spawned process must still reach ``--registry`` and serve its
    own health/artifact endpoints — remote charges boot their models and
    checkpoints from pulled artifacts (serving/artifacts.py), which is
    what makes cross-host placement work without a shared filesystem."""
    base = shlex.split(template)

    def spawn(argv: list) -> subprocess.Popen:
        out: list = []
        spliced = False
        for tok in base:
            if tok == "{argv}":
                out.extend(argv)
                spliced = True
            elif "{argv}" in tok:
                out.append(tok.replace("{argv}", shlex.join(argv)))
                spliced = True
            else:
                out.append(tok)
        if not spliced:
            out.extend(argv)
        return subprocess.Popen(out)

    return spawn


class PlacementProvider:
    """WHERE a (re)spawned charge's process lands — the ``--spawn-cmd``
    template generalized into a first-class hook.

    The supervisor only ever calls :meth:`spawn`; everything upstream of
    that call is provider-independent and therefore carries over to
    remote placements verbatim: restart backoff pacing, the
    ``supervisor.restart`` fault point, and the whole split-brain
    fencing stack — boot-stamped roster waits (``rolling_restart``),
    epoch tokens on every write plane the spawned process touches, and
    the majority-claim respawn deferral (``_incumbent_fenced``). A
    remotely-placed trainer is fenced by exactly the same rules as a
    local one, because fencing reads the REGISTRY view, never the
    process table.

    Remote charges cannot see the supervisor's filesystem: they boot
    models and checkpoints from pulled artifacts
    (serving/artifacts.py), which is what makes cross-host placement
    work without a shared directory."""

    scheme = "local"

    def spawn(self, argv: list) -> subprocess.Popen:
        raise NotImplementedError

    def describe(self) -> str:
        return self.scheme


class LocalPlacement(PlacementProvider):
    """Processes land on this machine: plain ``subprocess.Popen``, or a
    wrapper template (``nice -n 10 {argv}``) via
    :func:`spawn_from_template` when ``template`` is given."""

    scheme = "local"

    def __init__(self, template: Optional[str] = None):
        self.template = template
        self._spawn = (
            spawn_from_template(template) if template
            else (lambda argv: subprocess.Popen(argv))
        )

    def spawn(self, argv: list) -> subprocess.Popen:
        return self._spawn(argv)

    def describe(self) -> str:
        return f"local:{self.template}" if self.template else "local"


class RemotePlacement(PlacementProvider):
    """Base for placements that launch the charge on ANOTHER host.

    Subclasses implement :meth:`transport_argv` — argv -> the local
    command whose job is to start the charge remotely (``ssh …``,
    ``kubectl run …``). Fault point ``supervisor.spawn_remote`` fires
    as each remote launch is about to happen: an injected error is "the
    remote scheduler refused the allocation" — the spawn fails and the
    ordinary supervision loop retries it next tick under backoff, while
    ``delay_s`` models a slow placement decision. ``runner`` is
    injectable for tests (defaults to ``subprocess.Popen``) so the
    transport argv can be asserted without an ssh/kubectl binary."""

    scheme = "remote"

    def __init__(self, target: str, runner: Any = None):
        self.target = target
        self._runner = runner or subprocess.Popen

    def transport_argv(self, argv: list) -> list:
        raise NotImplementedError

    def spawn(self, argv: list) -> subprocess.Popen:
        faults.inject(
            "supervisor.spawn_remote",
            context={"scheme": self.scheme, "target": self.target},
        )
        return self._runner(self.transport_argv(argv))

    def describe(self) -> str:
        return f"{self.scheme}:{self.target}"


class SshPlacement(RemotePlacement):
    """SSH-shaped placement: ``ssh <host> 'exec <shell-quoted argv>'``.

    The command line is shell-quoted as ONE remote token because ssh
    joins its arguments with plain spaces and the far side word-splits
    them; ``exec`` keeps the remote shell from lingering as an extra
    parent. BatchMode refuses interactive prompts — a supervisor must
    fail fast and retry under backoff, not hang on a password read."""

    scheme = "ssh"

    def transport_argv(self, argv: list) -> list:
        return [
            "ssh", "-o", "BatchMode=yes", self.target,
            "exec " + shlex.join(argv),
        ]


class K8sPlacement(RemotePlacement):
    """k8s-shaped placement stub: ``kubectl run <name> --image=<image>
    --restart=Never -- <argv>``.

    A stub in the precise sense: the transport argv is the real kubectl
    shape, but nothing here watches the pod — the supervisor supervises
    the LOCAL kubectl process it spawned, so ``--restart=Never`` plus
    ``--attach`` semantics (kubectl exits when the pod does) are what
    tie pod death back to the charge-exit path. Pod names are
    ``mmlspark-<charge>-<n>`` with a per-provider counter: ``kubectl
    run`` refuses duplicate names, and a respawn must be a NEW pod."""

    scheme = "k8s"

    def __init__(self, image: str, namespace: str = "default",
                 runner: Any = None):
        super().__init__(target=f"{image}@{namespace}", runner=runner)
        self.image = image
        self.namespace = namespace
        self._seq = 0

    def transport_argv(self, argv: list) -> list:
        self._seq += 1
        return [
            "kubectl", "run", f"mmlspark-charge-{self._seq}",
            f"--image={self.image}", f"--namespace={self.namespace}",
            "--restart=Never", "--attach", "--rm", "--quiet", "--",
            *argv,
        ]


def placement_from_spec(spec: str) -> PlacementProvider:
    """``--placement`` grammar -> a provider.

    ``local``                 -> plain subprocess
    ``ssh:<host>``            -> :class:`SshPlacement`
    ``k8s:<image>[@<ns>]``    -> :class:`K8sPlacement`
    anything else             -> a :class:`LocalPlacement` wrapper
                                 template (the legacy ``--spawn-cmd``
                                 form — ``nice -n 10 {argv}``)"""
    spec = spec.strip()
    if spec in ("", "local"):
        return LocalPlacement()
    if spec.startswith("ssh:"):
        host = spec[len("ssh:"):]
        if not host:
            raise ValueError("placement 'ssh:' needs a host")
        return SshPlacement(host)
    if spec.startswith("k8s:"):
        rest = spec[len("k8s:"):]
        if not rest:
            raise ValueError("placement 'k8s:' needs an image")
        image, _, ns = rest.partition("@")
        return K8sPlacement(image, namespace=ns or "default")
    return LocalPlacement(template=spec)


class FleetSupervisor:
    """Watch charges, restart the dead and the wedged, export status.

    ``registry_url``: when set, the supervisor heartbeat-registers its
    own status endpoint under ``<service_name>-supervisor`` so ``fleet
    top`` can find it. ``spawn`` is injectable for tests (defaults to
    ``subprocess.Popen``); ``spawn_cmd`` is the operator-facing template
    form of the same hook (:func:`spawn_from_template`); ``placement``
    is the generalization of both — a :class:`PlacementProvider` (or
    its ``--placement`` spec string) deciding WHERE every spawn lands:
    local subprocess, SSH-shaped, or k8s-shaped remote."""

    def __init__(
        self,
        charges: list,
        registry_url: Optional[str] = None,
        service_name: str = "serving",
        probe_s: float = 2.0,
        probe_timeout_s: float = 2.0,
        wedge_after: int = 3,
        backoff_s: float = 1.0,
        backoff_max_s: float = 30.0,
        stable_s: float = 30.0,
        startup_grace_s: float = 60.0,
        host: str = "127.0.0.1",
        port: int = 0,
        spawn: Any = None,
        spawn_cmd: Optional[str] = None,
        placement: Any = None,
        autoscaler: Any = None,
        worker_template: Optional[str] = None,
        signals_fn: Any = None,
    ):
        """``autoscaler`` (an :class:`~mmlspark_tpu.online.autoscaler.
        Autoscaler`) turns supervision into autoscaling: each tick the
        policy decides a desired replica count from ``signals_fn()``
        (a :class:`ScaleSignals` source, e.g. ``FleetSignals``) and the
        supervisor spawns a ``worker_template`` charge or reaps an
        autoscaled one — only charges IT created are ever reaped, the
        operator's original ``--worker`` charges are a floor. The
        ``autoscaler.scale`` fault point gates every action."""
        self.charges: list = list(charges)
        self.registry_url = registry_url
        self.service_name = service_name
        self.probe_s = probe_s
        self.probe_timeout_s = probe_timeout_s
        self.wedge_after = max(1, int(wedge_after))
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self.stable_s = stable_s
        self.startup_grace_s = startup_grace_s
        self._host = host
        self._port = port
        # placement resolution, most specific first: an explicit spawn
        # callable (test hook) > a PlacementProvider (or its --placement
        # spec string) > the legacy --spawn-cmd wrapper template > local
        # subprocess. All four funnel into the same self._spawn call
        # site, so fencing and backoff see no difference.
        if isinstance(placement, str):
            placement = placement_from_spec(placement)
        if placement is None and spawn_cmd:
            placement = LocalPlacement(template=spawn_cmd)
        if placement is None and spawn is None:
            placement = LocalPlacement()
        self._placement = placement
        self._spawn = spawn or placement.spawn
        self._autoscaler = autoscaler
        self._worker_template = worker_template
        self._signals_fn = signals_fn
        # latest sample from the signals thread: signal sources scrape
        # /metrics over the network with multi-second timeouts, and that
        # must never stall the supervision tick — crash/wedge handling
        # has to stay responsive exactly when nodes are dying
        self._last_signals: Any = None
        self._signals_thread: Optional[threading.Thread] = None
        self._autoscaled: list = []  # charges the autoscaler created
        self._scale_index = len(self.charges)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ingress: Any = None
        self._info: Any = None
        self._lock = threading.Lock()
        _M_CHARGES.set(len(self.charges))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        from mmlspark_tpu.serving.server import WorkerServer

        # minimal status ingress: GET /metrics is answered inline by the
        # WorkerServer machinery; nothing ever dispatches from its queue
        self._ingress = WorkerServer(
            host=self._host, port=self._port,
            name=f"{self.service_name}-supervisor",
        )
        self._info = self._ingress.start()
        for c in self.charges:
            self._spawn_charge(c, first=True)
        if self._autoscaler is not None and self._signals_fn is not None:
            self._signals_thread = threading.Thread(
                target=self._signals_loop, name="fleet-autoscale-signals",
                daemon=True,
            )
            self._signals_thread.start()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def _signals_loop(self) -> None:
        """Sample the scale-signal source off the supervision path: a
        blackholed scrape eats its own thread's time, not a tick's."""
        while not self._stop.is_set():
            try:
                self._last_signals = self._signals_fn()
            except Exception as e:  # noqa: BLE001 — a blind sample = hold
                print(f"supervisor: signal sample failed: {e}",
                      file=sys.stderr, flush=True)
            self._stop.wait(self.probe_s)

    def stop(self, kill_charges: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
        if self._signals_thread is not None:
            self._signals_thread.join(5.0)
        if kill_charges:
            for c in self.charges:
                if c.alive():
                    c.proc.terminate()
            for c in self.charges:
                if c.proc is not None:
                    try:
                        c.proc.wait(5.0)
                    except Exception:  # noqa: BLE001 — escalate to SIGKILL
                        c.proc.kill()
        if self._info is not None:
            from mmlspark_tpu.serving.registry import DriverRegistry

            for url in self._registry_urls():
                try:
                    DriverRegistry.deregister(url, self._info)
                except Exception:  # noqa: BLE001 — registry may be gone
                    pass
        if self._ingress is not None:
            self._ingress.stop()

    def _registry_urls(self) -> list:
        """Registry HA: ``registry_url`` may be one URL, a comma-
        separated list, or a sequence — heartbeats go to ALL of them."""
        from mmlspark_tpu.serving.fleet import split_registry_urls

        return split_registry_urls(self.registry_url)

    @property
    def url(self) -> str:
        return f"http://{self._info.host}:{self._info.port}/"

    # -- supervision ---------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            return {
                "charges": len(self.charges),
                "placement": (
                    self._placement.describe()
                    if self._placement is not None else "custom"
                ),
                "up": sum(1 for c in self.charges if c.alive()),
                "restarts": sum(c.restarts for c in self.charges),
                "workers": {
                    c.name: {
                        "alive": c.alive(),
                        "restarts": c.restarts,
                        "last_reason": c.last_reason,
                    }
                    for c in self.charges
                },
            }

    def _spawn_charge(self, c: WorkerCharge, first: bool = False) -> bool:
        try:
            # fault point supervisor.restart: an injected error is "the
            # scheduler refused this respawn" — retried next tick; delay
            # stalls the restart like a slow node allocation
            if not first:
                faults.inject(
                    "supervisor.restart", context={"worker": c.name}
                )
            c.proc = self._spawn(c.argv)
            c.started_at = time.monotonic()
            c.probe_fails = 0
            c.healthy_once = False
            return True
        except Exception as e:  # noqa: BLE001 — injected or spawn failure
            c.last_reason = f"spawn failed: {e}"
            c.restart_due = time.monotonic() + self.backoff_s
            return False

    def _restart(self, c: WorkerCharge, reason: str) -> None:
        now = time.monotonic()
        if c.alive():  # wedged: the process must die before its successor
            c.proc.kill()
            try:
                c.proc.wait(5.0)
            except Exception:  # noqa: BLE001
                pass
        if c.restart_due == 0.0:
            # first detection of this death: compute the backoff window
            if now - c.started_at >= self.stable_s:
                c.streak = 0  # it ran fine for a while — fresh slate
            c.streak += 1
            delay = min(
                self.backoff_max_s, self.backoff_s * (2 ** (c.streak - 1))
            )
            _M_BACKOFF.inc(delay)
            c.restart_due = now + delay
            c.last_reason = reason
            print(
                f"supervisor: worker {c.name} {reason}; restart in "
                f"{delay:.1f}s (streak {c.streak})",
                file=sys.stderr, flush=True,
            )
            return
        if now < c.restart_due:
            return  # still inside the backoff window
        if self._incumbent_fenced(c):
            # the majority registry view says this gang member is STILL
            # ALIVE — the "death" we observed is our local partition
            # talking, and a respawn would seed a same-name twin gang.
            # Defer; TTL expiry clears the entry once it is truly dead.
            _M_FENCED_RESPAWNS.labels(worker=c.name).inc()
            c.restart_due = now + self.backoff_s
            c.last_reason = "fenced: incumbent alive in majority view"
            print(
                f"supervisor: respawn of {c.name} fenced — gang member "
                f"{c.gang_member} is alive on a registry majority; "
                f"retry in {self.backoff_s:.1f}s",
                file=sys.stderr, flush=True,
            )
            return
        if self._spawn_charge(c):
            c.restarts += 1
            c.restart_due = 0.0
            _M_RESTARTS.labels(worker=c.name, reason=reason).inc()
            print(
                f"supervisor: worker {c.name} restarted ({reason}, "
                f"restart #{c.restarts})", file=sys.stderr, flush=True,
            )

    def _tick(self) -> None:
        with self._lock:
            up = 0
            for c in self.charges:
                if not c.alive():
                    self._restart(c, c.last_reason or "exited")
                    if c.alive():
                        up += 1
                    continue
                c.restart_due = 0.0
                c.last_reason = ""
                if c.health_url:
                    verdict = _probe_health(c.health_url, self.probe_timeout_s)
                    if verdict == "ok":
                        c.probe_fails = 0
                        c.healthy_once = True
                    elif (
                        c.healthy_once
                        or time.monotonic() - c.started_at
                        > self.startup_grace_s
                    ):
                        # startup grace: a worker that has never answered
                        # yet may still be importing/warming — killing it
                        # mid-warmup would crash-loop a healthy charge.
                        # Once it HAS been healthy (or the grace is
                        # blown), silence means wedged. A REFUSED connect
                        # is weighed 10x more leniently: no listener
                        # means booting or mid graceful-drain (SIGTERM
                        # closed the acceptor while in-flight work
                        # finishes) — killing a draining worker would
                        # drop exactly the requests the drain protects;
                        # a shutdown genuinely stuck with its listener
                        # closed still gets reaped, just slowly
                        weight = 1.0 if verdict == "silent" else 0.1
                        c.probe_fails += weight
                        # the metric carries the SAME weight as the
                        # wedge accounting: a draining worker's refused
                        # probes must not read as full-rate failures to
                        # an operator alert
                        _M_PROBE_FAILS.labels(worker=c.name).inc(weight)
                        if c.probe_fails >= self.wedge_after:
                            self._restart(c, "wedged")
                            continue
                up += 1
            _M_UP.set(up)
            _M_CHARGES.set(len(self.charges))
        self._autoscale()
        if self._info is not None:
            from mmlspark_tpu.serving.registry import DriverRegistry

            for url in self._registry_urls():
                try:
                    DriverRegistry.register(url, self._info)
                except Exception:  # noqa: BLE001 — registry may be restarting
                    pass

    # -- autoscaling ---------------------------------------------------------

    def _autoscale(self) -> None:
        if self._autoscaler is None:
            return
        from mmlspark_tpu.online.autoscaler import Autoscaler, ScaleSignals

        # the signals thread feeds _last_signals; until the first sample
        # lands (or with no source at all) the policy sees a quiet fleet
        # and holds — an autoscaler without evidence must not act
        signals = self._last_signals
        if signals is None:
            signals = ScaleSignals()
        with self._lock:
            current = len(self.charges)
        Autoscaler.export_replicas(current)
        desired, reason = self._autoscaler.decide(current, signals)
        if desired == current:
            return
        direction = "out" if desired > current else "in"
        try:
            # fault point autoscaler.scale: an injected error is "the
            # scheduler refused this scale event" — retried next tick
            faults.inject(
                "autoscaler.scale",
                context={"direction": direction, "reason": reason},
            )
        except Exception as e:  # noqa: BLE001 — injected refusal
            print(
                f"supervisor: autoscale {direction} suppressed: {e}",
                file=sys.stderr, flush=True,
            )
            return
        if direction == "out":
            self._scale_out(reason)
        else:
            self._scale_in(reason)
        with self._lock:
            Autoscaler.export_replicas(len(self.charges))

    def _scale_out(self, reason: str) -> None:
        from mmlspark_tpu.online.autoscaler import Autoscaler

        if not self._worker_template:
            print(
                "supervisor: autoscale wants a replica but no "
                "--worker-template is set", file=sys.stderr, flush=True,
            )
            return
        c = charge_from_worker_args(
            self._worker_template, self.registry_url or "",
            self._scale_index,
        )
        self._scale_index += 1
        c.name = f"autoscaled-{c.name}"
        if not self._spawn_charge(c, first=True):
            return
        with self._lock:
            self.charges.append(c)
            self._autoscaled.append(c)
            _M_CHARGES.set(len(self.charges))
        Autoscaler.note_applied("out")
        print(
            f"supervisor: scaled OUT to {len(self.charges)} ({reason}): "
            f"{c.name}", file=sys.stderr, flush=True,
        )

    def _scale_in(self, reason: str) -> None:
        from mmlspark_tpu.online.autoscaler import Autoscaler

        with self._lock:
            # only reap replicas the autoscaler created — the operator's
            # own charges are a floor, not scaling headroom
            victim = None
            while self._autoscaled:
                cand = self._autoscaled.pop()
                if cand in self.charges:
                    victim = cand
                    break
            if victim is None:
                return
            self.charges.remove(victim)
            _M_CHARGES.set(len(self.charges))
        if victim.alive():
            victim.proc.terminate()  # SIGTERM: the worker deregisters clean
            try:
                victim.proc.wait(5.0)
            except Exception:  # noqa: BLE001 — escalate
                victim.proc.kill()
        Autoscaler.note_applied("in")
        print(
            f"supervisor: scaled IN to {len(self.charges)} ({reason}): "
            f"reaped {victim.name}", file=sys.stderr, flush=True,
        )

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — supervision must survive
                print(f"supervisor: tick failed: {e}", file=sys.stderr,
                      flush=True)
            self._stop.wait(self.probe_s)

    # -- rolling restart ------------------------------------------------------

    def rolling_restart(
        self, wait_up_s: float = 60.0, settle_s: float = 1.0,
    ) -> bool:
        """Restart every charge ONE AT A TIME with zero capacity dip
        beyond a single replica: SIGTERM the charge (a fleet worker's
        graceful-drain path — deregister, stop accepting, finish
        in-flight work, exit), let the ordinary supervision loop respawn
        it, and only move to the next charge once the replacement is
        **routable again** — up, answering ``/health`` (when probed),
        AND back on the registry roster (when one is configured): health
        alone is not enough, because SIGTERMing the next charge while
        this one is alive-but-unregistered would drain the roster dry
        and strand the gateway. ``settle_s`` then lets gateway roster
        refreshes pick the replacement up before the next roll. The
        fleet-roll primitive the chaos drill drives at throughput-gate
        load with zero dropped requests (docs/chaos.md). Returns False
        if any replacement failed to come back within ``wait_up_s``."""
        import signal as signal_mod
        import urllib.parse

        ok = True
        for c in list(self.charges):
            if not c.alive():
                continue  # the loop is already restarting it
            old_pid = c.proc.pid
            try:
                c.proc.send_signal(signal_mod.SIGTERM)
            except OSError:
                continue
            rostered_url = None
            old_boot = None
            if self.registry_url and c.health_url:
                u = urllib.parse.urlparse(c.health_url)
                rostered_url = f"http://{u.hostname}:{u.port}"
                # the dying worker's own entry must not satisfy the
                # wait below: remember its boot stamp so only a FRESH
                # registration (new process generation) counts — a
                # blackholed deregister leaves the stale entry on a
                # TTL-less registry, same port as the replacement
                old_boot = self._roster_boot(rostered_url)
            deadline = time.monotonic() + wait_up_s
            # wait out the drain + respawn (the supervision loop's
            # backoff applies — a clean roll restarts on the base delay)
            while time.monotonic() < deadline:
                if (
                    c.alive() and c.proc.pid != old_pid
                    and (
                        c.health_url is None
                        or _probe_health(
                            c.health_url, self.probe_timeout_s
                        ) == "ok"
                    )
                    and self._rostered(rostered_url, not_boot=old_boot)
                ):
                    break
                # every iteration costs a health probe + a registry
                # roster fetch: 0.25 s keeps the roll just as tight
                # without hammering the registry the roll depends on
                time.sleep(0.25)
            else:
                ok = False
                print(
                    f"supervisor: rolling restart of {c.name} did not "
                    f"come back within {wait_up_s:g}s",
                    file=sys.stderr, flush=True,
                )
            time.sleep(settle_s)
        return ok

    def _incumbent_fenced(self, c: WorkerCharge) -> bool:
        """Does a STRICT MAJORITY of the configured registries still
        advertise a live ``<gang_service>-gang`` roster entry for this
        charge's member name? True fences the respawn: the incumbent
        process is alive somewhere we cannot see (partition), and
        spawning a twin with the same gang identity is exactly the
        split-brain the quorum commit exists to prevent. Registries we
        cannot reach count as NOT claiming the incumbent alive — total
        blindness therefore never blocks recovery (majority unreachable
        → no majority view → respawn allowed, the CAS commit path is
        the backstop)."""
        if not c.gang_member or not self.registry_url:
            return False
        from mmlspark_tpu.serving.fleet import roster_entries_from_registry

        urls = self._registry_urls()
        gang = f"{c.gang_service or 'train'}-gang"
        claims = 0
        for url in urls:
            try:
                for e in roster_entries_from_registry(url, gang):
                    if e.get("host") == c.gang_member:
                        claims += 1
                        break
            except Exception:  # noqa: BLE001 — blind registry: no claim
                continue
        return claims >= len(urls) // 2 + 1

    def _roster_entries(self, url: str) -> list:
        """Roster entries whose bound OR forwarded port matches ``url``'s
        — never the forwarded-preferring URL the gateway routes to: a
        worker fronted by a port forward advertises
        forwarded_host:forwarded_port while the supervisor probes the
        local health endpoint, so an exact-URL comparison would never
        match. Supervised charges are local siblings with distinct fixed
        ports, so the port is their stable roster identity."""
        import urllib.parse

        from mmlspark_tpu.serving.fleet import roster_entries_from_registry

        port = urllib.parse.urlparse(url).port
        matched = []
        for e in roster_entries_from_registry(
            self.registry_url, self.service_name
        ):
            try:
                if int(e.get("port") or 0) == port or int(
                    e.get("forwarded_port") or 0
                ) == port:
                    matched.append(e)
            except (TypeError, ValueError):
                continue
        return matched

    def _roster_boot(self, url: str) -> Optional[float]:
        """The process-generation ``boot`` stamp of the roster entry
        matching ``url`` (None when absent or unstamped)."""
        try:
            for e in self._roster_entries(url):
                if e.get("boot") is not None:
                    return e["boot"]
        except Exception:  # noqa: BLE001 — no registry answered
            pass
        return None

    def _rostered(
        self, url: Optional[str], not_boot: Optional[float] = None
    ) -> bool:
        """Is the charge behind ``url`` advertised under this service on
        any registry? ``not_boot`` excludes a known-stale generation: an
        entry still carrying the SIGTERM'd process's boot stamp is the
        old worker's ghost (failed deregister + no TTL), not evidence
        the replacement is routable. True when there is nothing to
        check (no registry / no fixed port)."""
        if url is None:
            return True
        try:
            entries = self._roster_entries(url)
        except Exception:  # noqa: BLE001 — no registry answered: degrade
            return True
        for e in entries:
            if not_boot is not None and e.get("boot") == not_boot:
                continue
            return True
        return False


def charge_from_train_args(
    args_str: str, registry_url: str, index: int,
    python: Optional[str] = None,
) -> WorkerCharge:
    """One ``--train "<fleet train args>"`` CLI string -> a charge.

    Training charges make the supervisor the training plane's crash
    handler: a SIGKILLed trainer is re-spawned with its full original
    argv, and because ``fleet train`` auto-resumes from its ``--ckpt-dir``
    (checkpoint_dir doubles as resume_from), the restart comes back WARM
    at the latest round checkpoint and rejoins the gang at the next
    checkpoint boundary (parallel/elastic.py grow-back). Trainers run no
    HTTP ingress, so they are supervised on process liveness alone."""
    extra = shlex.split(args_str)
    argv = [
        python or sys.executable, "-m", "mmlspark_tpu.serving.fleet",
        "train", "--registry", registry_url, *extra,
    ]
    name = "trainer"
    if "--name" in extra:
        try:
            name = extra[extra.index("--name") + 1]
        except IndexError:
            pass
    service = "train"
    if "--service-name" in extra:
        try:
            service = extra[extra.index("--service-name") + 1]
        except IndexError:
            pass
    # gang identity makes the respawn FENCEABLE: while the majority
    # registry view still advertises this member alive under
    # <service>-gang, the supervisor must not seed a same-name twin
    gang_member = name if name != "trainer" else None
    return WorkerCharge(
        argv, name=f"train-{index}:{name}", health_url=None,
        gang_member=gang_member, gang_service=service,
    )


def charge_from_worker_args(
    args_str: str, registry_url: str, index: int,
    python: Optional[str] = None,
) -> WorkerCharge:
    """One ``--worker "<fleet worker args>"`` CLI string -> a charge.

    The charge's argv re-enters ``fleet worker`` with ``--registry``
    prepended (the supervisor's registry is authoritative); a fixed
    ``--port`` yields a ``/health`` probe URL, an ephemeral port leaves
    the charge on process-liveness supervision only."""
    extra = shlex.split(args_str)
    argv = [
        python or sys.executable, "-m", "mmlspark_tpu.serving.fleet",
        "worker", "--registry", registry_url, *extra,
    ]
    host, port = "127.0.0.1", None
    for flag in ("--advertise-host", "--host"):
        if flag in extra:
            v = extra[extra.index(flag) + 1]
            if v not in ("0.0.0.0", ""):
                host = v
            if flag == "--advertise-host":
                break
    if "--port" in extra:
        try:
            port = int(extra[extra.index("--port") + 1]) or None
        except (ValueError, IndexError):
            port = None
    health = f"http://{host}:{port}/health" if port else None
    return WorkerCharge(argv, name=f"worker-{index}:{port or 'ephemeral'}",
                        health_url=health)
