"""Low-latency model serving (Spark Serving analogue, SURVEY.md §2.7).

The reference serves models from Structured Streaming: per-executor HTTP
servers feed epoch-keyed request queues, replies are routed back by request
id on the same machine, and crashed partitions replay their queue history
(HTTPSourceV2.scala:457-675). This package rebuilds that design TPU-first:

- :class:`WorkerServer` — asyncio HTTP ingress with epoch-keyed queues,
  request-id routing table, history replay and commit pruning. A request
  never leaves its host: ingress -> batch -> TPU -> reply is machine-local,
  which is what makes the reference's sub-millisecond claim achievable.
- :class:`ServingQuery` — couples a server to a Transformer/function:
  *continuous* mode batches whatever is queued (up to ``max_batch_size`` /
  ``max_wait_ms``) and replies immediately; *micro-batch* mode advances
  epochs on a timer. Batches are padded to fixed shapes so the jitted model
  never recompiles (the load-bearing TPU detail).
- :class:`DriverRegistry` — the driver-side registration service workers
  report their ``ServiceInfo`` to (DriverServiceUtils analogue).
- :class:`ServingGateway` / :class:`BackendPool` — the distributed mode:
  N workers behind ONE endpoint with registry discovery, model-aware
  round-robin dispatch and cross-worker re-dispatch when a worker dies
  mid-request (DistributedHTTPSource analogue).
- :class:`ModelStore` / :class:`ModelDispatcher` (``modelstore/``) — the
  model-lifecycle layer: named+versioned models resident in device
  memory under a byte budget, background load+warmup, zero-downtime
  hot-swap, per-model queues with deadline-aware admission control, and
  a ``/models`` control plane (docs/modelstore.md).
- :class:`ArtifactStore` (``artifacts.py``) — the content-addressed
  artifact plane: hash-verified, resumable checkpoint/snapshot
  replication over the same worker ingress, so the fleet recovers
  without a shared filesystem (docs/artifacts.md).
- ``make_reply`` / ``request_to_row`` — ServingUDFs analogues.
"""

from mmlspark_tpu.serving.artifacts import ArtifactServer, ArtifactStore
from mmlspark_tpu.serving.server import CachedRequest, ServiceInfo, WorkerServer
from mmlspark_tpu.serving.query import (
    ServingQuery,
    SplitHandler,
    serve_transformer,
)
from mmlspark_tpu.serving.registry import DriverRegistry
from mmlspark_tpu.serving.distributed import Backend, BackendPool, ServingGateway
from mmlspark_tpu.serving.modelstore import (
    LoadedModel,
    ModelDispatcher,
    ModelStore,
)
from mmlspark_tpu.serving.udfs import make_reply, request_to_json, request_to_text

__all__ = [
    "ArtifactServer",
    "ArtifactStore",
    "WorkerServer",
    "CachedRequest",
    "ServiceInfo",
    "ServingQuery",
    "SplitHandler",
    "serve_transformer",
    "DriverRegistry",
    "Backend",
    "BackendPool",
    "ServingGateway",
    "LoadedModel",
    "ModelDispatcher",
    "ModelStore",
    "make_reply",
    "request_to_json",
    "request_to_text",
]
