"""DriverRegistry: the driver-side service-registration endpoint.

DriverServiceUtils analogue (HTTPSourceV2.scala:113-173): each host's
WorkerServer reports its ServiceInfo here once at startup; clients (or a
load balancer) query the roster. In a multi-host TPU deployment this runs
on the coordinator host next to ``jax.distributed``'s rendezvous.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.io.clients import send_request
from mmlspark_tpu.io.http_schema import HTTPRequestData
from mmlspark_tpu.serving.server import ServiceInfo

_M_REGISTRATIONS = obs.counter(
    "mmlspark_registry_registrations_total",
    "Worker (re)registrations accepted", labels=("service",),
)
_M_DEREGISTRATIONS = obs.counter(
    "mmlspark_registry_deregistrations_total",
    "Explicit roster removals (clean worker shutdown)", labels=("service",),
)
_M_EXPIRATIONS = obs.counter(
    "mmlspark_registry_expirations_total",
    "Roster entries dropped by TTL expiry", labels=("service",),
)
_M_ENTRIES = obs.gauge(
    "mmlspark_registry_entries_count",
    "Live roster entries per service", labels=("service",),
)
_M_RECONCILES = obs.counter(
    "mmlspark_registry_reconciles_total",
    "Anti-entropy passes pulled from peer registries",
)
_M_RECONCILED = obs.counter(
    "mmlspark_registry_reconciled_entries_total",
    "Roster entries adopted from peers (newer registration stamp)",
)
_M_CAS = obs.counter(
    "mmlspark_registry_cas_commits_total",
    "Generation CAS commits by outcome (committed/conflict/stale)",
    labels=("result",),
)


class DriverRegistry:
    def __init__(
        self, host: str = "127.0.0.1", port: int = 0,
        max_entries_per_service: int = 256,
        ttl_s: Optional[float] = None,
        peers: Optional[list] = None,
        reconcile_s: float = 5.0,
    ):
        """``max_entries_per_service`` bounds each roster: crash-looping
        workers on ephemeral ports register a NEW (host, port) every
        restart, and without a cap the dead entries accumulate without
        bound (oldest registrations are dropped first).

        ``ttl_s``: heartbeat expiry — an entry whose last (re)registration
        is older than this is dropped at the next read. Workers heartbeat
        by re-registering (serving/fleet.py), so a silently-dead host
        vanishes from the roster within one TTL instead of lingering until
        gateway failures evict it; set it to a few heartbeat periods.

        ``peers``: anti-entropy (ROADMAP 5c) — multi-registry fleets can
        disagree after a partition (clients multi-home their heartbeats,
        but a registry that missed beats holds a stale roster). Every
        ``reconcile_s`` this registry pulls each peer's roster and merges
        entries by NEWEST registration stamp; a worker that could only
        reach one registry during a partition becomes visible on all of
        them within one pass after heal. TTL still governs liveness, so
        a truly-dead entry adopted from a peer expires normally."""
        self.host = host
        self.max_entries_per_service = max_entries_per_service
        self.ttl_s = ttl_s
        self.peers = [p.rstrip("/") for p in (peers or [])]
        self.reconcile_s = reconcile_s
        self._services: dict[str, list] = {}
        # anti-entropy tombstones: explicit DELETEs recorded by (service,
        # host, port) -> delete time, so a reconcile pass cannot
        # resurrect a cleanly-deregistered worker from a peer that
        # missed the goodbye (a RE-registration after the delete carries
        # a newer stamp and wins over the tombstone)
        self._tombstones: dict = {}
        # committed generation records (split-brain fencing): keyed by the
        # record name (``<service>-gen``), each holds the HIGHEST
        # CAS-committed generation. Deliberately exempt from TTL expiry —
        # a committed epoch is durable coordination state (the fencing
        # token a late zombie must still collide with), not a liveness
        # claim.
        self._generations: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._stop_reconcile = threading.Event()
        self._reconcile_thread: Optional[threading.Thread] = None
        registry = self

        def expire_locked() -> None:
            if registry.ttl_s is None:
                return
            floor = time.time() - registry.ttl_s
            for svc in list(registry._services):
                kept = [
                    e for e in registry._services[svc]
                    if e.get("ts", 0.0) >= floor
                ]
                dropped = len(registry._services[svc]) - len(kept)
                if dropped:
                    _M_EXPIRATIONS.labels(service=svc).inc(dropped)
                    _M_ENTRIES.labels(service=svc).set(len(kept))
                if kept:
                    registry._services[svc] = kept
                else:
                    del registry._services[svc]

        self._expire_locked = expire_locked

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                if self.path.split("?", 1)[0] == "/debug/dump":
                    # on-demand flight-recorder dump, same contract as the
                    # WorkerServer endpoint (docs/observability.md)
                    from mmlspark_tpu.obs.flightrec import FLIGHT

                    dump_path = FLIGHT.dump("manual")
                    body = json.dumps({
                        "dumped": dump_path is not None,
                        "path": dump_path,
                        "records": len(FLIGHT),
                    }).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path.split("?", 1)[0] == "/generation/commit":
                    # compare-and-swap generation commit (split-brain
                    # fencing): the predecessor check rejects conflicting
                    # or stale commits instead of last-writer-wins
                    try:
                        n = int(self.headers.get("Content-Length") or 0)
                        body = json.loads(self.rfile.read(n))
                        name = body["name"]
                        gen = int(body["gen"])
                        expected = int(body.get("expected_gen", 0))
                        record = dict(body.get("record") or {})
                    except (ValueError, KeyError, TypeError):
                        code, out = 400, {
                            "committed": False, "reason": "bad-request",
                        }
                    else:
                        try:
                            code, out = registry.commit_cas(
                                name, gen, expected, record
                            )
                        except Exception as e:  # noqa: BLE001 — injected
                            # fault / internal error: refuse the commit
                            # (the client counts this as a missing ack,
                            # never as a committed generation)
                            code, out = 503, {
                                "committed": False, "reason": str(e),
                            }
                    payload = json.dumps(out).encode()
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    info = json.loads(self.rfile.read(n))
                    name = info["name"]
                except (ValueError, KeyError, TypeError):
                    self.send_response(400)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if name.endswith("-gen") and info.get("host") == "generation":
                    # plain roster POST of a generation record (heartbeat
                    # refresh / HA catch-up): monotone-guarded so a zombie
                    # re-advertising a superseded epoch is rejected, not
                    # last-writer-wins
                    code, out = registry._gen_refresh(name, info)
                    payload = json.dumps(out).encode()
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                with registry._lock:
                    # re-registration replaces the same (host, port) — a
                    # restarted worker must not linger twice, but several
                    # workers on one host (distinct ports) all coexist
                    entries = registry._services.setdefault(name, [])
                    key = (info.get("host"), info.get("port"))
                    entries[:] = [
                        e for e in entries
                        if (e.get("host"), e.get("port")) != key
                    ]
                    info["ts"] = time.time()  # consumers detect re-registration
                    entries.append(info)
                    if len(entries) > registry.max_entries_per_service:
                        entries.sort(key=lambda e: e.get("ts", 0.0))
                        del entries[: len(entries) - registry.max_entries_per_service]
                    _M_REGISTRATIONS.labels(service=name).inc()
                    _M_ENTRIES.labels(service=name).set(len(entries))
                body = b'{"registered": true}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_DELETE(self):
                """Explicit deregistration: a cleanly-stopping worker
                removes its roster entry instead of waiting out the TTL."""
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    info = json.loads(self.rfile.read(n))
                    name = info["name"]
                    key = (info.get("host"), info.get("port"))
                except (ValueError, KeyError, TypeError):
                    self.send_response(400)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                with registry._lock:
                    entries = registry._services.get(name, [])
                    before = len(entries)
                    entries[:] = [
                        e for e in entries
                        if (e.get("host"), e.get("port")) != key
                    ]
                    removed = before - len(entries)
                    registry._tombstones[(name,) + key] = time.time()
                    registry._prune_tombstones_locked()
                    if removed:
                        _M_DEREGISTRATIONS.labels(service=name).inc(removed)
                        _M_ENTRIES.labels(service=name).set(len(entries))
                    if not entries:
                        registry._services.pop(name, None)
                body = json.dumps({"deregistered": removed > 0}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path_only = self.path.split("?", 1)[0]
                if path_only == "/profile":
                    from mmlspark_tpu.obs import prof
                    # first scrape starts the sampler if the process
                    # booted without it
                    body = prof.ensure_started().profile_payload().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path_only == "/debug/threads":
                    from mmlspark_tpu.obs import prof
                    body = json.dumps(prof.threads_payload()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path_only == "/traces" or path_only.startswith("/traces/"):
                    tid = path_only[len("/traces/"):] or None
                    body = obs.render_traces(tid).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path.split("?", 1)[0] == "/metrics":
                    with registry._lock:
                        registry._expire_locked()  # scrape sees fresh TTLs
                    body = obs.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                with registry._lock:
                    registry._expire_locked()
                    body = json.dumps(registry._dump_locked()).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="driver-registry", daemon=True
        )
        self._thread.start()
        if self.peers:
            self._reconcile_thread = threading.Thread(
                target=self._reconcile_loop, name="registry-reconcile",
                daemon=True,
            )
            self._reconcile_thread.start()

    # -- generation CAS (split-brain fencing) ---------------------------------

    def _dump_locked(self) -> dict:
        """Roster dump plus the committed generation records, each
        rendered as a single ``host="generation"`` entry so existing
        readers (``GangMember.read_generation``) work unchanged."""
        out = {k: list(v) for k, v in self._services.items()}
        for name, g in self._generations.items():
            out[name] = [dict(g["record"])]
        return out

    def commit_cas(
        self, name: str, gen: int, expected_gen: int, record: dict,
    ) -> tuple:
        """Compare-and-swap commit of generation ``gen`` for record
        ``name`` (``<service>-gen``). Commits iff ``gen`` advances the
        currently committed generation AND the committer's predecessor
        claim is not stale (``expected_gen >= cur_gen``) — a commit
        racing against an already-won epoch (conflict) or rolling it
        back (stale) gets a 409 carrying the winner, never
        last-writer-wins. ``expected_gen > cur_gen`` is accepted: that
        is a member whose adopted predecessor this registry missed
        (registry catch-up), not a stale read. Forward jumps (2 -> 5)
        are allowed for the same reason."""
        faults.inject("registry.commit_cas", context={
            "name": name, "gen": gen, "expected_gen": expected_gen,
        })
        with self._lock:
            cur = self._generations.get(name)
            cur_gen = int(cur["gen"]) if cur else 0
            if gen <= cur_gen or expected_gen < cur_gen:
                result = "stale" if gen <= cur_gen else "conflict"
                _M_CAS.labels(result=result).inc()
                return 409, {
                    "committed": False, "reason": result,
                    "current_gen": cur_gen,
                    "current": dict(cur["record"]) if cur else None,
                }
            rec = dict(record)
            rec["name"] = name
            rec["host"] = "generation"
            rec["port"] = gen
            rec["ts"] = time.time()  # the REGISTRY stamps commit order
            self._generations[name] = {"gen": gen, "record": rec}
            _M_CAS.labels(result="committed").inc()
            _M_REGISTRATIONS.labels(service=name).inc()
            return 200, {"committed": True, "gen": gen}

    def _gen_refresh(self, name: str, info: dict) -> tuple:
        """Monotone rules for plain roster POSTs of generation records:
        accept a strictly newer generation (HA catch-up: a member
        multi-homing a record this registry missed), refresh the stamp on
        an exact re-post of the current one (heartbeat TTL refresh), and
        reject everything else — a lower gen, or the same gen with a
        different member set, is a zombie trying to roll the epoch back."""
        g = int(info.get("port", 0))
        with self._lock:
            cur = self._generations.get(name)
            cur_gen = int(cur["gen"]) if cur else 0
            if cur is None or g > cur_gen:
                rec = dict(info)
                rec["ts"] = time.time()
                self._generations[name] = {"gen": g, "record": rec}
                _M_REGISTRATIONS.labels(service=name).inc()
                return 200, {"registered": True}
            if g == cur_gen and info.get("members") == cur["record"].get(
                "members"
            ):
                cur["record"]["ts"] = time.time()
                _M_REGISTRATIONS.labels(service=name).inc()
                return 200, {"registered": True}
            _M_CAS.labels(result="stale").inc()
            return 409, {
                "registered": False, "reason": "stale-generation",
                "current_gen": cur_gen,
            }

    # -- anti-entropy ---------------------------------------------------------

    def _prune_tombstones_locked(self) -> None:
        """Tombstones older than any peer's plausible stale copy can be
        forgotten (a dead entry that old fails the TTL floor anyway);
        without a TTL keep them a few minutes. Called on every DELETE
        too, so a peer-less registry under restart churn cannot grow
        them without bound."""
        horizon = time.time() - (
            2 * self.ttl_s if self.ttl_s is not None else 300.0
        )
        for k in [k for k, t in self._tombstones.items() if t < horizon]:
            del self._tombstones[k]

    def _reconcile_loop(self) -> None:
        while not self._stop_reconcile.is_set():
            self._stop_reconcile.wait(self.reconcile_s)
            if self._stop_reconcile.is_set():
                return
            try:
                self.reconcile_now()
            except Exception:  # noqa: BLE001 — a dead peer must not kill us
                pass

    def reconcile_now(self) -> int:
        """One anti-entropy pass: pull every peer's roster, merge entries
        whose registration stamp is newer than the local copy's (or that
        the local roster lacks entirely). Returns entries adopted.
        Exposed separately so tests drive deterministic passes."""
        adopted = 0
        for peer in self.peers:
            try:
                resp = send_request(
                    HTTPRequestData(peer + "/", "GET"), timeout=5.0
                )
                if resp["status_code"] != 200:
                    continue
                remote = json.loads(resp["entity"])
            except Exception:  # noqa: BLE001 — partitioned/dead peer: skip
                continue
            floor = (
                time.time() - self.ttl_s if self.ttl_s is not None else None
            )
            with self._lock:
                self._prune_tombstones_locked()
                for svc, entries in remote.items():
                    if svc.endswith("-gen") and any(
                        e.get("host") == "generation" for e in entries
                    ):
                        # generation records merge to the HIGHEST
                        # committed gen (never by freshness): a registry
                        # restarted mid-commit must re-learn the winning
                        # epoch from its peers, not resurrect a
                        # superseded one. No TTL floor — committed epochs
                        # are durable fencing state.
                        for e in entries:
                            if e.get("host") != "generation":
                                continue
                            g = int(e.get("port", 0))
                            cur = self._generations.get(svc)
                            cur_gen = int(cur["gen"]) if cur else 0
                            if g > cur_gen:
                                self._generations[svc] = {
                                    "gen": g, "record": dict(e),
                                }
                                adopted += 1
                            elif cur is not None and g == cur_gen and float(
                                e.get("ts", 0.0)
                            ) > float(cur["record"].get("ts", 0.0)):
                                cur["record"] = dict(e)
                        continue
                    local = self._services.setdefault(svc, [])
                    by_key = {
                        (e.get("host"), e.get("port")): e for e in local
                    }
                    for e in entries:
                        ts = float(e.get("ts", 0.0))
                        if floor is not None and ts < floor:
                            continue  # would expire immediately anyway
                        key = (e.get("host"), e.get("port"))
                        dead = self._tombstones.get((svc,) + key)
                        if dead is not None and ts <= dead:
                            continue  # explicitly deregistered here —
                            # only a NEWER re-registration resurrects it
                        mine = by_key.get(key)
                        if mine is not None and float(
                            mine.get("ts", 0.0)
                        ) >= ts:
                            continue  # local copy is as new or newer
                        if mine is not None:
                            local.remove(mine)
                        local.append(dict(e))
                        by_key[key] = e
                        adopted += 1
                    if len(local) > self.max_entries_per_service:
                        local.sort(key=lambda e: e.get("ts", 0.0))
                        del local[: len(local) - self.max_entries_per_service]
                    if not local:
                        self._services.pop(svc, None)
                    else:
                        _M_ENTRIES.labels(service=svc).set(len(local))
        _M_RECONCILES.inc()
        if adopted:
            _M_RECONCILED.inc(adopted)
        return adopted

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def services(self, name: Optional[str] = None) -> list:
        with self._lock:
            self._expire_locked()
            if name is not None:
                return list(self._services.get(name, ()))
            return [s for infos in self._services.values() for s in infos]

    def live_hosts(self, name: str) -> list:
        """Host names currently on the (TTL-filtered) roster — the shape
        ``parallel.distributed.barrier(alive=...)`` consumes to name the
        missing host in its timeout diagnostics."""
        return sorted({e.get("host") for e in self.services(name)})

    def stop(self) -> None:
        self._stop_reconcile.set()
        if self._reconcile_thread is not None:
            self._reconcile_thread.join(5.0)
        self._httpd.shutdown()
        self._thread.join(5.0)
        # shutdown() only stops the serve loop; the listening socket must
        # be closed too or the port stays bound (restart-on-same-port)
        self._httpd.server_close()

    @staticmethod
    def register(
        registry_url: str, info: ServiceInfo, timeout: float = 10.0,
    ) -> bool:
        """Worker-side: report a ServiceInfo to the driver registry.

        ``timeout`` is the explicit socket budget for the POST —
        heartbeat loops pass a SHORT one (a blackholed registry must
        cost a beat, not park the heartbeat thread for the transport
        default; pinned by the chaos-proxy blackhole test)."""
        payload = {
            "name": info.name, "host": info.host,
            "port": info.port, "path": info.path,
        }
        if info.models is not None:
            # advertised model names ride the roster entry so the gateway
            # can route model-aware (serving/distributed.py)
            payload["models"] = list(info.models)
        if info.artifacts is not None:
            # content-addressed artifact advertisement (name@sha256):
            # consumers resolve fetch peers from the roster
            # (serving/artifacts.py registry_peers)
            payload["artifacts"] = list(info.artifacts)
        if info.boot is not None:
            # process-generation stamp: constant across heartbeats, new
            # per restart — the gateway's restart-detection signal (the
            # server-side "ts" is bumped by EVERY re-registration)
            payload["boot"] = info.boot
        resp = send_request(
            HTTPRequestData(
                registry_url, "POST", {"Content-Type": "application/json"},
                json.dumps(payload),
            ),
            timeout=timeout,
        )
        return resp["status_code"] == 200

    @staticmethod
    def deregister(
        registry_url: str, info: ServiceInfo, timeout: float = 5.0,
    ) -> bool:
        """Worker-side: remove this worker's roster entry (clean SIGTERM
        path — the TTL handles workers that die without saying goodbye).
        Short explicit ``timeout``: a blackholed registry must not hang
        a clean shutdown (the TTL covers the missed goodbye anyway)."""
        resp = send_request(
            HTTPRequestData(
                registry_url, "DELETE", {"Content-Type": "application/json"},
                json.dumps({
                    "name": info.name, "host": info.host, "port": info.port,
                }),
            ),
            timeout=timeout,
        )
        return resp["status_code"] == 200
