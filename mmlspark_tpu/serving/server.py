"""WorkerServer: machine-local HTTP ingress for model serving.

Rebuilds the continuous-serving server of the reference
(HTTPSourceV2.scala:457-675) without the JVM: an asyncio event loop on one
thread parses HTTP/1.1 (keep-alive) and enqueues :class:`CachedRequest`s
into epoch-keyed queues; a routing table maps request id -> connection so
replies from the dispatcher thread land on the originating socket
(replyTo, :516-533); uncommitted epochs are kept in ``history`` and can be
replayed after a crash (:470-487); ``commit`` prunes them (:535-547).

The ingress threads do no model work — batching and TPU dispatch live in
:class:`~mmlspark_tpu.serving.query.ServingQuery` — so request queuing
stays O(µs) and the end-to-end budget is spent on the XLA call.

Multi-reactor ingress (the throughput rewrite): ``num_reactors > 1``
runs N acceptor/reader event loops over ONE shared listening socket
(each reactor polls its own dup of the listen fd and races ``accept``;
the kernel hands every connection to exactly one loop). A connection
lives its whole life on the reactor that accepted it, so one slow
client — or a multi-MB ``/artifacts`` window draining inline — stalls
only its own reactor while the others keep taking requests. The inline
``/metrics``, ``/traces`` and ``/artifacts`` contracts (answered on the
reactor, never queued or counted) hold per reactor, and all reactors
feed the one shared request queue the dispatcher pops.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import socket as socket_mod
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.obs.registry import SIZE_BUCKETS

_REASONS = {200: "OK", 202: "Accepted", 400: "Bad Request", 404: "Not Found",
             408: "Request Timeout", 413: "Payload Too Large",
             429: "Too Many Requests", 431: "Request Header Fields Too Large",
             500: "Internal Server Error", 502: "Bad Gateway",
             503: "Service Unavailable", 504: "Gateway Timeout",
             507: "Insufficient Storage"}

# ingress telemetry (docs/observability.md). Families are module-level;
# each server pre-binds its label children in __init__ so the per-request
# hot path is one enabled-check + one locked add per instrument.
_M_ACCEPTED = obs.counter(
    "mmlspark_serving_requests_total",
    "Requests accepted into the ingress queue", labels=("server",),
)
_M_REJECTED = obs.counter(
    "mmlspark_serving_rejected_total",
    "Requests rejected at ingress (never queued)",
    labels=("server", "reason"),
)
_M_QDEPTH = obs.gauge(
    "mmlspark_serving_queue_depth_requests",
    "Requests currently queued awaiting dispatch", labels=("server",),
)
_M_QWAIT = obs.histogram(
    "mmlspark_serving_queue_wait_seconds",
    "Ingress-to-dispatch wait (arrival_ns to queue pop)", labels=("server",),
)
_M_BATCH = obs.histogram(
    "mmlspark_serving_batch_size_requests",
    "Requests per dispatched batch", labels=("server",),
    buckets=SIZE_BUCKETS,
)
_M_REPLAYED = obs.counter(
    "mmlspark_serving_replayed_total",
    "Requests re-enqueued by epoch replay recovery", labels=("server",),
)
_M_REACTOR_CONNS = obs.counter(
    "mmlspark_serving_reactor_connections_total",
    "Client connections accepted, per ingress reactor",
    labels=("server", "reactor"),
)
_M_INFLIGHT = obs.gauge(
    "mmlspark_serving_inflight_requests",
    "Accepted (non-probe) requests not yet replied to — the ingress "
    "routing table. MUST drain to zero after traffic stops; the "
    "invariant checker's nothing-lost gauge (chaos/invariants.py)",
    labels=("server",),
)
_METRICS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


@dataclass
class CachedRequest:
    id: str
    epoch: int
    method: str
    path: str
    headers: dict
    body: bytes
    arrival_ns: int = 0
    attempt: int = 0


@dataclass
class ServiceInfo:
    """What a worker reports to the driver registry
    (HTTPSourceV2.scala ServiceInfo :649-655)."""

    name: str
    host: str
    port: int
    path: str = "/"
    # public endpoint when an SSH reverse forward fronts the worker
    # (HTTPSourceV2.scala :657-665 forwarding options)
    forwarded_host: Optional[str] = None
    forwarded_port: Optional[int] = None
    # model names this worker serves (ModelStore-backed workers advertise
    # them so the gateway can route model-aware); None = unadvertised
    models: Optional[tuple] = None
    # content-addressed artifacts this process can serve over GET
    # /artifacts/<digest> ("name@sha256" strings, serving/artifacts.py);
    # consumers resolve fetch peers by scanning rosters for a digest
    artifacts: Optional[tuple] = None
    # process-generation stamp: set once when the server starts, constant
    # across heartbeat re-registrations, new on every restart. Roster
    # consumers use it to tell "new process" from "same process, fresh
    # heartbeat" — the registry's own ``ts`` is bumped by every beat, so
    # it cannot carry that distinction (the gateway resets a backend's
    # circuit breaker only on a new boot)
    boot: Optional[float] = None


class WorkerServer:
    """Epoch-queued HTTP ingress with reply routing and history replay."""

    # health probes may queue past max_queue (they are never bounced with
    # an inline answer — see _handle_conn), but only this many: beyond it
    # the connection closes unanswered, preserving the wedge signal
    # without letting a probing supervisor grow the queue forever
    _PROBE_OVERFLOW = 64

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        api_path: str = "/",
        name: str = "serving",
        max_queue: int = 100_000,
        forwarding: Optional[dict] = None,
        num_reactors: int = 1,
        header_deadline_s: Optional[float] = 30.0,
        max_header_bytes: int = 65536,
        max_body_bytes: int = 256 << 20,
        max_conns_per_reactor: int = 4096,
    ):
        """``forwarding``: kwargs for io.port_forwarding.PortForwarding
        (remote_host, remote_port, user, key_file, ...) — when given,
        ``start()`` opens an ssh -R tunnel exposing this worker publicly
        and reports the forwarded endpoint in ServiceInfo, like the
        reference's worker port forwarding (HTTPSourceV2.scala:657-665).

        ``num_reactors``: ingress event loops sharing the listening
        socket (module docstring). 1 keeps the classic single-loop
        ingress; fleet workers and gateways default higher.

        Hostile-client hardening (docs/chaos.md; the slowloris defenses
        the wire chaos harness forces):

        - ``header_deadline_s``: once a request's FIRST byte arrives,
          the full head must land within this budget or the connection
          is answered 408 and closed (an idle keep-alive connection
          between requests is never timed — idleness is not dripping).
          The body rides the same clock with a floor of 256 KiB/s so a
          legitimately large upload at normal speed always fits. None
          disables.
        - ``max_header_bytes`` / ``max_body_bytes``: 431 / 413 bounds —
          a hostile client cannot buffer-balloon a reactor.
        - ``max_conns_per_reactor``: connections beyond the cap are
          answered 503 and closed immediately, so one client opening
          sockets in a loop cannot pin a reactor's fd table. All four
          sheds are counted in ``mmlspark_serving_rejected_total`` and
          never touch the request queue."""
        self.name = name
        self.host = host
        self._forwarding_cfg = forwarding
        self._forwarding: Any = None
        self.api_path = api_path.rstrip("/") or "/"
        self._requested_port = port
        self.port: int = 0
        self.num_reactors = max(1, int(num_reactors or 1))
        # reactor index -> (loop, server); _loop stays reactor 0's loop
        self._reactors: list = []
        self._lsock: Optional[socket_mod.socket] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._threads: list = []
        self._started = threading.Event()
        self._boot_errors: list = []
        self._max_queue = max_queue
        # request ids: uuid4 costs ~14 µs in sandboxed processes (PR 2's
        # measurement) — at data-plane rates that is real budget, so ids
        # are one process-unique prefix + a shared atomic counter
        self._id_prefix = uuid.uuid4().hex[:12]
        self._id_counter = itertools.count()
        self._header_deadline_s = header_deadline_s
        self._max_header_bytes = int(max_header_bytes)
        self._max_body_bytes = int(max_body_bytes)
        self._max_conns_per_reactor = max(1, int(max_conns_per_reactor))
        # per-reactor live-connection counts (each loop touches only its
        # own key from its own thread)
        self._conn_counts: dict = {}

        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._epoch = 0
        self._queue: deque[CachedRequest] = deque()
        # epoch -> [CachedRequest] for replay-on-failure (historyQueues)
        self._history: dict[int, list[CachedRequest]] = {}
        # request id -> (writer, keep_alive) — pending replies (routingTable)
        self._routing: dict[str, tuple] = {}
        # open client connections -> owning reactor loop, so stop() can
        # close them on the right loop: a stopped worker whose sockets
        # linger half-open looks "slow" (send succeeds, reply never
        # comes) to keep-alive peers like the gateway, instead of
        # cleanly dead
        self._writers: dict = {}
        self.requests_seen = 0
        # optional AdmissionController (serving/admission.py): consulted
        # before a request is queued — the adaptive-concurrency shed path.
        # Attribute, not constructor arg: the query/dispatcher layer that
        # owns the controller attaches it (ServingQuery/ModelDispatcher)
        self.admission: Any = None
        # optional ArtifactStore (serving/artifacts.py): when attached,
        # GET /artifacts[/<digest>] is answered inline off this ingress
        # (ranged, never queued or counted — the /metrics contract), so
        # any worker doubles as a content-addressed artifact peer
        self.artifact_store: Any = None
        self._m_accepted = _M_ACCEPTED.labels(server=name)
        self._m_rej_full = _M_REJECTED.labels(server=name, reason="queue_full")
        self._m_rej_admission = _M_REJECTED.labels(
            server=name, reason="admission"
        )
        self._m_rej_404 = _M_REJECTED.labels(server=name, reason="not_found")
        self._m_rej_400 = _M_REJECTED.labels(server=name, reason="bad_request")
        self._m_rej_slow = _M_REJECTED.labels(
            server=name, reason="slow_client"
        )
        self._m_rej_hdr_big = _M_REJECTED.labels(
            server=name, reason="header_too_large"
        )
        self._m_rej_body_big = _M_REJECTED.labels(
            server=name, reason="body_too_large"
        )
        self._m_rej_conn_cap = _M_REJECTED.labels(
            server=name, reason="conn_cap"
        )
        self._m_inflight = _M_INFLIGHT.labels(server=name)
        self._inflight_accepted = 0
        self._m_qdepth = _M_QDEPTH.labels(server=name)
        self._m_qwait = _M_QWAIT.labels(server=name)
        self._m_batch = _M_BATCH.labels(server=name)
        self._m_replayed = _M_REPLAYED.labels(server=name)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> ServiceInfo:
        # bind + listen ONCE on the calling thread; every reactor then
        # polls its own dup of this fd and races accept() — the kernel
        # delivers each connection to exactly one reactor. Family
        # resolved per host (an IPv6 literal/host must keep working the
        # way asyncio.start_server(host=...) did). ONE family only —
        # unlike asyncio's bind-every-result — so on a dual-stack name
        # like "localhost" prefer the IPv4 entry: every roster address,
        # Backend and tool in this repo speaks IPv4 literals
        infos = socket_mod.getaddrinfo(
            self.host or None, self._requested_port,
            type=socket_mod.SOCK_STREAM, flags=socket_mod.AI_PASSIVE,
        )
        family, _, _, _, sockaddr = next(
            (i for i in infos if i[0] == socket_mod.AF_INET), infos[0]
        )
        lsock = socket_mod.socket(family, socket_mod.SOCK_STREAM)
        lsock.setsockopt(socket_mod.SOL_SOCKET, socket_mod.SO_REUSEADDR, 1)
        lsock.bind(sockaddr[:2] if family == socket_mod.AF_INET else sockaddr)
        lsock.listen(512)
        lsock.setblocking(False)
        self._lsock = lsock
        self.port = lsock.getsockname()[1]
        started = threading.Barrier(self.num_reactors + 1)
        for i in range(self.num_reactors):
            t = threading.Thread(
                target=self._run_reactor, args=(i, started),
                name=f"{self.name}-ingress-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        try:
            started.wait(10.0)
        except threading.BrokenBarrierError:
            # release what did come up: the bound listen socket and any
            # reactor that booted — a caller retrying start() on a fixed
            # port must not hit EADDRINUSE against our own leaked fd
            self.stop()
            raise RuntimeError("WorkerServer failed to start") from None
        if self._boot_errors:
            self.stop()
            raise RuntimeError(
                f"WorkerServer reactor failed to start: {self._boot_errors[0]}"
            )
        self._started.set()
        info = ServiceInfo(
            self.name, self.host, self.port, self.api_path,
            boot=time.time(),
        )
        if self._forwarding_cfg:
            from mmlspark_tpu.io.port_forwarding import PortForwarding

            try:
                cfg = dict(self._forwarding_cfg)
                cfg.setdefault("local_port", self.port)
                self._forwarding = PortForwarding(**cfg).start()
            except Exception:
                # a failed start() must not leave a live listener behind
                self.stop()
                raise
            info.forwarded_host = cfg.get("remote_host")
            info.forwarded_port = cfg.get("remote_port")
        return info

    def _run_reactor(self, idx: int, started: threading.Barrier) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        if idx == 0:
            self._loop = loop
        m_conns = _M_REACTOR_CONNS.labels(server=self.name, reactor=str(idx))

        async def handle(reader, writer) -> None:
            if m_conns._on:
                m_conns.inc()
            await self._handle_conn(reader, writer)

        async def boot() -> bool:
            try:
                # each reactor owns a dup of the shared listen fd: the
                # loops race accept(); asyncio absorbs the loser's
                # BlockingIOError, so the herd costs a wakeup, not a bug
                # the stream buffer must hold one full-size header line:
                # asyncio's default 64 KiB limit would make readline()
                # raise ValueError BEFORE the head_bytes/431 check sees
                # a configured max_header_bytes >= 64 KiB
                aserver = await asyncio.start_server(
                    handle, sock=self._lsock.dup(),
                    limit=self._max_header_bytes + 4096,
                )
                self._reactors.append((loop, aserver))
                ok = True
            except Exception as e:  # noqa: BLE001 — surfaced by start()
                self._boot_errors.append(e)
                ok = False
            started.wait(10.0)
            return ok

        booted = loop.run_until_complete(boot())
        try:
            # a reactor that failed to boot never registered in
            # _reactors, so stop() could not reach its loop — it must
            # not enter run_forever or the thread leaks alive
            if booted:
                loop.run_forever()
        finally:
            loop.close()

    def pause_accepting(self) -> None:
        """Stop taking NEW connections; established connections (and
        their in-flight requests) live on. The graceful-drain lifecycle's
        middle step: deregister -> pause_accepting -> wait
        :meth:`inflight` to zero -> :meth:`stop` (docs/chaos.md)."""
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass
        for loop, aserver in list(self._reactors):
            try:
                loop.call_soon_threadsafe(aserver.close)
            except RuntimeError:
                pass

    def stop(self) -> None:
        if self._forwarding is not None:
            self._forwarding.stop()
            self._forwarding = None
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass
        for loop, aserver in list(self._reactors):

            def _shutdown(loop=loop, aserver=aserver) -> None:
                aserver.close()
                # close this reactor's client connections BEFORE stopping
                # its loop: cancelled handler tasks never get to run their
                # cleanup once the loop stops, and a lingering ESTABLISHED
                # socket makes this worker look slow (send-then-silence)
                # rather than dead to keep-alive clients. transport.abort()
                # alone isn't enough — its close callbacks need loop
                # iterations that never come — so shut the raw socket down
                # synchronously (FIN goes out now; the fd stays valid for
                # the transport's own teardown)
                for w, owner in list(self._writers.items()):
                    if owner is not loop:
                        continue
                    try:
                        sock = w.transport.get_extra_info("socket")
                        w.transport.abort()
                        if sock is not None:
                            sock.shutdown(socket_mod.SHUT_RDWR)
                    except Exception:
                        pass
                    self._writers.pop(w, None)
                for task in asyncio.all_tasks(loop):
                    task.cancel()
                loop.stop()

            try:
                loop.call_soon_threadsafe(_shutdown)
            except RuntimeError:
                pass
        for t in self._threads:
            t.join(5.0)
        with self._not_empty:
            self._not_empty.notify_all()

    # -- ingress (loop thread) -----------------------------------------------

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        loop = asyncio.get_running_loop()
        key = id(loop)
        n_conns = self._conn_counts.get(key, 0)
        if n_conns >= self._max_conns_per_reactor:
            # per-reactor connection cap: a client opening sockets in a
            # loop must not pin this reactor's fd table — shed NOW,
            # before the connection costs anything
            self._m_rej_conn_cap.inc()
            try:
                self._write_response(
                    writer, 503, b"connection limit", False,
                    {"Retry-After": "1"},
                )
                await writer.drain()
            except Exception:
                pass
            try:
                writer.close()
            except Exception:
                pass
            return
        self._conn_counts[key] = n_conns + 1
        self._writers[writer] = loop
        watchdog = None  # the current request's slow-client timer
        try:
            while True:
                # line-framed head read (readline resolves from the
                # stream buffer without suspending once bytes are in),
                # decoded and split in one pass at the end. NOT
                # readuntil(b"\r\n\r\n"): a bare-LF client — which this
                # parser has always tolerated — would never match the
                # CRLF terminator and hang the connection open forever.
                #
                # Slowloris defense: the idle wait for a request's FIRST
                # byte is unbounded (keep-alive idleness is legitimate),
                # but once that byte lands the WHOLE request must land
                # within its deadline — a client dripping one header
                # byte per second is answered 408 and dropped, pinning
                # nothing. Enforced by ONE call_later watchdog per
                # request, not a wait_for per line: wait_for mints a
                # Task + timer per call, and at data-plane rates that
                # tax measured ~2x on echo throughput
                first = await reader.read(1)
                if not first:
                    return
                reading = [True]  # the watchdog's am-I-still-relevant flag
                if self._header_deadline_s:
                    def _expire(reading=reading, writer=writer):
                        if not reading[0]:
                            return
                        reading[0] = False  # mark expired for the reader
                        self._m_rej_slow.inc()
                        try:
                            self._write_response(
                                writer, 408, b"request read timed out",
                                False,
                            )
                            # flush the 408, FIN, and wake the pending
                            # readline/readexactly with EOF
                            writer.transport.close()
                        except Exception:
                            pass

                    watchdog = loop.call_later(
                        self._header_deadline_s, _expire
                    )
                raw_lines = []
                head_bytes = 0
                lead = first
                while True:
                    try:
                        h = await reader.readline()
                    except ValueError:
                        # a single line overran the stream buffer (sized
                        # max_header_bytes + margin above): same attack,
                        # same counted 431 as the head_bytes check below
                        if watchdog is not None:
                            watchdog.cancel()
                        self._m_rej_hdr_big.inc()
                        self._write_response(
                            writer, 431, b"header too large", False
                        )
                        return
                    if not reading[0]:
                        return  # the watchdog fired (already 408'd)
                    if lead is not None:
                        h = lead + h
                        lead = None
                    head_bytes += len(h)
                    if head_bytes > self._max_header_bytes:
                        if watchdog is not None:
                            watchdog.cancel()
                        self._m_rej_hdr_big.inc()
                        self._write_response(
                            writer, 431, b"header too large", False
                        )
                        return
                    if h in (b"\r\n", b"\n", b""):
                        break
                    raw_lines.append(h)
                if not raw_lines:
                    if watchdog is not None:
                        watchdog.cancel()
                    return
                try:
                    # split on the actual line framing only — NOT
                    # str.splitlines(), which also breaks on latin1
                    # control bytes (NEL \x85, \x0b, \x0c, ...) that a
                    # header value may legally carry
                    lines = [
                        ln.rstrip("\r")
                        for ln in b"".join(raw_lines).decode("latin1")
                        .split("\n")
                    ]
                    if lines and lines[-1] == "":
                        lines.pop()  # the head's trailing newline
                    try:
                        method, path, version = lines[0].split()
                    except ValueError:
                        return
                    headers: dict = {}
                    for h in lines[1:]:
                        k, _, v = h.partition(":")
                        headers[k.strip().lower()] = v.strip()
                    try:
                        n = int(headers.get("content-length") or 0)
                    except ValueError:
                        self._m_rej_400.inc()
                        self._write_response(
                            writer, 400, b"bad Content-Length", False
                        )
                        return
                    if n < 0:
                        self._m_rej_400.inc()
                        self._write_response(
                            writer, 400, b"bad Content-Length", False
                        )
                        return
                    if n > self._max_body_bytes:
                        self._m_rej_body_big.inc()
                        self._write_response(
                            writer, 413, b"body too large", False
                        )
                        return
                    if n and watchdog is not None:
                        # the body gets a fresh budget with a floor of
                        # 256 KiB/s, so a large-but-honest upload at
                        # normal speed always fits; a dripped body does
                        # not (the watchdog 408s and closes)
                        watchdog.cancel()
                        watchdog = loop.call_later(
                            max(
                                self._header_deadline_s,
                                n / (256 * 1024.0),
                            ),
                            _expire,
                        )
                    body = await reader.readexactly(n) if n else b""
                    if not reading[0]:
                        return  # the watchdog fired mid-body
                finally:
                    # the request is fully read (or abandoned): the
                    # slow-client clock stops here, before any model
                    # work or queue wait
                    if watchdog is not None:
                        watchdog.cancel()
                keep = headers.get("connection", "keep-alive").lower() != "close"
                prefix = self.api_path.rstrip("/")
                path_only = path.split("?", 1)[0]
                if path_only == "/metrics" and method == "GET":
                    # scrape endpoint: answered inline on the ingress
                    # thread (no model work), never queued or counted as
                    # an accepted request — scraping must not perturb the
                    # request metrics it reports
                    self._write_response(
                        writer, 200, obs.render().encode(), keep,
                        {"Content-Type": _METRICS_CONTENT_TYPE},
                    )
                    if not keep:
                        return
                    continue
                if method == "GET" and (
                    path_only == "/traces"
                    or path_only.startswith("/traces/")
                ):
                    # span-buffer scrape (trace assembly): same inline,
                    # never-counted contract as /metrics
                    tid = path_only[len("/traces/"):] or None
                    self._write_response(
                        writer, 200, obs.render_traces(tid).encode(), keep,
                        {"Content-Type": "application/json"},
                    )
                    if not keep:
                        return
                    continue
                if (
                    method in ("GET", "PUT")
                    and self.artifact_store is not None
                    and (
                        path_only == "/artifacts"
                        or path_only.startswith("/artifacts/")
                    )
                ):
                    # content-addressed artifact plane (serving/
                    # artifacts.py): advertisement + ranged blob reads +
                    # pushed replica windows (PUT), answered inline like
                    # /metrics. Blobs can be many MB — drain so
                    # backpressure lands here, not in an unbounded
                    # transport buffer
                    code, body_out, hdrs = self.artifact_store.handle_http(
                        path_only, headers, method=method, body=body
                    )
                    self._write_response(writer, code, body_out, keep, hdrs)
                    try:
                        await writer.drain()
                    except ConnectionError:
                        return
                    if not keep:
                        return
                    continue
                if path_only == "/profile" and method == "GET":
                    # sampling-profiler scrape: collapsed flame stacks,
                    # same inline never-counted contract as /metrics.
                    # First scrape starts the sampler, so even a process
                    # booted without it accumulates from the moment
                    # someone looks (obs/prof.py)
                    from mmlspark_tpu.obs import prof

                    body_out = prof.ensure_started().profile_payload()
                    self._write_response(
                        writer, 200, body_out.encode(), keep,
                        {"Content-Type": "text/plain; version=0.0.4"},
                    )
                    if not keep:
                        return
                    continue
                if path_only == "/debug/threads" and method == "GET":
                    # instant all-thread stack dump — what is this
                    # process standing in RIGHT NOW (no sampler needed)
                    from mmlspark_tpu.obs import prof

                    self._write_response(
                        writer, 200,
                        json.dumps(prof.threads_payload()).encode(), keep,
                        {"Content-Type": "application/json"},
                    )
                    if not keep:
                        return
                    continue
                if path_only == "/debug/dump" and method == "POST":
                    # on-demand flight-recorder dump (docs/observability.md)
                    from mmlspark_tpu.obs.flightrec import FLIGHT

                    dump_path = FLIGHT.dump("manual")
                    body_out = json.dumps({
                        "dumped": dump_path is not None,
                        "path": dump_path,
                        "records": len(FLIGHT),
                    }).encode()
                    self._write_response(
                        writer, 200, body_out, keep,
                        {"Content-Type": "application/json"},
                    )
                    if not keep:
                        return
                    continue
                on_path = (
                    not prefix
                    or path_only == prefix
                    or path_only.startswith(prefix + "/")
                )
                if not on_path:
                    self._m_rej_404.inc()
                    self._write_response(writer, 404, b"not found", keep)
                    if not keep:
                        return
                    continue
                # Health probes (supervisor, orchestrators, humans) are
                # monitoring, not traffic: never counted as accepted,
                # never admission-shed, never bounced by a full queue —
                # a saturated worker answering 429 to its supervisor
                # would be wedge-killed, shrinking the fleet under
                # overload. The probe still rides the QUEUE though: a
                # wedged dispatcher answers nothing, which is exactly
                # the signal wedge detection needs.
                bare = (
                    path_only[len(prefix):]
                    if prefix and path_only.startswith(prefix)
                    else path_only
                )
                is_probe = (
                    method == "GET" and bare in ("/health", "/healthz")
                )
                admission = self.admission if not is_probe else None
                if admission is not None:
                    # adaptive-concurrency shed (serving/admission.py):
                    # beyond the AIMD in-flight limit the request is
                    # answered 429 + Retry-After HERE, in microseconds,
                    # instead of joining a queue that already guarantees
                    # a blown deadline. Fault point admission.shed: a
                    # truthy payload forces the shed, delay_s stalls the
                    # admission path (chaos latency fault)
                    forced = None
                    try:
                        forced = faults.inject("admission.shed")
                    except Exception:  # noqa: BLE001 — injected error = shed
                        forced = True
                    if forced or not admission.try_acquire():
                        if forced:
                            admission.force_shed()
                        self._m_rej_admission.inc()
                        self._write_response(
                            writer, 429,
                            b'{"error": "over concurrency limit"}', keep,
                            admission.shed_headers(),
                        )
                        if not keep:
                            return
                        continue
                req = CachedRequest(
                    id=f"{self._id_prefix}-{next(self._id_counter)}",
                    epoch=self._epoch,
                    method=method,
                    path=path,
                    headers=headers,
                    body=body,
                    arrival_ns=time.perf_counter_ns(),
                )
                replied = asyncio.Event()
                with self._not_empty:
                    qlen = len(self._queue)
                    if not is_probe and qlen >= self._max_queue:
                        if admission is not None:
                            admission.release()  # the slot never queued
                        self._m_rej_full.inc()
                        self._write_response(writer, 503, b"queue full", keep)
                        if not keep:
                            return
                        continue
                    if is_probe and qlen >= self._max_queue + \
                            self._PROBE_OVERFLOW:
                        # probes ride the queue so a wedged dispatcher
                        # answers nothing (the wedge signal) — but they
                        # must not grow it unboundedly either. Past a
                        # small overflow allowance, close unanswered:
                        # any inline answer (even a 503) would read as
                        # "alive" to the supervisor and defeat wedge
                        # detection; a dropped connection reads as a
                        # failed probe, exactly the signal intended
                        return
                    self._routing[req.id] = (
                        writer, keep, replied, admission is not None, loop,
                        not is_probe,
                    )
                    self._queue.append(req)
                    self._history.setdefault(req.epoch, []).append(req)
                    self.requests_seen += 1
                    if not is_probe:
                        # the nothing-lost gauge: accepted, not yet
                        # replied — the invariant checker demands this
                        # drains to zero after traffic stops
                        self._inflight_accepted += 1
                        if self._m_accepted._on:
                            self._m_accepted.inc()
                            self._m_qdepth.set(len(self._queue))
                            self._m_inflight.set(self._inflight_accepted)
                    self._not_empty.notify()
                # wait for the reply before reading the next request on this
                # connection (no HTTP/1.1 pipelining needed)
                await replied.wait()
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError):
            return
        finally:
            if watchdog is not None:
                # a head/body read that RAISED (client reset mid-request)
                # skips the per-request cancel — without this, the timer
                # later fires on the dead connection and falsely counts
                # a slow_client shed for every abrupt disconnect
                watchdog.cancel()
            self._conn_counts[key] = max(0, self._conn_counts.get(key, 1) - 1)
            self._writers.pop(writer, None)
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    def _write_response(
        writer: asyncio.StreamWriter, code: int, body: bytes, keep: bool,
        headers: Optional[dict] = None,
    ) -> None:
        reason = _REASONS.get(code, "")
        head = [f"HTTP/1.1 {code} {reason}"]
        hdrs = {"Content-Length": str(len(body)),
                "Connection": "keep-alive" if keep else "close"}
        hdrs.update(headers or {})
        head += [f"{k}: {v}" for k, v in hdrs.items()]
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin1") + body)

    # -- consumption (dispatcher thread) --------------------------------------

    def get_next_batch(
        self, max_n: int, timeout_s: float = 0.1, min_n: int = 1,
        accumulate_s: float = 0.0,
    ) -> list:
        """Pop up to ``max_n`` queued requests; blocks up to ``timeout_s``
        for the first ``min_n`` (getNextRequest analogue, :588-623).
        ``accumulate_s > 0`` then waits that long for more arrivals (batch
        accumulation window) unless ``max_n`` is already reached."""
        deadline = time.monotonic() + timeout_s
        with self._not_empty:
            while len(self._queue) < min_n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._not_empty.wait(remaining)
            if self._queue and accumulate_s > 0:
                acc_deadline = time.monotonic() + accumulate_s
                while len(self._queue) < max_n:
                    remaining = acc_deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._not_empty.wait(remaining)
            out = []
            while self._queue and len(out) < max_n:
                out.append(self._queue.popleft())
            if out and self._m_qwait._on:
                # ingress->dispatch latency: arrival_ns was previously
                # recorded but never reported anywhere — the queue-wait
                # histogram is where it lands (docs/observability.md)
                now_ns = time.perf_counter_ns()
                for r in out:
                    self._m_qwait.observe((now_ns - r.arrival_ns) / 1e9)
                self._m_batch.observe(len(out))
                self._m_qdepth.set(len(self._queue))
            return out

    # -- replies (any thread) --------------------------------------------------

    def reply_to(
        self, request_id: str, body: bytes, code: int = 200,
        headers: Optional[dict] = None,
    ) -> bool:
        """Write the response on the originating connection. Idempotent:
        second reply for the same id is a no-op (routing-table removal,
        HTTPSourceV2.scala:516-527)."""
        with self._lock:
            entry = self._routing.pop(request_id, None)
            if entry is not None and entry[5]:
                self._inflight_accepted -= 1
                if self._m_inflight._on:
                    self._m_inflight.set(self._inflight_accepted)
        if entry is None:
            return False
        writer, keep, replied, admitted, loop, _counted = entry
        if admitted and self.admission is not None:
            # the admitted request is answered (any status): free its
            # concurrency slot exactly once (the routing-table pop above
            # is the idempotency guard). Probes were never admitted —
            # releasing for one would mint a phantom slot.
            self.admission.release()
        if loop is None:
            return False

        def _send() -> None:
            try:
                self._write_response(writer, code, body, keep, headers)
            except Exception:
                pass
            finally:
                replied.set()

        try:
            # the reply must be written by the reactor that owns the
            # connection — asyncio transports are not thread-safe
            loop.call_soon_threadsafe(_send)
        except RuntimeError:  # loop already closed (server stopped first)
            return False
        return True

    def reply_many(self, replies: list) -> int:
        """Batched :meth:`reply_to`: ``[(request_id, body, code,
        headers), ...]`` with ONE loop wakeup per owning reactor instead
        of one per request — on a 64-request dispatch batch that is 63
        fewer cross-thread signal syscalls on the reply path. Same
        idempotency (routing-table pop) and admission-release semantics
        per entry; returns how many replies were actually deliverable."""
        with self._lock:
            entries = [
                (entry, body, code, headers)
                for rid, body, code, headers in replies
                if (entry := self._routing.pop(rid, None)) is not None
            ]
            dec = sum(1 for entry, _b, _c, _h in entries if entry[5])
            if dec:
                self._inflight_accepted -= dec
                if self._m_inflight._on:
                    self._m_inflight.set(self._inflight_accepted)
        by_loop: dict = {}
        for (writer, keep, replied, admitted, loop, _counted), body, code, \
                hdrs in entries:
            if admitted and self.admission is not None:
                self.admission.release()
            if loop is not None:
                by_loop.setdefault(id(loop), (loop, []))[1].append(
                    (writer, keep, replied, body, code, hdrs)
                )
        for loop, items in by_loop.values():

            def _send_all(items=items) -> None:
                for writer, keep, replied, body, code, hdrs in items:
                    try:
                        self._write_response(writer, code, body, keep, hdrs)
                    except Exception:
                        pass
                    finally:
                        replied.set()

            try:
                loop.call_soon_threadsafe(_send_all)
            except RuntimeError:
                pass  # loop already closed (server stopped first)
        return len(entries)

    # -- epochs / recovery -----------------------------------------------------

    @property
    def epoch(self) -> int:
        return self._epoch

    def new_epoch(self) -> int:
        """Advance the epoch (micro-batch mode boundary)."""
        with self._lock:
            self._epoch += 1
            return self._epoch

    def commit(self, epoch: int) -> None:
        """Acknowledge an epoch fully replied: prune its replay history
        (:535-547)."""
        with self._lock:
            for e in [e for e in self._history if e <= epoch]:
                del self._history[e]

    def auto_commit(self) -> None:
        """Compact history down to the still-unanswered requests — the
        continuous-mode commit policy. (The old floor-epoch prune never
        fired in continuous mode: the epoch stays 0, one in-flight
        request kept it live, and epoch 0's list grew — and was
        re-scanned — per batch, forever. Compacting per epoch keeps
        replay semantics byte-identical: replay() only ever re-enqueues
        requests still awaiting a reply.)"""
        with self._lock:
            for e in list(self._history):
                reqs = [
                    r for r in self._history[e] if r.id in self._routing
                ]
                if reqs:
                    self._history[e] = reqs
                else:
                    del self._history[e]

    def replay(self, epoch: int) -> int:
        """Re-enqueue uncommitted requests of ``epoch`` whose replies never
        happened — the re-registration recovery path (:470-487). Returns the
        number of requests rehydrated."""
        with self._not_empty:
            reqs = [
                r for r in self._history.get(epoch, ())
                if r.id in self._routing  # unanswered only
            ]
            for r in reqs:
                r.attempt += 1
            # remove any still-queued instances to avoid double delivery
            queued = {r.id for r in reqs}
            self._queue = deque(r for r in self._queue if r.id not in queued)
            self._queue.extendleft(reversed(reqs))
            if reqs:
                self._m_replayed.inc(len(reqs))
                self._m_qdepth.set(len(self._queue))
            self._not_empty.notify()
            return len(reqs)

    def pending(self) -> int:
        with self._lock:
            return len(self._queue)

    def inflight(self) -> int:
        """Accepted requests not yet replied to (queued OR handed to a
        dispatcher) — the set a graceful drain must see through to zero."""
        with self._lock:
            return len(self._routing)

    def drain_inflight(self, timeout_s: float = 10.0) -> bool:
        """Wait until every accepted (non-probe) request has been
        replied to — queued, dispatched AND staged continuous batches
        all hold routing entries until their reply lands, so a True
        return means zero requests will be dropped by a subsequent
        :meth:`stop`. Supervisor health probes are excluded (a probing
        supervisor must not hold the drain open)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and self._inflight_accepted <= 0:
                    return True
            time.sleep(0.02)
        with self._lock:
            return not self._queue and self._inflight_accepted <= 0
