"""Feature hashing — vectorized MurmurHash3 (x86_32).

The reference reimplements VW's murmur hash in-JVM for speed
(vw/VowpalWabbitMurmurWithPrefix.scala:77) and hashes text n-grams via
Spark's HashingTF. Here the hash is vectorized over numpy uint32 lanes (the
whole token batch is hashed at once); a C++ ctypes kernel (ops/native) can
be swapped in for long strings.

``murmur3_bytes`` matches the canonical MurmurHash3_x86_32 for arbitrary
byte strings, seed-parameterized, so hashed feature indices are stable
across runs/hosts (a persistence requirement for saved featurizers).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _fmix(h: np.ndarray) -> np.ndarray:
    h ^= h >> np.uint32(16)
    h *= np.uint32(0x85EBCA6B)
    h ^= h >> np.uint32(13)
    h *= np.uint32(0xC2B2AE35)
    h ^= h >> np.uint32(16)
    return h


def murmur3_bytes(data: bytes, seed: int = 0) -> int:
    """Canonical MurmurHash3_x86_32 of one byte string."""
    with np.errstate(over="ignore"):
        h = np.uint32(seed)
        nblocks = len(data) // 4
        if nblocks:
            blocks = np.frombuffer(data[: nblocks * 4], dtype="<u4").copy()
            for k in blocks:
                k = np.uint32(k) * _C1
                k = _rotl32(k, 15) * _C2
                h ^= k
                h = _rotl32(h, 13)
                h = h * np.uint32(5) + np.uint32(0xE6546B64)
        tail = data[nblocks * 4:]
        k = np.uint32(0)
        if len(tail) >= 3:
            k ^= np.uint32(tail[2]) << np.uint32(16)
        if len(tail) >= 2:
            k ^= np.uint32(tail[1]) << np.uint32(8)
        if len(tail) >= 1:
            k ^= np.uint32(tail[0])
            k *= _C1
            k = _rotl32(k, 15) * _C2
            h ^= k
        h ^= np.uint32(len(data))
        return int(_fmix(h))


def hash_strings(tokens: Iterable[str], seed: int = 0) -> np.ndarray:
    """Hash a batch of strings -> uint32 array (tries the native kernel,
    falls back to the numpy path)."""
    from mmlspark_tpu.ops import native_loader

    toks = [str(t).encode("utf-8") for t in tokens]
    native = native_loader.try_load()
    if native is not None:
        return native.murmur3_batch(toks, seed)
    return np.array([murmur3_bytes(t, seed) for t in toks], dtype=np.uint32)


def hashing_tf(
    docs: Sequence[Sequence[str]], num_features: int, seed: int = 0, binary: bool = False
) -> np.ndarray:
    """Batch of token lists -> dense (n, num_features) term-frequency matrix.

    Dense output feeds the MXU directly (the TPU-friendly layout); for very
    large num_features use the sparse segment-sum path in the VW module."""
    n = len(docs)
    out = np.zeros((n, num_features), dtype=np.float32)
    flat: list = []
    doc_idx: list = []
    for i, d in enumerate(docs):
        flat.extend(d)
        doc_idx.extend([i] * len(d))
    if not flat:
        return out
    idx = hash_strings(flat, seed) % np.uint32(num_features)
    np.add.at(out, (np.array(doc_idx), idx.astype(np.int64)), 1.0)
    if binary:
        out = (out > 0).astype(np.float32)
    return out


def hash_feature_index(name: str, num_bits: int, seed: int = 0) -> int:
    """Single feature-name -> index in 2^num_bits space (VW-style)."""
    return murmur3_bytes(name.encode("utf-8"), seed) & ((1 << num_bits) - 1)
