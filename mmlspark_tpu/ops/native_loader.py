"""Native kernel loader — the NativeLoader analogue (core/env/NativeLoader.java:28-62).

The reference extracts platform .so files from jars and ``System.load``s
them; here the C++ sources live in ``ops/native`` and are compiled on first
use with g++ into the package build dir, then bound via ctypes. Absence of
a toolchain degrades gracefully to the numpy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_lib: Optional["_NativeLib"] = None
_failed = False

_SRC_DIR = os.path.join(os.path.dirname(__file__), "native")
_BUILD_DIR = os.path.join(_SRC_DIR, "build")


class _NativeLib:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        lib.mml_murmur3_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.mml_murmur3_batch.restype = None
        lib.mml_bin_features.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.mml_bin_features.restype = None
        lib.mml_parse_csv.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
        ]
        lib.mml_parse_csv.restype = ctypes.c_int64
        lib.mml_csv_dims.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.mml_csv_dims.restype = None

    def murmur3_batch(self, toks: list, seed: int) -> np.ndarray:
        n = len(toks)
        arr = (ctypes.c_char_p * n)(*toks)
        lens = np.array([len(t) for t in toks], dtype=np.int32)
        out = np.empty(n, dtype=np.uint32)
        self._lib.mml_murmur3_batch(
            ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n,
            seed,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
        return out

    def bin_features(self, x: np.ndarray, uppers: list) -> np.ndarray:
        """(n, d) float32 -> uint8 bins via per-feature edge search (threaded)."""
        x = np.ascontiguousarray(x, np.float32)
        n, d = x.shape
        offsets = np.zeros(d + 1, np.int64)
        for f, u in enumerate(uppers):
            offsets[f + 1] = offsets[f] + len(u)
        edges = (
            np.concatenate([np.asarray(u, np.float64) for u in uppers])
            if offsets[-1]
            else np.zeros(0, np.float64)
        )
        out = np.empty((n, d), np.uint8)
        self._lib.mml_bin_features(
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
            d,
            edges.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        return out

    def parse_csv(self, data: bytes) -> np.ndarray:
        """Numeric CSV bytes -> (rows, cols) float64 (bad fields = NaN)."""
        n_rows = ctypes.c_int64()
        n_cols = ctypes.c_int64()
        self._lib.mml_csv_dims(data, len(data), ctypes.byref(n_rows), ctypes.byref(n_cols))
        out = np.empty((n_rows.value, n_cols.value), np.float64)
        got = self._lib.mml_parse_csv(
            data,
            len(data),
            n_cols.value,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            n_rows.value,
        )
        return out[:got]


def _build() -> Optional[str]:
    so_path = os.path.join(_BUILD_DIR, "libmmltpu.so")
    src = os.path.join(_SRC_DIR, "mmltpu.cc")
    if os.path.exists(so_path) and os.path.getmtime(so_path) >= os.path.getmtime(src):
        return so_path
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread", src, "-o", so_path]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception:
        return None
    return so_path


def try_load() -> Optional[_NativeLib]:
    """Build+load the native kernel library, or None if unavailable."""
    global _lib, _failed
    if _lib is not None:
        return _lib
    if _failed or os.environ.get("MMLSPARK_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _lib is not None or _failed:
            return _lib
        so = _build()
        if so is None:
            _failed = True
            return None
        try:
            _lib = _NativeLib(ctypes.CDLL(so))
        except Exception:
            _failed = True
            return None
    return _lib
