"""Batched image ops on TPU — the OpenCV-engine replacement.

The reference routes images through OpenCV JNI calls one row at a time
(opencv/ImageTransformer.scala:41-110, image/UnrollImage.scala:40-51).
Here every op is a jittable function over a dense (N, H, W, C) batch so the
whole augment/preprocess pipeline fuses into one XLA program next to the
model — no host round-trips between stages.

Channel conventions: arrays are HWC; the reference's unroll emits CHW planes
in BGR order (UnrollImage.scala:40-51) and ``unroll`` reproduces that
bit-for-bit so featurizer vectors match.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def resize(images: jnp.ndarray, height: int, width: int, method: str = "linear") -> jnp.ndarray:
    """Batched resize (ResizeImage stage analogue). images: (N,H,W,C)."""
    n, h, w, c = images.shape
    if (h, w) == (height, width):
        # already at target size: a same-size jax.image.resize is NOT free
        # (XLA can't fold the gather/weighting away) — skip it entirely
        return images.astype(jnp.float32)
    out = jax.image.resize(
        images.astype(jnp.float32), (n, height, width, c), method=method
    )
    return out


def center_crop(images: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """CropImage stage analogue (centered)."""
    _, h, w, _ = images.shape
    top = max(0, (h - height) // 2)
    left = max(0, (w - width) // 2)
    return images[:, top: top + height, left: left + width, :]


def crop(images: jnp.ndarray, x: int, y: int, height: int, width: int) -> jnp.ndarray:
    return images[:, y: y + height, x: x + width, :]


def flip(images: jnp.ndarray, horizontal: bool = True) -> jnp.ndarray:
    """Flip stage analogue (flipCode >=0 => horizontal in OpenCV terms)."""
    axis = 2 if horizontal else 1
    return jnp.flip(images, axis=axis)


def bgr_to_rgb(images: jnp.ndarray) -> jnp.ndarray:
    return images[..., ::-1]


rgb_to_bgr = bgr_to_rgb


def to_grayscale(images: jnp.ndarray, bgr: bool = True) -> jnp.ndarray:
    """ColorFormat(GRAY) analogue; ITU-R BT.601 weights like OpenCV."""
    w = jnp.array([0.114, 0.587, 0.299] if bgr else [0.299, 0.587, 0.114])
    g = jnp.tensordot(images.astype(jnp.float32), w, axes=[[-1], [0]])
    return g[..., None]


def gaussian_kernel(ksize: int, sigma: float) -> jnp.ndarray:
    """1-D gaussian taps (GaussianKernel stage analogue)."""
    x = jnp.arange(ksize, dtype=jnp.float32) - (ksize - 1) / 2.0
    k = jnp.exp(-0.5 * (x / sigma) ** 2)
    return k / k.sum()


def gaussian_blur(images: jnp.ndarray, ksize: int, sigma: float) -> jnp.ndarray:
    """Blur stage analogue as a separable depthwise conv (two small convs
    instead of one kxk — HBM-friendlier, still lowered to the MXU)."""
    k = gaussian_kernel(ksize, sigma)
    x = images.astype(jnp.float32)
    n, h, w, c = x.shape
    x = jnp.moveaxis(x, -1, 1).reshape(n * c, 1, h, w)  # NCHW depthwise
    kv = k.reshape(1, 1, ksize, 1)
    kh = k.reshape(1, 1, 1, ksize)
    x = jax.lax.conv_general_dilated(x, kv, (1, 1), padding="SAME")
    x = jax.lax.conv_general_dilated(x, kh, (1, 1), padding="SAME")
    return jnp.moveaxis(x.reshape(n, c, h, w), 1, -1)


def threshold(images: jnp.ndarray, thresh: float, max_val: float = 255.0) -> jnp.ndarray:
    """Threshold stage analogue (THRESH_BINARY)."""
    return jnp.where(images > thresh, max_val, 0.0)


def unroll(images: jnp.ndarray, bgr: bool = True) -> jnp.ndarray:
    """Image batch -> flat vectors in the reference's layout: CHW plane
    order, BGR channel order (UnrollImage.scala:40-51). images: (N,H,W,C)
    assumed RGB unless ``bgr=False`` means already BGR."""
    x = images
    if bgr:
        x = x[..., ::-1]  # RGB -> BGR planes
    x = jnp.moveaxis(x, -1, 1)  # N,C,H,W
    return x.reshape(x.shape[0], -1)


def roll(vectors: jnp.ndarray, height: int, width: int, channels: int = 3, bgr: bool = True) -> jnp.ndarray:
    """Inverse of unroll (UnrollImage.roll analogue)."""
    x = vectors.reshape(-1, channels, height, width)
    x = jnp.moveaxis(x, 1, -1)
    if bgr:
        x = x[..., ::-1]
    return x


def normalize(
    images: jnp.ndarray,
    mean: Sequence[float] = (0.485, 0.456, 0.406),
    std: Sequence[float] = (0.229, 0.224, 0.225),
    scale: float = 1.0 / 255.0,
) -> jnp.ndarray:
    """Standard model-input normalization (scale then per-channel z-score)."""
    x = images.astype(jnp.float32) * scale
    return (x - jnp.asarray(mean)) / jnp.asarray(std)


def decode_image(data: bytes) -> Optional[np.ndarray]:
    """Host-side image decode (bytes -> HWC uint8 RGB array).

    The decode itself is host CPU work (like the reference's
    ImageIO/OpenCV decode, io/image/ImageUtils.scala); everything after it
    is device-side. Uses PIL if present, else a minimal PPM/BMP fallback."""
    try:
        import io as _io

        from PIL import Image  # type: ignore

        img = Image.open(_io.BytesIO(data)).convert("RGB")
        return np.asarray(img, dtype=np.uint8)
    except ImportError:
        return _decode_fallback(data)
    except Exception:
        return None


def _decode_fallback(data: bytes) -> Optional[np.ndarray]:
    # raw PPM (P6) decode — keeps tests/e2e hermetic if PIL is absent
    if data[:2] == b"P6":
        try:
            parts = data.split(maxsplit=4)
            w, h = int(parts[1]), int(parts[2])
            raw = parts[4][-w * h * 3:] if len(parts[4]) > w * h * 3 else parts[4]
            return np.frombuffer(raw, dtype=np.uint8, count=w * h * 3).reshape(h, w, 3)
        except Exception:
            return None
    return None
