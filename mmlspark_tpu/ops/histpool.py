"""Process pool for the host histogram kernel — feature-parallel bincount
and whole-level depthwise grow steps.

Why processes: the host lowering's ``np.bincount`` accumulation loop holds
the GIL (measured: an 8-thread pool runs 3.3x SLOWER than serial), so the
only way to use more than one core per histogram build is separate
interpreters. sklearn's HistGradientBoosting — the bench head-to-head —
parallelizes its Cython histogram over features with OpenMP; this pool is
the numpy equivalent: W forked workers, each owning a stripe of features.

Transport: a Connection-per-worker pickle protocol costs ~0.6 ms per
roundtrip in syscalls alone (32 sends/receives at 8 workers) — as much as
the histogram itself. The hot path instead uses ONE shared task pipe and
ONE shared reply pipe: the main process stages all task parameters in a
fixed control shm block and writes W single bytes (each byte IS the
stripe id, so racing readers cannot steal each other's stripe), workers
read 1 byte, execute, write 1 status byte back. Arena (re)mapping is
generation-stamped inside the control block, so remaps need no extra
roundtrip. Connections remain for startup handshake, error detail, and
the spawn start method (where inherited pipe fds are unavailable).

Life cycle: lazily forked on the first large-enough call (small calls and
therefore most unit-test fits never start it), torn down atexit (tokens
0xFF + closing the task pipe EOFs every blocked worker). Fork, not spawn:
children only ever touch numpy and pipes (glibc's atfork handlers keep
malloc consistent), there is no __main__ re-execution hazard for
unguarded user scripts, and startup is milliseconds. A fork gone wrong
can only hang a child — the handshake/task timeouts turn that into a
permanent, logged degrade to the serial kernel.
``MMLSPARK_TPU_HIST_WORKERS`` overrides the worker count; ``0``/``1``
disables; ``MMLSPARK_TPU_HIST_POOL_CTX=spawn`` switches the start method.

Determinism: each (slot, feature, bin) cell is accumulated by exactly one
worker with the same row-order ``np.bincount`` the serial kernel uses, so
pooled and serial results are bit-identical.
"""

from __future__ import annotations

import atexit
import logging
import os
import select
import time
from typing import Any, Optional

import numpy as np

log = logging.getLogger("mmlspark_tpu.histpool")

# below this many (row, feature) items the roundtrip costs more than the
# bincount itself — stay serial (also keeps unit-test fits pool-free)
MIN_POOL_ITEMS = int(os.environ.get("MMLSPARK_TPU_HIST_POOL_MIN", "120000"))

_ARENAS = ("bins", "stats", "base", "out", "out0", "out1", "cand")
_CTRL_BYTES = 4 << 20          # fixed-size control block (never regrown)
_TOK_QUIT = 255

# control-block layout (all offsets in bytes)
_OFF_HDR = 0                   # int64[16]: gen, op, n, d, ns, nb, cur,
#                                prev, has_pair, P, s_prev, width,
#                                has_scan, has_cat
_OFF_FLT = 256                 # float64[4]: min_data, msh, lam, l1
_OFF_NAMES = 512               # len(_ARENAS) x 64 utf-8 shm names
_OFF_VAR = 4096                # fm f32[d] | cat u8[d] | rs u8[P] | pl i64[P]
_OP_RUN, _OP_GROW = 1, 2


def feature_candidates(
    cube: np.ndarray,         # (S, fdim, nb, 3) histogram stripe
    fm: np.ndarray,           # (fdim,) feature mask
    min_data: float,
    msh: float,
    lam: float,
    l1: float,
    cat_f: "np.ndarray | None",   # (fdim,) bool, or None (no categoricals)
) -> tuple:
    """Per-feature best split per slot — the numpy mirror of
    ``treegrow.make_leaf_best`` restricted to a feature stripe. Returns
    (gain (fdim, S) f64, bin/prefix (fdim, S) int64); masked-out and
    invalid candidates carry -inf. Shared by the pool workers and the
    serial host grower so both paths run literally the same scan.

    Tie-break parity with the XLA grower's flat (d*B) argmax: the
    per-bin argmax here takes the LOWEST bin among equals, and the
    caller's cross-feature argmax takes the lowest feature — together
    exactly the flat first-max."""
    c = cube.astype(np.float64)
    hg, hh, hc = c[..., 0], c[..., 1], c[..., 2]
    cg = np.cumsum(hg, axis=2)
    ch = np.cumsum(hh, axis=2)
    cc = np.cumsum(hc, axis=2)
    G, H, C = cg[..., -1:], ch[..., -1:], cc[..., -1:]

    def gscore(Gv: np.ndarray, Hv: np.ndarray) -> np.ndarray:
        if l1:
            t = np.sign(Gv) * np.maximum(np.abs(Gv) - l1, 0.0)
        else:
            t = Gv
        with np.errstate(divide="ignore", invalid="ignore"):
            return t * t / (Hv + lam)

    with np.errstate(invalid="ignore"):
        gain = gscore(cg, ch) + gscore(G - cg, H - ch) - gscore(G, H)
    valid = (
        (fm > 0)[None, :, None]
        & (cc >= min_data) & ((C - cc) >= min_data)
        & (ch >= msh) & ((H - ch) >= msh)
    )
    gain = np.where(valid, gain, -np.inf)
    if cat_f is not None and cat_f.any():
        with np.errstate(divide="ignore", invalid="ignore"):
            ratio = np.where(hc > 0, hg / (hh + 1e-12), -np.inf)
        order = np.argsort(-ratio, axis=2, kind="stable")
        cgs = np.cumsum(np.take_along_axis(hg, order, 2), axis=2)
        chs = np.cumsum(np.take_along_axis(hh, order, 2), axis=2)
        ccs = np.cumsum(np.take_along_axis(hc, order, 2), axis=2)
        with np.errstate(invalid="ignore"):
            gain_cat = (
                gscore(cgs, chs) + gscore(G - cgs, H - chs) - gscore(G, H)
            )
        valid_cat = (
            (fm > 0)[None, :, None]
            & (ccs >= min_data) & ((C - ccs) >= min_data)
            & (chs >= msh) & ((H - chs) >= msh)
        )
        gain = np.where(
            cat_f[None, :, None],
            np.where(valid_cat, gain_cat, -np.inf),
            gain,
        )
    bb = np.argmax(gain, axis=2)                     # (S, fdim): lowest bin
    bg = np.take_along_axis(gain, bb[..., None], 2)[..., 0]
    return bg.T, bb.T.astype(np.int64)


def _workers_wanted() -> int:
    env = os.environ.get("MMLSPARK_TPU_HIST_WORKERS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            return 0
    cpus = os.cpu_count() or 1
    # leave headroom for the main process + XLA's own threads (16 workers
    # measured best on a 24-core box; 8 within 10%)
    return min(16, max(0, cpus - 8)) if cpus >= 16 else min(8, max(0, cpus - 2))


def _stripe_hist(
    out: np.ndarray, b: np.ndarray, base: np.ndarray, s: np.ndarray,
    f0: int, f1: int, ns: int, nb: int,
) -> None:
    """Weighted bincounts for features [f0, f1) into out[:, f0:f1].
    ``out`` is (ns, d, nb, 3); base offsets of ns*nb drop the row."""
    trash = ns * nb
    for f in range(f0, f1):
        idx = base + b[:, f]
        for j in range(3):
            out[:, f, :, j] = np.bincount(
                idx, weights=s[j], minlength=trash + 1
            )[:trash].reshape(ns, nb)


class _Ctrl:
    """Typed views over the fixed control shm block (main and workers
    parse the identical layout)."""

    def __init__(self, buf) -> None:
        self.hdr = np.frombuffer(buf, np.int64, 16, _OFF_HDR)
        self.flt = np.frombuffer(buf, np.float64, 4, _OFF_FLT)
        self.names = np.frombuffer(
            buf, "S64", len(_ARENAS), _OFF_NAMES
        )
        self.buf = buf

    def var_views(self, d: int, P: int) -> tuple:
        off = _OFF_VAR
        fm = np.frombuffer(self.buf, np.float32, d, off)
        off += 4 * d
        cat = np.frombuffer(self.buf, np.uint8, d, off)
        off += d
        off = (off + 7) & ~7
        rs = np.frombuffer(self.buf, np.uint8, max(P, 1), off)
        off += max(P, 1)
        off = (off + 7) & ~7
        pl = np.frombuffer(self.buf, np.int64, max(P, 1), off)
        return fm, cat, rs, pl


def _attach(name: str):
    """SharedMemory attach with resource-tracker registration suppressed:
    on this interpreter SharedMemory(name=) registers even for attaches
    (cpython bpo-39959) and concurrent worker register/unregister
    messages corrupt the shared tracker cache. The parent owns the
    segments and unlinks them."""
    from multiprocessing import resource_tracker as _rt
    from multiprocessing import shared_memory

    orig = _rt.register
    _rt.register = lambda *a, **k: None
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        _rt.register = orig


def _exec_task(ctrl: "_Ctrl", bufs: dict, stripe: int) -> None:
    """Execute the staged task for one feature stripe (shared by the
    token and Connection protocols)."""
    (_, op, n, d, ns, nb, cur, prev, has_pair, P, s_prev, width,
     has_scan, has_cat) = (int(v) for v in ctrl.hdr[:14])
    per = (d + width - 1) // width
    f0, f1 = stripe * per, min((stripe + 1) * per, d)
    if f0 >= f1:
        return
    bins_dt = np.uint8 if int(ctrl.hdr[14]) == 1 else np.int32
    b = np.frombuffer(bufs["bins"].buf, bins_dt, n * d).reshape(n, d)
    s = np.frombuffer(
        bufs["stats"].buf, np.float32, 3 * n
    ).reshape(3, n).astype(np.float64)
    base = np.frombuffer(bufs["base"].buf, np.int64, n)
    fm_v, cat_v, rs_v, pl_v = ctrl.var_views(d, P)
    scan = None
    if has_scan:
        min_data, msh, lam, l1 = (float(v) for v in ctrl.flt[:4])
        cat_f = cat_v.astype(bool) if has_cat else None
    if op == _OP_GROW:
        cube = np.frombuffer(
            bufs["out%d" % cur].buf, np.float32, ns * d * nb * 3
        ).reshape(ns, d, nb, 3)
        if not has_pair:
            _stripe_hist(cube, b, base, s, f0, f1, ns, nb)
        else:
            # histogram only the smaller sibling; derive the other from
            # the previous level's cube (ping-pong arena, state that
            # lives only within one tree)
            fdim = f1 - f0
            half = np.empty((P, fdim, nb, 3), np.float32)
            _stripe_hist(half, b[:, f0:f1], base, s, 0, fdim, P, nb)
            prev_cube = np.frombuffer(
                bufs["out%d" % prev].buf, np.float32, s_prev * d * nb * 3
            ).reshape(s_prev, d, nb, 3)
            parent_local = pl_v[:P]
            parents_ok = parent_local >= 0
            parents = prev_cube[np.maximum(parent_local, 0), f0:f1]
            other = parents - half
            if not parents_ok.all():
                bad = ~parents_ok
                other[bad] = 0.0
                half[bad] = 0.0
            rs = rs_v[:P].astype(bool)[:, None, None, None]
            cube[0:2 * P:2, f0:f1] = np.where(rs, other, half)
            cube[1:2 * P:2, f0:f1] = np.where(rs, half, other)
            if 2 * P < ns:
                cube[2 * P:, f0:f1] = 0.0
        target = cube
    else:
        target = np.frombuffer(
            bufs["out"].buf, np.float32, ns * d * nb * 3
        ).reshape(ns, d, nb, 3)
        _stripe_hist(target, b, base, s, f0, f1, ns, nb)
    if has_scan:
        cand = np.frombuffer(
            bufs["cand"].buf, np.float64, d * ns * 2
        ).reshape(d, ns, 2)
        bg, bb = feature_candidates(
            target[:, f0:f1], fm_v[f0:f1], min_data, msh, lam, l1,
            cat_f[f0:f1] if has_scan and cat_f is not None else None,
        )
        cand[f0:f1, :, 0] = bg
        cand[f0:f1, :, 1] = bb


def _worker_main(
    wid: int, conn: Any, ctrl_name: str, task_fd: int, reply_fd: int
) -> None:
    """Worker loop. Children run numpy + pipes only — never jax/XLA/BLAS
    — which is what makes the fork start safe."""
    bufs: dict = {}
    ctrl = None
    gen = -1
    try:
        ctrl_shm = _attach(ctrl_name)
        ctrl = _Ctrl(ctrl_shm.buf)
        conn.send("pong")                 # startup handshake
    except Exception as e:  # noqa: BLE001
        try:
            conn.send(("error", repr(e)))
        except Exception:  # noqa: BLE001
            return
        return
    # hybrid wait: after finishing a task, spin on a non-blocking read for
    # a short window (the next level's tokens arrive within ~2 ms during a
    # fit; a blocking read costs ~0.1-0.5 ms of wakeup latency per level),
    # then park in select() so an idle pool burns nothing
    import fcntl

    fcntl.fcntl(task_fd, fcntl.F_SETFL,
                fcntl.fcntl(task_fd, fcntl.F_GETFL) | os.O_NONBLOCK)
    spin_s = float(os.environ.get("MMLSPARK_TPU_HIST_POOL_SPIN_S", "0.05"))
    spin_until = 0.0
    while True:
        tok = b""
        try:
            while True:
                try:
                    tok = os.read(task_fd, 1)
                    break
                except BlockingIOError:
                    if time.monotonic() >= spin_until:
                        select.select([task_fd], [], [])
        except OSError:
            break
        if not tok or tok[0] == _TOK_QUIT:
            break
        status = b"\x00"
        try:
            if int(ctrl.hdr[0]) != gen:
                # generation bump: (re)attach arenas named in the block
                for key, raw in zip(_ARENAS, ctrl.names):
                    name = bytes(raw).rstrip(b"\x00").decode()
                    if not name:
                        continue
                    if key in bufs:
                        if bufs[key][1] == name:
                            continue
                        bufs[key][0].close()
                    shm = _attach(name)
                    bufs[key] = [shm, name]
                gen = int(ctrl.hdr[0])
            _exec_task(ctrl, {k: v[0] for k, v in bufs.items()}, tok[0])
        except Exception as e:  # noqa: BLE001 — report, main degrades
            status = b"\x01"
            try:
                conn.send(("error", repr(e)))
            except Exception:  # noqa: BLE001
                break
        try:
            os.write(reply_fd, status)
        except OSError:
            break
        spin_until = time.monotonic() + spin_s
    for v in bufs.values():
        try:
            v[0].close()
        except Exception:  # noqa: BLE001
            pass


class _HistPool:
    def __init__(self) -> None:
        self.procs: list = []
        self.conns: list = []
        self.shms: dict = {}
        self.caps: dict = {k: 0 for k in _ARENAS}
        self.dead = False
        self.width = 0
        self.toks: dict = {}
        self.ctrl_shm = None
        self.ctrl: Optional[_Ctrl] = None
        self.gen = 0
        self.task_w = self.reply_r = -1
        self._extra_fds: list = []

    # -- lifecycle ---------------------------------------------------------

    def _start(self) -> bool:
        import multiprocessing as mp
        from multiprocessing import shared_memory

        w = _workers_wanted()
        if w <= 1:
            return False
        ctx = mp.get_context(
            os.environ.get("MMLSPARK_TPU_HIST_POOL_CTX", "fork")
        )
        if ctx.get_start_method() != "fork":
            # the token pipes rely on fd inheritance; without fork there
            # is no cheap transport, and the serial kernel is already
            # within ~2x of a chatty pool — stay serial
            log.info("hist pool requires the fork start method; serial")
            return False
        try:
            import warnings

            self.ctrl_shm = shared_memory.SharedMemory(
                create=True, size=_CTRL_BYTES
            )
            self.ctrl = _Ctrl(self.ctrl_shm.buf)
            self.ctrl.hdr[0] = 0
            task_r, self.task_w = os.pipe()
            self.reply_r, reply_w = os.pipe()
            self._extra_fds = [task_r, reply_w]
            for i in range(w):
                ours, theirs = ctx.Pipe(duplex=True)
                p = ctx.Process(
                    target=_worker_main,
                    args=(i, theirs, self.ctrl_shm.name, task_r, reply_w),
                    daemon=True,
                )
                with warnings.catch_warnings():
                    # the interpreter warns that fork + threads can
                    # deadlock; the children run numpy + pipes only and
                    # the handshake/task timeouts degrade a wedged child
                    # to the serial kernel
                    warnings.simplefilter("ignore", RuntimeWarning)
                    warnings.simplefilter("ignore", DeprecationWarning)
                    p.start()
                theirs.close()
                self.conns.append(ours)
                self.procs.append(p)
            deadline = time.monotonic() + 30.0
            for conn in self.conns:
                remaining = max(deadline - time.monotonic(), 0.0)
                if not conn.poll(remaining) or conn.recv() != "pong":
                    raise RuntimeError("worker failed startup handshake")
        except Exception as e:  # noqa: BLE001
            log.warning("hist pool start failed (%s); staying serial", e)
            self._shutdown()
            return False
        self.width = w
        atexit.register(self._shutdown)
        return True

    def _shutdown(self) -> None:
        if self.task_w >= 0:
            try:
                os.write(self.task_w, bytes([_TOK_QUIT]) * len(self.procs))
            except OSError:
                pass
            try:
                os.close(self.task_w)   # EOF wakes any blocked reader
            except OSError:
                pass
            self.task_w = -1
        for p in self.procs:
            try:
                p.join(timeout=1.0)
                if p.is_alive():
                    p.terminate()
            except Exception:  # noqa: BLE001
                pass
        for fd in [self.reply_r] + self._extra_fds:
            if fd >= 0:
                try:
                    os.close(fd)
                except OSError:
                    pass
        self.reply_r = -1
        self._extra_fds = []
        for conn in self.conns:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass
        self.ctrl = None  # drop the typed views before closing the block
        for s in list(self.shms.values()) + (
            [self.ctrl_shm] if self.ctrl_shm is not None else []
        ):
            # close and unlink separately: a caller still holding a view
            # of an arena makes close() raise BufferError, but the
            # segment must be unlinked (and tracker-unregistered) anyway
            try:
                s.close()
            except Exception:  # noqa: BLE001
                pass
            try:
                s.unlink()
            except Exception:  # noqa: BLE001
                pass
            # a caller-held view keeps the mmap exported; silence the
            # interpreter-exit __del__ retry (the segment is already
            # unlinked, nothing leaks)
            s.close = lambda: None
        self.procs, self.conns, self.shms = [], [], {}
        self.ctrl_shm = None
        self.caps = {k: 0 for k in _ARENAS}
        self.toks = {}
        self.dead = True

    # -- arenas ------------------------------------------------------------

    def _ensure_arenas(self, need: dict) -> None:
        """Grow shared buffers to at least the needed byte sizes; workers
        re-attach lazily via the generation stamp in the control block."""
        from multiprocessing import shared_memory

        grow = {k: v for k, v in need.items() if v > self.caps[k]}
        if not grow:
            return
        for key, size in grow.items():
            size = max(size * 2, 1 << 20)  # 2x headroom, 1 MiB floor
            old = self.shms.get(key)
            self.shms[key] = shared_memory.SharedMemory(create=True, size=size)
            self.caps[key] = size
            self.toks.pop(key, None)  # fresh arena: cached content gone
            if old is not None:
                old.close()
                old.unlink()
        for i, key in enumerate(_ARENAS):
            shm = self.shms.get(key)
            self.ctrl.names[i] = (shm.name if shm else "").encode()
        self.gen += 1

    def _write_arena(
        self, key: str, dtype, data: np.ndarray, token: Any
    ) -> None:
        """Copy ``data`` into the named arena unless the caller's token
        says the arena already holds it (the host grower reuses bins and
        stats across a tree's levels — tokens are object ids the CALLER
        keeps alive for the duration, so they cannot be recycled)."""
        tok = None
        if token is not None:
            tok = (token, data.shape, data.dtype.str)
            if self.toks.get(key) == tok:
                return
        flat = np.frombuffer(self.shms[key].buf, dtype, data.size)
        flat[:] = data.reshape(-1)
        self.toks[key] = tok

    # -- task dispatch -----------------------------------------------------

    def _dispatch(self, d: int) -> bool:
        """Wake one worker per feature stripe and collect status bytes."""
        width = int(self.ctrl.hdr[11])
        try:
            os.write(self.task_w, bytes(range(width)))
        except OSError:
            return False
        got = 0
        errs = 0
        deadline = time.monotonic() + 60.0
        while got < width:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            r, _, _ = select.select([self.reply_r], [], [], remaining)
            if not r:
                return False
            chunk = os.read(self.reply_r, width - got)
            if not chunk:
                return False
            got += len(chunk)
            errs += sum(1 for c in chunk if c != 0)
        if errs:
            for conn in self.conns:
                while conn.poll(0):
                    msg = conn.recv()
                    if isinstance(msg, tuple):
                        log.warning("hist pool worker failed: %s", msg[1])
            return False
        return True

    def _stage(
        self, op: int, b: np.ndarray, base: np.ndarray, s3: np.ndarray,
        ns: int, nb: int, scan: Optional[tuple],
        cur: int, prev: int, pair: Optional[tuple],
        bins_token: Any, stats_token: Any,
    ):
        n, d = b.shape
        if self.dead or n * d < MIN_POOL_ITEMS:
            return None
        if not self.procs and not self._start():
            self.dead = True
            return None
        P = len(pair[0]) if pair is not None else 0
        if _OFF_VAR + 4 * d + d + 16 + 9 * max(P, 1) > _CTRL_BYTES:
            return None  # shapes beyond the fixed control block
        need = {
            "bins": b.nbytes,
            "stats": s3.nbytes,
            "base": base.nbytes,
            ("out%d" % cur if op == _OP_GROW else "out"): ns * d * nb * 3 * 4,
        }
        if scan is not None:
            need["cand"] = d * ns * 2 * 8
        self._ensure_arenas(need)
        self._write_arena("bins", b.dtype, b, bins_token)
        self._write_arena("stats", np.float32, s3, stats_token)
        self._write_arena("base", np.int64, base, None)
        width = min(self.width, d)
        hdr = self.ctrl.hdr
        hdr[1:15] = 0
        hdr[1] = op
        hdr[14] = b.dtype.itemsize
        hdr[2], hdr[3], hdr[4], hdr[5] = n, d, ns, nb
        hdr[6], hdr[7] = cur, prev
        hdr[11] = width
        fm_v, cat_v, rs_v, pl_v = self.ctrl.var_views(d, P)
        if scan is not None:
            fm, cat_f, min_data, msh, lam, l1 = scan
            self.ctrl.flt[:4] = (min_data, msh, lam, l1)
            fm_v[:] = np.asarray(fm, np.float32)
            hdr[12] = 1
            if cat_f is not None:
                cat_v[:] = np.asarray(cat_f, np.uint8)
                hdr[13] = 1
        if pair is not None:
            right_small, parent_local, s_prev = pair
            hdr[8], hdr[9], hdr[10] = 1, P, s_prev
            rs_v[:P] = np.asarray(right_small, np.uint8)
            pl_v[:P] = parent_local
        # publish the generation last: workers reading a stale gen would
        # re-attach before touching the arenas
        hdr[0] = self.gen
        return self._dispatch(d)

    # -- public ops --------------------------------------------------------

    def bincounts(
        self, b: np.ndarray, base: np.ndarray, s3: np.ndarray,
        ns: int, nb: int, scan: Optional[tuple] = None,
        bins_token: Any = None, stats_token: Any = None,
    ) -> "Optional[tuple]":
        """Pooled equivalent of the serial per-feature bincount loop.

        ``b``: (n, d) int32 bins (in range); ``base``: (n,) int64 plane
        offsets (a trash offset of ns*nb drops the row); ``s3``: (3, n)
        f32 stats. ``scan``: optional (fm, cat_f, min_data, msh, lam,
        l1) — the workers also run :func:`feature_candidates` on their
        stripe. Returns (cube (ns, d, nb, 3) f32, cand (d, ns, 2) f64 or
        None), both aliasing the shared arenas — valid until the NEXT
        call — or None when the pool should not / could not run (caller
        falls back to the serial loop)."""
        n, d = b.shape
        try:
            ok = self._stage(
                _OP_RUN, b, base, s3, ns, nb, scan, 0, 0, None,
                bins_token, stats_token,
            )
        except Exception as e:  # noqa: BLE001
            log.warning("hist pool degraded to serial: %s", e)
            self._shutdown()
            return None
        if ok is None:
            return None
        if not ok:
            log.warning("hist pool task failed; degrading to serial")
            self._shutdown()
            return None
        cube = np.frombuffer(
            self.shms["out"].buf, np.float32, ns * d * nb * 3
        ).reshape(ns, d, nb, 3)
        cand = None
        if scan is not None:
            cand = np.frombuffer(
                self.shms["cand"].buf, np.float64, d * ns * 2
            ).reshape(d, ns, 2)
        return cube, cand

    def grow_level(
        self, b: np.ndarray, base: np.ndarray, s3: np.ndarray,
        S: int, nb: int, scan: tuple, pair: Optional[tuple], cur: int,
        bins_token: Any = None, stats_token: Any = None,
    ) -> "Optional[tuple]":
        """One depthwise level fully in the workers: stripe histograms
        (of the smaller sibling only when ``pair`` is given), sibling
        derivation against the previous level's cube (ping-pong arenas
        out0/out1 — state that lives only WITHIN one tree; every tree
        opens with a full pair=None build), and the split scan.

        ``pair``: (right_small (P,) bool, parent_local (P,) i64 with -1
        for dead pairs, S_prev). Returns (cube (S, d, nb, 3) f32 view,
        gains (d, S) f64, bins (d, S) i64) aliasing the arenas, or None
        to run serial."""
        n, d = b.shape
        try:
            ok = self._stage(
                _OP_GROW, b, base, s3, S, nb, scan, cur, 1 - cur, pair,
                bins_token, stats_token,
            )
        except Exception as e:  # noqa: BLE001
            log.warning("hist pool degraded to serial: %s", e)
            self._shutdown()
            return None
        if ok is None:
            return None
        if not ok:
            log.warning("hist pool task failed; degrading to serial")
            self._shutdown()
            return None
        cube = np.frombuffer(
            self.shms["out%d" % cur].buf, np.float32, S * d * nb * 3
        ).reshape(S, d, nb, 3)
        cand = np.frombuffer(
            self.shms["cand"].buf, np.float64, d * S * 2
        ).reshape(d, S, 2)
        return cube, cand[:, :, 0], cand[:, :, 1].astype(np.int64)


_POOL: Optional[_HistPool] = None


def get_pool() -> _HistPool:
    global _POOL
    if _POOL is None:
        _POOL = _HistPool()
    return _POOL


def pooled_bincounts(
    b: np.ndarray, base: np.ndarray, s3: np.ndarray, ns: int, nb: int
) -> Optional[np.ndarray]:
    """Entry point used by the host histogram kernel. None = run serial.
    The returned cube aliases the pool's shared arena — consume (or
    copy) it before the next pooled call."""
    res = get_pool().bincounts(b, base, s3, ns, nb)
    return None if res is None else res[0]
