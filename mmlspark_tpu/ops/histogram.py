"""Histogram plane builder — the GBDT hot op.

LightGBM's C++ trainer spends its time building per-leaf gradient
histograms. Here the op is ``plane_histogram(bins, stats, mask)``:
scatter the (g, h, count) stats of the masked rows into a
``(d * NUM_BINS, 3)`` plane.

Two lowerings:

- **Pallas (TPU, single chip)**: grid over (feature-blocks, row-chunks);
  each step builds a bf16 one-hot (DF, B, rows) block in VMEM (rows on the
  128-lane dim) and accumulates ``one_hot @ stats_hi/lo`` into the output
  block — the scatter becomes an MXU matmul, which is how TPUs like their
  histograms. Stats are split hi+lo bf16 so two native MXU passes recover
  f32-grade sums. Rows stream chunk by chunk so VMEM stays bounded.
- **shard_map + Pallas (TPU, sharded meshes)**: when the caller passes the
  mesh whose ``data`` axis shards the rows, the kernel runs PER SHARD under
  ``jax.shard_map`` and the (d*B, 3) planes are combined with an explicit
  ``psum`` riding ICI — exactly LightGBM's data_parallel per-iteration
  histogram allreduce (lightgbm/TrainUtils.scala:496-512 NetworkInit +
  socket rings), with the MXU kernel intact on every chip.
- **XLA scatter-add (sharded meshes without a mesh handle)**: GSPMD
  partitions the scatter across the mesh and inserts the ICI allreduce
  automatically. When the caller passes the mesh and Pallas is off, the
  same scatter runs PER SHARD under ``shard_map`` with an explicit
  ``psum`` instead — the allreduce stays visible (and measurable) in the
  program rather than implied by the partitioner.
- **Host bincount (CPU)**: XLA:CPU lowers scatter-add to an
  element-by-element update loop (~70 ns/update measured — the reason
  BENCH r06 *lost* to single-core sklearn by 4-35x); ``np.bincount``
  does the identical accumulation at ~2 ns/update and, because the
  kernel sees the row mask/slot vector instead of pre-zeroed stats, it
  compacts to the selected rows first — per-split cost becomes
  proportional to the CHILD size, LightGBM's DataPartition cost model
  without the permutation. Runs as a ``pure_callback`` inside the jitted
  (and scan-fused) growers; on CPU the "device" is the host, so
  residency is preserved. Trade-off: callback programs are excluded
  from jax's persistent compilation cache, so CPU training programs
  recompile once per process (the ~10x runtime win repays one compile
  within a single 20-iteration fit).

Selection is automatic (see :func:`use_pallas` / :func:`use_host_hist` /
:func:`hist_lowering`) and overridable with ``MMLSPARK_TPU_PALLAS=0|1``
and ``MMLSPARK_TPU_HIST_HOST=0|1``.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.parallel.compat import shard_map

# The host-kernel pure_callbacks deadlock against XLA:CPU's async
# dispatch: the callback thread's operand conversion (np.asarray on a
# jax.Array) waits on a d2h materialization that is queued behind the
# very computation suspended in the callback. The wedged pair was
# captured by the stall-forensics watchdog — MainThread in
# jax array._value under fit(), callback thread in hostgrow.py's
# np.asarray(bins) under pure_callback_impl; see docs/gbdt-training.md
# "Known issues". The flag is read ONCE at CPU client creation, so this
# import-time update only protects processes that import this module
# before their first dispatch — embedding code that runs jax first must
# set it itself (tests/conftest.py and bench.py do). No effect on TPU.
if os.environ.get("MMLSPARK_TPU_CPU_ASYNC_DISPATCH") != "1":
    try:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    except Exception:  # pragma: no cover - option absent in this jax
        pass

NUM_BINS = 256

# block sizes: DF features x NC rows per grid step; the one-hot block is
# (DF, B, NC) bf16 = 8 x 256 x 512 x 2B = 2 MB VMEM by default, with rows
# on the 128-lane dim (NC must be a multiple of 128 on real TPU; DF a
# multiple of 8). Env-tunable (MMLSPARK_TPU_HIST_DF / _NC) so on-chip
# sweeps need no code edits.
_DF = int(os.environ.get("MMLSPARK_TPU_HIST_DF", "8"))
_NC = int(os.environ.get("MMLSPARK_TPU_HIST_NC", "512"))


def _tpu_compiler_params():
    """Mosaic scoped-VMEM ceiling for the histogram kernels.

    The default 16 MB limit is too tight for the multi-plane kernel's
    resident set (one-hot block + packed accumulator: ~16.1 MB at
    DF=32, B=256, 32 slots — observed as a compile-time scoped-vmem OOM
    at d=64 on v5e). The chip has 128 MB of VMEM; raise the ceiling so
    legal block choices aren't rejected 128 KB over the default bound.
    """
    if jax.default_backend() != "tpu":
        return None
    from jax.experimental.pallas import tpu as pltpu

    # jax renamed TPUCompilerParams -> CompilerParams (0.6); accept both
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is None:
        return None
    return cls(vmem_limit_bytes=_hist_vmem_mb() << 20)


def _hist_vmem_mb() -> int:
    return int(os.environ.get("MMLSPARK_TPU_HIST_VMEM_MB", "96"))


def _pallas_enabled() -> bool:
    """Is the Pallas lowering wanted at all (any device layout)?"""
    env = os.environ.get("MMLSPARK_TPU_PALLAS")
    if env is not None:
        return env not in ("0", "false", "")
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def use_pallas() -> bool:
    """Unsharded-trace lowering choice (single-chip; or env-forced)."""
    env = os.environ.get("MMLSPARK_TPU_PALLAS")
    if env is not None:
        return env not in ("0", "false", "")
    try:
        return jax.default_backend() == "tpu" and jax.device_count() == 1
    except Exception:
        return False


def use_host_hist() -> bool:
    """Host-bincount lowering choice (CPU backend; or env-forced).

    ``MMLSPARK_TPU_HIST_HOST=0`` restores the XLA scatter lowering (the
    only pre-host-kernel CPU path — kept for A/B measurement and for the
    GSPMD-partitioned sharded case, which never takes the host path)."""
    env = os.environ.get("MMLSPARK_TPU_HIST_HOST")
    if env is not None:
        return env not in ("0", "false", "")
    try:
        return jax.default_backend() == "cpu" and not use_pallas()
    except Exception:
        return False


def hist_lowering() -> str:
    """Name of the unsharded-trace lowering that :func:`plane_histogram`
    would pick right now: ``pallas`` | ``cpu`` (host bincount) |
    ``scatter``. Recorded by the bench so the CPU-vs-TPU numbers say
    which kernel produced them."""
    if use_pallas():
        return "pallas"
    if use_host_hist():
        return "cpu"
    return "scatter"


def _rows_sharded(mesh, shard_axis) -> bool:
    try:
        return (
            mesh is not None
            and shard_axis is not None
            and dict(mesh.shape).get(shard_axis, 1) > 1
        )
    except Exception:
        return False


# -- host (numpy bincount) lowering -----------------------------------------
#
# One module-level kernel per op so the traced callback target is a stable
# object: jit caches of the enclosing programs stay valid across train()
# calls (a fresh closure per call would retrace every fit).


def _host_bincounts(
    out: np.ndarray, b: np.ndarray, base, s: np.ndarray, ns: int, nb: int,
    in_range: bool = False,
) -> None:
    """Shared accumulation loop: per feature, one weighted bincount per
    stat column into ``out[:, f]``. ``base`` is the per-row plane offset
    (slot * nb, or 0) with a trash value of ns*nb for dropped rows;
    out-of-range bin codes also land in the trash slot (scatter's
    mode='drop' semantics). np.bincount accumulates in f64 and the result
    is cast once — slightly MORE accurate than the f32 scatter it
    replaces."""
    g, h, c = s[:, 0], s[:, 1], s[:, 2]
    trash = ns * nb
    width = trash + 1
    # one contiguous transpose up front: per-feature rows become
    # sequential reads, and the per-feature astype goes away (~30% of the
    # kernel at bench shapes)
    bT = np.ascontiguousarray(b.T, np.int32)
    in_range = in_range or (
        bool((bT.min() >= 0) and (bT.max() < nb)) if bT.size else True
    )
    for f in range(bT.shape[0]):
        col = bT[f]
        if in_range:
            idx = base + col
        else:
            idx = np.where((col >= 0) & (col < nb), base + col, trash)
        for j, w in enumerate((g, h, c)):
            out[:, f, :, j] = np.bincount(
                idx, weights=w, minlength=width
            )[:trash].reshape(ns, nb)


def _pool_worthwhile(kept_rows: int, d: int) -> bool:
    from mmlspark_tpu.ops.histpool import MIN_POOL_ITEMS

    return kept_rows * d >= MIN_POOL_ITEMS


def _try_pool(
    b: np.ndarray, base: np.ndarray, s3: np.ndarray, ns: int, nb: int
) -> "np.ndarray | None":
    """Feature-parallel process pool (histpool.py). None = run serial.
    Bit-identical to the serial loop either way (same per-feature
    bincounts, same row order)."""
    from mmlspark_tpu.ops.histpool import pooled_bincounts

    res = pooled_bincounts(b, base, s3, ns, nb)
    if res is None:
        return None
    # the pool result aliases its shared arena (valid until the next
    # call) — copy before handing it to the callback bridge
    return res.reshape(ns, b.shape[1] * nb, 3).copy()


def _host_plane_kernel(
    num_bins: int, in_range: bool, bins, stats, mask=None
) -> np.ndarray:
    """(n, d) bins + (n, 3) stats [+ (n,) weight mask] -> (d*B, 3) f32.

    The mask arrives as the raw row selector, not pre-zeroed stats, so
    sparse selections (a leaf-wise split's moved rows) compact to the
    selected rows first: per-split cost is proportional to the CHILD
    size. At >= half the rows kept, scanning everything with zeroed
    weights beats the gather; full-width builds go to the worker pool."""
    nb = num_bins
    b = np.asarray(bins)
    n = b.shape[0]
    m = None if mask is None else np.asarray(mask, np.float32)
    n_kept = n if m is None else int(np.count_nonzero(m))
    if (
        in_range
        and b.dtype in (np.int32, np.uint8)
        and n_kept == n
        and _pool_worthwhile(n, b.shape[1])
        # fractional masks stay serial: the pool transports f32 stats, so
        # an f32 mask multiply would differ from the serial kernel's f64
        # product in the last ulp — only exact 0/1 selectors preserve the
        # pooled == serial bit-identity invariant
        and (m is None or bool(np.all((m == 0.0) | (m == 1.0))))
    ):
        s32 = np.asarray(stats, np.float32)
        s3 = np.ascontiguousarray((s32 if m is None else s32 * m[:, None]).T)
        res = _try_pool(b, np.zeros(n, np.int64), s3, 1, nb)
        if res is not None:
            return res.reshape(b.shape[1] * nb, 3)
    s = np.asarray(stats, np.float64)
    base: "np.ndarray | int" = 0
    if m is not None:
        m64 = m.astype(np.float64)
        if n_kept < (n >> 1):
            keep = np.flatnonzero(m64)
            b, s = b[keep], s[keep] * m64[keep, None]
        else:
            s = s * m64[:, None]
    out = np.empty((1, b.shape[1], nb, 3), np.float32)
    _host_bincounts(out, b, base, s, 1, nb, in_range)
    return out.reshape(b.shape[1] * nb, 3)


def _host_multi_kernel(
    num_slots: int, num_bins: int, in_range: bool, bins, stats, slot
) -> np.ndarray:
    """Multi-leaf planes: (n,) slot selects the plane; out-of-range slots
    drop, so the sibling-subtraction caller's cost is proportional to the
    rows it actually histograms, not the dataset. Large builds go to the
    worker pool (dropped rows ride along as trash offsets — cheaper than
    a main-thread compaction gather)."""
    ns, nb = num_slots, num_bins
    b = np.asarray(bins)
    sl = np.asarray(slot).astype(np.int64)
    ok = (sl >= 0) & (sl < ns)
    all_ok = bool(ok.all())
    kept = b.shape[0] if all_ok else int(ok.sum())
    # pool only when the SELECTED work is large: the pool scans dropped
    # rows too (trash offsets), so a small child inside a big dataset is
    # cheaper through the compacting serial path
    if (
        in_range
        and b.dtype in (np.int32, np.uint8)
        and _pool_worthwhile(kept, b.shape[1])
    ):
        base = sl * nb if all_ok else np.where(ok, sl * nb, ns * nb)
        res = _try_pool(
            b, base, np.ascontiguousarray(np.asarray(stats, np.float32).T),
            ns, nb,
        )
        if res is not None:
            return res
    s = np.asarray(stats, np.float64)
    if not all_ok:
        keep = np.flatnonzero(ok)
        if keep.size < (b.shape[0] >> 1):
            b, s, sl = b[keep], s[keep], sl[keep]
            base = sl * nb
        else:
            base = np.where(ok, sl * nb, ns * nb)
    else:
        base = sl * nb
    out = np.empty((ns, b.shape[1], nb, 3), np.float32)
    _host_bincounts(out, b, base, s, ns, nb, in_range)
    return out.reshape(ns, b.shape[1] * nb, 3)


_DEVICE_PHASE = None


def _attributed(kernel, stage: str):
    """Wrap a pure_callback host kernel so its wall time lands in
    ``mmlspark_device_seconds_total{phase="host_callback"}`` — host time
    the device computation sits waiting out (core/profiling.py)."""
    def run(*args):
        global _DEVICE_PHASE
        if _DEVICE_PHASE is None:
            from mmlspark_tpu.core.profiling import device_phase

            _DEVICE_PHASE = device_phase
        with _DEVICE_PHASE("host_callback", stage):
            return kernel(*args)

    return run


def _callback(kernel, out_shape, *args) -> jnp.ndarray:
    """pure_callback with version-portable vmap handling."""
    try:
        return jax.pure_callback(
            kernel, out_shape, *args, vmap_method="sequential"
        )
    except TypeError:  # older jax: no vmap_method kwarg
        return jax.pure_callback(kernel, out_shape, *args, vectorized=False)


def _plane_histogram_host(
    bins: jnp.ndarray,
    stats: jnp.ndarray,
    mask: "jnp.ndarray | None",
    num_bins: int = NUM_BINS,
    assume_in_range: bool = False,
) -> jnp.ndarray:
    d = bins.shape[1]
    out = jax.ShapeDtypeStruct((d * num_bins, 3), jnp.float32)
    kern = _attributed(
        functools.partial(_host_plane_kernel, num_bins, assume_in_range),
        "histogram_plane",
    )
    if mask is None:
        return _callback(kern, out, bins, stats)
    return _callback(kern, out, bins, stats, mask)


def _multi_plane_host(
    bins: jnp.ndarray,
    stats: jnp.ndarray,
    slot: jnp.ndarray,
    num_slots: int,
    num_bins: int = NUM_BINS,
    assume_in_range: bool = False,
) -> jnp.ndarray:
    d = bins.shape[1]
    out = jax.ShapeDtypeStruct((num_slots, d * num_bins, 3), jnp.float32)
    kern = _attributed(
        functools.partial(
            _host_multi_kernel, num_slots, num_bins, assume_in_range
        ),
        "histogram_multi",
    )
    return _callback(kern, out, bins, stats, slot)


def _hist_kernel(bins_ref, stats_ref, out_ref, *, num_bins: int):
    """One (feature-block, row-chunk) step: accumulate one-hot @ stats."""
    import jax.experimental.pallas as pl

    row_chunk = pl.program_id(1)

    @pl.when(row_chunk == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:]          # (DF, NC) int32; out-of-range = contribute nowhere
    stats = stats_ref[:]        # (NC, 3) f32 (already mask-scaled; 0 rows inert)
    df, nc = bins.shape
    b = num_bins
    # one_hot[f, v, r] = (bins[f, r] == v): a 3-D iota compare instead of a
    # repeat — Mosaic lowers the broadcast/compare on the VPU, and the
    # (features, rows) layout keeps the 128-lane dim on rows so the block
    # shape tiles legally on real TPU hardware (rows % 128 == 0).
    v = jax.lax.broadcasted_iota(jnp.int32, (df, b, nc), 1)
    one_hot = (bins[:, None, :] == v).astype(jnp.bfloat16)  # 0/1: exact in bf16
    # bf16-split matmul: the MXU's native pass truncates f32 operands to
    # bf16, which visibly perturbs gradient sums (and split decisions).
    # Stats split as hi + lo bf16 terms recovers ~f32 accuracy in 2 native
    # passes instead of Precision.HIGHEST's 6 (one-hot needs no split).
    hi = stats.astype(jnp.bfloat16)
    lo = (stats - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    both = jnp.concatenate([hi, lo], axis=1)  # (NC, 6)
    acc = jax.lax.dot_general(
        one_hot.reshape(df * b, nc), both,
        dimension_numbers=(((1,), (0,)), ((), ())),  # contract over rows -> (DF*B, 6)
        preferred_element_type=jnp.float32,
    )
    out_ref[:] += acc[:, :3] + acc[:, 3:]


def _hist_split_kernel(bins_ref, stats_ref, out_ref, *, bh: int, bl: int):
    """Decomposed one-hot step: bin = hi * BL + lo.

    The plain kernel's VPU cost is B compares per (row, feature) cell —
    the measured bound at B=256. Decomposing cuts that to
    BH compares (the hi one-hot, the matmul lhs) plus BL*6 compare-selects
    (the rhs: per (lo, stat) column, the row's stat value where its lo
    code matches). The MXU contraction then recovers every (hi, lo) bin
    pair: acc[f, hi, lo*6+j] = sum_r oh_hi * rhs. Measured ~2x the plain
    kernel on real hardware at B=256 (BH=32, BL=8). Output stays PACKED
    (df*BH, BL*6); the caller unpacks outside the kernel where layout is
    free — in-kernel recombination would need minor-dim reshapes Mosaic
    rejects."""
    import jax.experimental.pallas as pl

    row_chunk = pl.program_id(1)

    @pl.when(row_chunk == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:]          # (DF, NC) int32; sentinel -> hi code == BH
    stats = stats_ref[:]        # (NC, 3) f32
    df, nc = bins.shape
    hi_c = bins // bl
    lo_c = bins % bl
    vh = jax.lax.broadcasted_iota(jnp.int32, (df, bh, nc), 1)
    oh_hi = (hi_c[:, None, :] == vh).astype(jnp.bfloat16)
    s_hi = stats.astype(jnp.bfloat16)
    s_lo = (stats - s_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    both = jnp.concatenate([s_hi, s_lo], axis=1).T               # (6, NC)
    # rhs[f, lo*6+j, r] = both[j, r] where lo_c[f, r] == lo else 0
    vl = jax.lax.broadcasted_iota(jnp.int32, (df, bl * 6, nc), 1) // 6
    both_t = jnp.tile(both, (bl, 1))                             # (BL*6, NC)
    rhs = jnp.where(
        lo_c[:, None, :] == vl, both_t[None], 0
    ).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        oh_hi, rhs,
        dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                            # (DF, BH, BL*6)
    out_ref[:] += acc.reshape(df * bh, bl * 6)


# the decomposed kernel's feature block (bigger blocks amortize the rhs
# build; 32 measured within 2% of the best and halves padding waste)
_DF_SPLIT = int(os.environ.get("MMLSPARK_TPU_HIST_SPLIT_DF", "32"))
_BL_SPLIT = 8


def _use_split(num_bins: int) -> bool:
    """Decomposition pays when B is large (compare-bound); at B <= 64 the
    plain one-hot is already cheap and the split's fixed rhs cost
    (BL*6 = 48 ops/cell) stops being a win."""
    if num_bins % _BL_SPLIT != 0 or num_bins < 2 * _BL_SPLIT:
        # the decomposition needs bin = hi*BL + lo to tile exactly; an env
        # force must not override that into a trace-time crash
        return False
    env = os.environ.get("MMLSPARK_TPU_HIST_SPLIT")
    if env is not None:
        return env not in ("0", "false", "")
    return num_bins >= 128


def _plane_histogram_pallas(
    bins: jnp.ndarray, stats: jnp.ndarray, num_bins: int = NUM_BINS
) -> jnp.ndarray:
    """(n, d) int32 bins + (n, 3) stats -> (d * B, 3) plane via Pallas."""
    import jax.experimental.pallas as pl

    n, d = bins.shape
    b = num_bins
    split = _use_split(b)
    df = _DF_SPLIT if split else _DF
    d_pad = ((d + df - 1) // df) * df
    n_pad = ((n + _NC - 1) // _NC) * _NC
    # sentinel: any value outside [0, B) matches no one-hot column (its hi
    # code b // BL == BH in the split kernel), so the row contributes
    # nowhere. Used for padded features AND for out-of-range caller bins —
    # the scatter lowering drops those (mode='drop') and the lowerings
    # must agree exactly.
    sentinel = b
    bins = jnp.where((bins >= 0) & (bins < b), bins, sentinel)
    if d_pad != d:
        bins = jnp.pad(bins, ((0, 0), (0, d_pad - d)), constant_values=sentinel)
    if n_pad != n:
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)), constant_values=sentinel)
        stats = jnp.pad(stats, ((0, n_pad - n), (0, 0)))

    if split:
        bl = _BL_SPLIT
        bh = b // bl
        packed = pl.pallas_call(
            functools.partial(_hist_split_kernel, bh=bh, bl=bl),
            grid=(d_pad // df, n_pad // _NC),
            in_specs=[
                pl.BlockSpec((df, _NC), lambda f, r: (f, r)),
                pl.BlockSpec((_NC, 3), lambda f, r: (r, 0)),
            ],
            out_specs=pl.BlockSpec((df * bh, bl * 6), lambda f, r: (f, 0)),
            out_shape=jax.ShapeDtypeStruct((d_pad * bh, bl * 6), jnp.float32),
            interpret=jax.default_backend() == "cpu",
            compiler_params=_tpu_compiler_params(),
        )(bins.T.astype(jnp.int32), stats.astype(jnp.float32))
        un = packed.reshape(d_pad, bh, bl, 6)
        out = (un[..., :3] + un[..., 3:]).reshape(d_pad * b, 3)
        return out[: d * b]

    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=b),
        grid=(d_pad // df, n_pad // _NC),
        in_specs=[
            pl.BlockSpec((df, _NC), lambda f, r: (f, r)),
            pl.BlockSpec((_NC, 3), lambda f, r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((df * b, 3), lambda f, r: (f, 0)),
        out_shape=jax.ShapeDtypeStruct((d_pad * b, 3), jnp.float32),
        interpret=jax.default_backend() == "cpu",
        compiler_params=_tpu_compiler_params(),
    )(bins.T.astype(jnp.int32), stats.astype(jnp.float32))
    return out[: d * b]


def _multi_kernel(
    bins_ref, stats_ref, slot_ref, out_ref, *, num_slots: int, num_bins: int
):
    """One (feature-block, row-chunk) step of the multi-leaf build: the
    bin one-hot is built ONCE and contracted against slot-masked stats
    columns, producing every leaf's plane stripe in a single wide matmul
    (rhs column s*6+j = [slot==s] * stats_hi/lo[j])."""
    import jax.experimental.pallas as pl

    row_chunk = pl.program_id(1)

    @pl.when(row_chunk == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:]          # (DF, NC) int32
    stats = stats_ref[:]        # (NC, 3) f32
    slot = slot_ref[:]          # (1, NC) int32; out-of-range = no plane
    df, nc = bins.shape
    b = num_bins
    v = jax.lax.broadcasted_iota(jnp.int32, (df, b, nc), 1)
    one_hot = (bins[:, None, :] == v).astype(jnp.bfloat16)
    s_hi = stats.astype(jnp.bfloat16).astype(jnp.float32)
    s_lo = stats - s_hi
    both = jnp.concatenate([s_hi, s_lo], axis=1)                  # (NC, 6)
    w = num_slots * 6
    both_wide = jnp.concatenate([both] * num_slots, axis=1)       # (NC, S*6)
    s_iota = jax.lax.broadcasted_iota(jnp.int32, (nc, w), 1) // 6
    slot_match = (slot[0][:, None] == s_iota).astype(jnp.float32)
    rhs = (slot_match * both_wide).astype(jnp.bfloat16)           # (NC, S*6)
    out_ref[:] += jax.lax.dot_general(
        one_hot.reshape(df * b, nc), rhs,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _multi_resident_bytes(df: int, num_slots: int, num_bins: int) -> int:
    """Estimated VMEM-resident set of one multi-kernel grid step: the
    bf16 one-hot block (DF*B*NC) plus the packed f32 accumulator and
    its dot_general result (2 x DF*B*S*6) — these dominate; row-chunk
    inputs and the slot-mask rhs are < 1 MB."""
    return df * num_bins * (_NC * 2 + num_slots * 6 * 4 * 2)


def _multi_df(num_slots: int, num_bins: int, d: int = 1 << 30) -> int | None:
    """Feature block for the multi-plane kernel: as large as the
    kernel's VMEM-resident set allows (bigger blocks amortize the
    slot-mask rhs; measured +11% at S=32), but never wider than the
    feature count needs (padding a d=4 input to a 32-wide block would
    4x the one-hot work on sentinel rows).

    The budget is 2/3 of the Mosaic ceiling :func:`_tpu_compiler_params`
    sets (same env knob), leaving headroom for double-buffered input DMA
    and Mosaic's own scratch. Returns ``None`` when not even the
    smallest block fits — the caller must use the scatter lowering
    (e.g. thousands of slots at 256 bins)."""
    budget = _hist_vmem_mb() * 2 // 3 << 20
    d_need = max(8, ((d + 7) // 8) * 8)
    best = None
    for df in sorted({32, 16, 8, _DF}, reverse=True):
        if _multi_resident_bytes(df, num_slots, num_bins) > budget:
            continue
        # compare resulting PADDED widths: a wider block that pads to the
        # same width does the same one-hot work in fewer grid steps (fewer
        # slot-mask rebuilds), so prefer it
        pad_w = ((d_need + df - 1) // df) * df
        if best is None or pad_w < best[0] or (pad_w == best[0] and df > best[1]):
            best = (pad_w, df)
    return best[1] if best else None


def _multi_plane_pallas(
    bins: jnp.ndarray, stats: jnp.ndarray, slot: jnp.ndarray, num_slots: int,
    num_bins: int = NUM_BINS, df: int | None = None,
) -> jnp.ndarray:
    import functools as _ft

    import jax.experimental.pallas as pl

    n, d = bins.shape
    b = num_bins
    _df_m = df if df is not None else _multi_df(num_slots, b, d)
    assert _df_m is not None, "no feature block fits VMEM; use scatter"
    d_pad = ((d + _df_m - 1) // _df_m) * _df_m
    n_pad = ((n + _NC - 1) // _NC) * _NC
    sentinel = b
    bins = jnp.where((bins >= 0) & (bins < b), bins, sentinel)
    if d_pad != d:
        bins = jnp.pad(bins, ((0, 0), (0, d_pad - d)), constant_values=sentinel)
    if n_pad != n:
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)), constant_values=sentinel)
        stats = jnp.pad(stats, ((0, n_pad - n), (0, 0)))
        slot = jnp.pad(slot, (0, n_pad - n), constant_values=num_slots)
    packed = pl.pallas_call(
        _ft.partial(_multi_kernel, num_slots=num_slots, num_bins=b),
        grid=(d_pad // _df_m, n_pad // _NC),
        in_specs=[
            pl.BlockSpec((_df_m, _NC), lambda f, r: (f, r)),
            pl.BlockSpec((_NC, 3), lambda f, r: (r, 0)),
            pl.BlockSpec((1, _NC), lambda f, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((_df_m * b, num_slots * 6), lambda f, r: (f, 0)),
        out_shape=jax.ShapeDtypeStruct((d_pad * b, num_slots * 6), jnp.float32),
        interpret=jax.default_backend() == "cpu",
        compiler_params=_tpu_compiler_params(),
    )(
        bins.T.astype(jnp.int32),
        stats.astype(jnp.float32),
        slot.astype(jnp.int32)[None, :],
    )
    # (f*B+v, s*6+j) -> (s, f*B+v, j), summing hi/lo halves
    un = packed.reshape(d_pad * b, num_slots, 6)
    out = jnp.transpose(un[..., :3] + un[..., 3:], (1, 0, 2))
    return out[:, : d * b]


def _multi_plane_scatter(
    bins: jnp.ndarray, stats: jnp.ndarray, slot: jnp.ndarray, num_slots: int,
    num_bins: int = NUM_BINS,
) -> jnp.ndarray:
    n, d = bins.shape
    b = num_bins
    plane_idx = (jnp.arange(d, dtype=jnp.int32) * b)[None, :] + bins   # (n, d)
    flat = slot[:, None] * (d * b) + plane_idx
    oob = (
        (bins < 0) | (bins >= b) | (slot[:, None] < 0) | (slot[:, None] >= num_slots)
    )
    flat = jnp.where(oob, num_slots * d * b, flat)
    contrib = jnp.broadcast_to(stats[:, None, :], (n, d, 3))
    out = (
        jnp.zeros((num_slots * d * b, 3), jnp.float32)
        .at[flat]
        .add(contrib, mode="drop")
    )
    return out.reshape(num_slots, d * b, 3)


def multi_plane_histogram(
    bins: jnp.ndarray,
    stats: jnp.ndarray,
    slot: jnp.ndarray,
    num_slots: int,
    num_bins: int = NUM_BINS,
    mesh=None,
    shard_axis: str | None = None,
    bins_in_range: bool = False,
) -> jnp.ndarray:
    """Histogram planes for MANY leaves in one pass over the rows.

    ``slot``: (n,) int leaf-plane index per row; out-of-range = the row
    contributes to no plane. Returns (num_slots, d*NUM_BINS, 3). This is
    the depthwise grower's workhorse: one row pass per LEVEL instead of
    one per leaf, with the bin one-hot (the VPU-bound part) amortized
    across all the level's leaves. ``mesh``/``shard_axis`` as in
    :func:`plane_histogram` (per-shard kernel + psum of the cube).

    When the slot count is so large that no feature block fits the
    kernel's VMEM budget (thousands of planes at 256 bins — see
    :func:`_multi_df`), the scatter lowering is used regardless of
    backend: slower, but it compiles instead of tripping Mosaic's
    scoped-VMEM ceiling."""
    df_fit = _multi_df(num_slots, num_bins, bins.shape[1])
    use_pl = df_fit is not None and _pallas_enabled()
    if _rows_sharded(mesh, shard_axis):
        from jax.sharding import PartitionSpec as P

        def local(b, s, sl):
            if use_pl:
                cube = _multi_plane_pallas(
                    b.astype(jnp.int32), s, sl.astype(jnp.int32), num_slots,
                    num_bins, df=df_fit,
                )
            else:
                # per-shard scatter partials + the same explicit allreduce
                # (LightGBM data_parallel with the MXU kernel swapped out)
                cube = _multi_plane_scatter(
                    b.astype(jnp.int32), s, sl.astype(jnp.int32), num_slots,
                    num_bins,
                )
            return jax.lax.psum(cube, shard_axis)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(shard_axis, None), P(shard_axis, None), P(shard_axis)),
            out_specs=P(),
            check_vma=False,
        )(bins, stats, slot)
    if df_fit is not None and use_pallas():
        return _multi_plane_pallas(
            bins.astype(jnp.int32), stats, slot.astype(jnp.int32), num_slots,
            num_bins, df=df_fit,
        )
    if use_host_hist():
        return _multi_plane_host(
            bins.astype(jnp.int32), stats, slot.astype(jnp.int32), num_slots,
            num_bins, assume_in_range=bins_in_range,
        )
    # scatter path; under a sharded trace GSPMD partitions the scatter
    # and inserts the allreduce automatically
    return _multi_plane_scatter(
        bins.astype(jnp.int32), stats, slot.astype(jnp.int32), num_slots,
        num_bins,
    )


def leaf_stat_sums(
    leaf: jnp.ndarray, stats: jnp.ndarray, num_leaves: int,
    sharded: bool = False,
) -> jnp.ndarray:
    """Per-leaf (g, h, count) totals: (n,) leaf ids + (n, 3) stats ->
    (num_leaves, 3). The growers' end-of-tree reduction — a (n,)
    scatter-add on the XLA path, one bincount pass on the host path (the
    scatters cost ~3 ms/tree at bench shapes on XLA:CPU, ~25x the host
    kernel). ``sharded``: the caller's rows are sharded over a mesh —
    keep the scatter (GSPMD partitions it; a host callback would force a
    gather)."""
    if not sharded and use_host_hist():
        # leaf ids are grower outputs, always in [0, num_leaves)
        return _plane_histogram_host(
            leaf[:, None].astype(jnp.int32), stats, None, num_leaves,
            assume_in_range=True,
        )
    z = jnp.zeros((num_leaves, 3), jnp.float32)
    return z.at[leaf].add(stats)


def _plane_histogram_scatter(
    bins: jnp.ndarray, stats: jnp.ndarray, num_bins: int = NUM_BINS
) -> jnp.ndarray:
    n, d = bins.shape
    b = num_bins
    plane_idx = (jnp.arange(d, dtype=jnp.int32) * b)[None, :] + bins  # (n, d)
    # out-of-range bins contribute nowhere (a negative bin would otherwise
    # alias into the previous feature's stripe; matches the Pallas lowering)
    plane_idx = jnp.where((bins >= 0) & (bins < b), plane_idx, d * b)
    contrib = jnp.broadcast_to(stats[:, None, :], (n, d, 3))
    return (
        jnp.zeros((d * b, 3), jnp.float32).at[plane_idx].add(contrib, mode="drop")
    )


def _plane_histogram_shard_map(
    bins: jnp.ndarray, stats: jnp.ndarray, mesh, shard_axis: str,
    num_bins: int,
) -> jnp.ndarray:
    """Per-shard kernel + explicit psum of the planes — LightGBM
    data_parallel's per-iteration histogram allreduce over ICI
    (TrainUtils.scala:496-512). On TPU the local kernel is the Pallas MXU
    one-hot; with Pallas off (CPU meshes, forced-device scaling runs) the
    local kernel is the XLA scatter — either way the allreduce is an
    explicit ``psum`` in the program, not a GSPMD inference."""
    from jax.sharding import PartitionSpec as P

    use_pl = _pallas_enabled()

    def local(b: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
        if use_pl:
            h = _plane_histogram_pallas(b.astype(jnp.int32), s, num_bins)
        else:
            h = _plane_histogram_scatter(b.astype(jnp.int32), s, num_bins)
        return jax.lax.psum(h, shard_axis)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(shard_axis, None), P(shard_axis, None)),
        out_specs=P(),
        check_vma=False,
    )(bins, stats)


# wall time of one EAGER sharded histogram build including the explicit
# psum allreduce — the bench's hist scaling rows observe this so the
# ICI-allreduce claim is a recorded number (in-jit builds fuse into the
# surrounding program and cannot be timed individually)
_M_ALLREDUCE_SECONDS = None
_SHARDED_BUILD_CACHE: dict = {}


def sharded_build_timed(
    bins: jnp.ndarray, stats: jnp.ndarray, mesh, shard_axis: str,
    num_bins: int = NUM_BINS,
) -> jnp.ndarray:
    """Eagerly run one per-shard histogram + explicit psum and record the
    wall time into ``mmlspark_gbdt_hist_allreduce_seconds``."""
    global _M_ALLREDUCE_SECONDS
    if _M_ALLREDUCE_SECONDS is None:
        from mmlspark_tpu import obs

        _M_ALLREDUCE_SECONDS = obs.histogram(
            "mmlspark_gbdt_hist_allreduce_seconds",
            "Wall time of one sharded histogram build including the "
            "explicit psum allreduce (observed by eager/bench builds)",
        )
    import time as _t

    key = (mesh, shard_axis, num_bins)
    fn = _SHARDED_BUILD_CACHE.get(key)
    if fn is None:
        fn = jax.jit(
            functools.partial(
                _plane_histogram_shard_map, mesh=mesh,
                shard_axis=shard_axis, num_bins=num_bins,
            )
        )
        _SHARDED_BUILD_CACHE[key] = fn
    t0 = _t.perf_counter()
    out = fn(bins, stats)
    jax.block_until_ready(out)
    _M_ALLREDUCE_SECONDS.observe(_t.perf_counter() - t0)
    return out


def plane_histogram(
    bins: jnp.ndarray, stats: jnp.ndarray, mask: jnp.ndarray | None = None,
    num_bins: int = NUM_BINS, mesh=None, shard_axis: str | None = None,
    allow_host: bool = True, bins_in_range: bool = False,
) -> jnp.ndarray:
    """(d * NUM_BINS, 3) gradient-histogram plane of the masked rows.

    ``bins``: (n, d) int bin codes; ``stats``: (n, 3) per-row (g, h, count);
    ``mask``: optional (n,) row selector (0 rows contribute nothing).
    ``mesh``/``shard_axis``: when the rows are sharded over that mesh axis,
    run the local kernel (Pallas on TPU, scatter otherwise) per shard
    under shard_map and psum the planes.
    """
    if _rows_sharded(mesh, shard_axis):
        if mask is not None:
            stats = stats * mask[:, None]
        return _plane_histogram_shard_map(
            bins, stats, mesh, shard_axis, num_bins
        )
    if use_pallas():
        if mask is not None:
            stats = stats * mask[:, None]
        return _plane_histogram_pallas(bins.astype(jnp.int32), stats, num_bins)
    if allow_host and use_host_hist():
        # the host kernel takes the RAW mask: sparse selections compact
        # to the selected rows instead of scanning zeroed stats
        return _plane_histogram_host(
            bins.astype(jnp.int32), stats, mask, num_bins,
            assume_in_range=bins_in_range,
        )
    if mask is not None:
        stats = stats * mask[:, None]
    return _plane_histogram_scatter(bins.astype(jnp.int32), stats, num_bins)
