"""Histogram plane builder — the GBDT hot op.

LightGBM's C++ trainer spends its time building per-leaf gradient
histograms. Here the op is ``plane_histogram(bins, stats, mask)``:
scatter the (g, h, count) stats of the masked rows into a
``(d * NUM_BINS, 3)`` plane.

Two lowerings:

- **Pallas (TPU, single chip)**: grid over (feature-blocks, row-chunks);
  each step builds a bf16 one-hot (DF, B, rows) block in VMEM (rows on the
  128-lane dim) and accumulates ``one_hot @ stats_hi/lo`` into the output
  block — the scatter becomes an MXU matmul, which is how TPUs like their
  histograms. Stats are split hi+lo bf16 so two native MXU passes recover
  f32-grade sums. Rows stream chunk by chunk so VMEM stays bounded.
- **shard_map + Pallas (TPU, sharded meshes)**: when the caller passes the
  mesh whose ``data`` axis shards the rows, the kernel runs PER SHARD under
  ``jax.shard_map`` and the (d*B, 3) planes are combined with an explicit
  ``psum`` riding ICI — exactly LightGBM's data_parallel per-iteration
  histogram allreduce (lightgbm/TrainUtils.scala:496-512 NetworkInit +
  socket rings), with the MXU kernel intact on every chip.
- **XLA scatter-add (CPU, or sharded meshes without a mesh handle)**:
  GSPMD partitions the scatter across the mesh and inserts the ICI
  allreduce automatically.

Selection is automatic (see :func:`use_pallas`) and overridable with
``MMLSPARK_TPU_PALLAS=0|1``.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.parallel.compat import shard_map

NUM_BINS = 256

# block sizes: DF features x NC rows per grid step; the one-hot block is
# (DF, B, NC) bf16 = 8 x 256 x 512 x 2B = 2 MB VMEM by default, with rows
# on the 128-lane dim (NC must be a multiple of 128 on real TPU; DF a
# multiple of 8). Env-tunable (MMLSPARK_TPU_HIST_DF / _NC) so on-chip
# sweeps need no code edits.
_DF = int(os.environ.get("MMLSPARK_TPU_HIST_DF", "8"))
_NC = int(os.environ.get("MMLSPARK_TPU_HIST_NC", "512"))


def _tpu_compiler_params():
    """Mosaic scoped-VMEM ceiling for the histogram kernels.

    The default 16 MB limit is too tight for the multi-plane kernel's
    resident set (one-hot block + packed accumulator: ~16.1 MB at
    DF=32, B=256, 32 slots — observed as a compile-time scoped-vmem OOM
    at d=64 on v5e). The chip has 128 MB of VMEM; raise the ceiling so
    legal block choices aren't rejected 128 KB over the default bound.
    """
    if jax.default_backend() != "tpu":
        return None
    from jax.experimental.pallas import tpu as pltpu

    # jax renamed TPUCompilerParams -> CompilerParams (0.6); accept both
    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    if cls is None:
        return None
    return cls(vmem_limit_bytes=_hist_vmem_mb() << 20)


def _hist_vmem_mb() -> int:
    return int(os.environ.get("MMLSPARK_TPU_HIST_VMEM_MB", "96"))


def _pallas_enabled() -> bool:
    """Is the Pallas lowering wanted at all (any device layout)?"""
    env = os.environ.get("MMLSPARK_TPU_PALLAS")
    if env is not None:
        return env not in ("0", "false", "")
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def use_pallas() -> bool:
    """Unsharded-trace lowering choice (single-chip; or env-forced)."""
    env = os.environ.get("MMLSPARK_TPU_PALLAS")
    if env is not None:
        return env not in ("0", "false", "")
    try:
        return jax.default_backend() == "tpu" and jax.device_count() == 1
    except Exception:
        return False


def _rows_sharded(mesh, shard_axis) -> bool:
    try:
        return (
            mesh is not None
            and shard_axis is not None
            and dict(mesh.shape).get(shard_axis, 1) > 1
        )
    except Exception:
        return False


def _hist_kernel(bins_ref, stats_ref, out_ref, *, num_bins: int):
    """One (feature-block, row-chunk) step: accumulate one-hot @ stats."""
    import jax.experimental.pallas as pl

    row_chunk = pl.program_id(1)

    @pl.when(row_chunk == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:]          # (DF, NC) int32; out-of-range = contribute nowhere
    stats = stats_ref[:]        # (NC, 3) f32 (already mask-scaled; 0 rows inert)
    df, nc = bins.shape
    b = num_bins
    # one_hot[f, v, r] = (bins[f, r] == v): a 3-D iota compare instead of a
    # repeat — Mosaic lowers the broadcast/compare on the VPU, and the
    # (features, rows) layout keeps the 128-lane dim on rows so the block
    # shape tiles legally on real TPU hardware (rows % 128 == 0).
    v = jax.lax.broadcasted_iota(jnp.int32, (df, b, nc), 1)
    one_hot = (bins[:, None, :] == v).astype(jnp.bfloat16)  # 0/1: exact in bf16
    # bf16-split matmul: the MXU's native pass truncates f32 operands to
    # bf16, which visibly perturbs gradient sums (and split decisions).
    # Stats split as hi + lo bf16 terms recovers ~f32 accuracy in 2 native
    # passes instead of Precision.HIGHEST's 6 (one-hot needs no split).
    hi = stats.astype(jnp.bfloat16)
    lo = (stats - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    both = jnp.concatenate([hi, lo], axis=1)  # (NC, 6)
    acc = jax.lax.dot_general(
        one_hot.reshape(df * b, nc), both,
        dimension_numbers=(((1,), (0,)), ((), ())),  # contract over rows -> (DF*B, 6)
        preferred_element_type=jnp.float32,
    )
    out_ref[:] += acc[:, :3] + acc[:, 3:]


def _hist_split_kernel(bins_ref, stats_ref, out_ref, *, bh: int, bl: int):
    """Decomposed one-hot step: bin = hi * BL + lo.

    The plain kernel's VPU cost is B compares per (row, feature) cell —
    the measured bound at B=256. Decomposing cuts that to
    BH compares (the hi one-hot, the matmul lhs) plus BL*6 compare-selects
    (the rhs: per (lo, stat) column, the row's stat value where its lo
    code matches). The MXU contraction then recovers every (hi, lo) bin
    pair: acc[f, hi, lo*6+j] = sum_r oh_hi * rhs. Measured ~2x the plain
    kernel on real hardware at B=256 (BH=32, BL=8). Output stays PACKED
    (df*BH, BL*6); the caller unpacks outside the kernel where layout is
    free — in-kernel recombination would need minor-dim reshapes Mosaic
    rejects."""
    import jax.experimental.pallas as pl

    row_chunk = pl.program_id(1)

    @pl.when(row_chunk == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:]          # (DF, NC) int32; sentinel -> hi code == BH
    stats = stats_ref[:]        # (NC, 3) f32
    df, nc = bins.shape
    hi_c = bins // bl
    lo_c = bins % bl
    vh = jax.lax.broadcasted_iota(jnp.int32, (df, bh, nc), 1)
    oh_hi = (hi_c[:, None, :] == vh).astype(jnp.bfloat16)
    s_hi = stats.astype(jnp.bfloat16)
    s_lo = (stats - s_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    both = jnp.concatenate([s_hi, s_lo], axis=1).T               # (6, NC)
    # rhs[f, lo*6+j, r] = both[j, r] where lo_c[f, r] == lo else 0
    vl = jax.lax.broadcasted_iota(jnp.int32, (df, bl * 6, nc), 1) // 6
    both_t = jnp.tile(both, (bl, 1))                             # (BL*6, NC)
    rhs = jnp.where(
        lo_c[:, None, :] == vl, both_t[None], 0
    ).astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        oh_hi, rhs,
        dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                            # (DF, BH, BL*6)
    out_ref[:] += acc.reshape(df * bh, bl * 6)


# the decomposed kernel's feature block (bigger blocks amortize the rhs
# build; 32 measured within 2% of the best and halves padding waste)
_DF_SPLIT = int(os.environ.get("MMLSPARK_TPU_HIST_SPLIT_DF", "32"))
_BL_SPLIT = 8


def _use_split(num_bins: int) -> bool:
    """Decomposition pays when B is large (compare-bound); at B <= 64 the
    plain one-hot is already cheap and the split's fixed rhs cost
    (BL*6 = 48 ops/cell) stops being a win."""
    if num_bins % _BL_SPLIT != 0 or num_bins < 2 * _BL_SPLIT:
        # the decomposition needs bin = hi*BL + lo to tile exactly; an env
        # force must not override that into a trace-time crash
        return False
    env = os.environ.get("MMLSPARK_TPU_HIST_SPLIT")
    if env is not None:
        return env not in ("0", "false", "")
    return num_bins >= 128


def _plane_histogram_pallas(
    bins: jnp.ndarray, stats: jnp.ndarray, num_bins: int = NUM_BINS
) -> jnp.ndarray:
    """(n, d) int32 bins + (n, 3) stats -> (d * B, 3) plane via Pallas."""
    import jax.experimental.pallas as pl

    n, d = bins.shape
    b = num_bins
    split = _use_split(b)
    df = _DF_SPLIT if split else _DF
    d_pad = ((d + df - 1) // df) * df
    n_pad = ((n + _NC - 1) // _NC) * _NC
    # sentinel: any value outside [0, B) matches no one-hot column (its hi
    # code b // BL == BH in the split kernel), so the row contributes
    # nowhere. Used for padded features AND for out-of-range caller bins —
    # the scatter lowering drops those (mode='drop') and the lowerings
    # must agree exactly.
    sentinel = b
    bins = jnp.where((bins >= 0) & (bins < b), bins, sentinel)
    if d_pad != d:
        bins = jnp.pad(bins, ((0, 0), (0, d_pad - d)), constant_values=sentinel)
    if n_pad != n:
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)), constant_values=sentinel)
        stats = jnp.pad(stats, ((0, n_pad - n), (0, 0)))

    if split:
        bl = _BL_SPLIT
        bh = b // bl
        packed = pl.pallas_call(
            functools.partial(_hist_split_kernel, bh=bh, bl=bl),
            grid=(d_pad // df, n_pad // _NC),
            in_specs=[
                pl.BlockSpec((df, _NC), lambda f, r: (f, r)),
                pl.BlockSpec((_NC, 3), lambda f, r: (r, 0)),
            ],
            out_specs=pl.BlockSpec((df * bh, bl * 6), lambda f, r: (f, 0)),
            out_shape=jax.ShapeDtypeStruct((d_pad * bh, bl * 6), jnp.float32),
            interpret=jax.default_backend() == "cpu",
            compiler_params=_tpu_compiler_params(),
        )(bins.T.astype(jnp.int32), stats.astype(jnp.float32))
        un = packed.reshape(d_pad, bh, bl, 6)
        out = (un[..., :3] + un[..., 3:]).reshape(d_pad * b, 3)
        return out[: d * b]

    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=b),
        grid=(d_pad // df, n_pad // _NC),
        in_specs=[
            pl.BlockSpec((df, _NC), lambda f, r: (f, r)),
            pl.BlockSpec((_NC, 3), lambda f, r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((df * b, 3), lambda f, r: (f, 0)),
        out_shape=jax.ShapeDtypeStruct((d_pad * b, 3), jnp.float32),
        interpret=jax.default_backend() == "cpu",
        compiler_params=_tpu_compiler_params(),
    )(bins.T.astype(jnp.int32), stats.astype(jnp.float32))
    return out[: d * b]


def _multi_kernel(
    bins_ref, stats_ref, slot_ref, out_ref, *, num_slots: int, num_bins: int
):
    """One (feature-block, row-chunk) step of the multi-leaf build: the
    bin one-hot is built ONCE and contracted against slot-masked stats
    columns, producing every leaf's plane stripe in a single wide matmul
    (rhs column s*6+j = [slot==s] * stats_hi/lo[j])."""
    import jax.experimental.pallas as pl

    row_chunk = pl.program_id(1)

    @pl.when(row_chunk == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = bins_ref[:]          # (DF, NC) int32
    stats = stats_ref[:]        # (NC, 3) f32
    slot = slot_ref[:]          # (1, NC) int32; out-of-range = no plane
    df, nc = bins.shape
    b = num_bins
    v = jax.lax.broadcasted_iota(jnp.int32, (df, b, nc), 1)
    one_hot = (bins[:, None, :] == v).astype(jnp.bfloat16)
    s_hi = stats.astype(jnp.bfloat16).astype(jnp.float32)
    s_lo = stats - s_hi
    both = jnp.concatenate([s_hi, s_lo], axis=1)                  # (NC, 6)
    w = num_slots * 6
    both_wide = jnp.concatenate([both] * num_slots, axis=1)       # (NC, S*6)
    s_iota = jax.lax.broadcasted_iota(jnp.int32, (nc, w), 1) // 6
    slot_match = (slot[0][:, None] == s_iota).astype(jnp.float32)
    rhs = (slot_match * both_wide).astype(jnp.bfloat16)           # (NC, S*6)
    out_ref[:] += jax.lax.dot_general(
        one_hot.reshape(df * b, nc), rhs,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _multi_resident_bytes(df: int, num_slots: int, num_bins: int) -> int:
    """Estimated VMEM-resident set of one multi-kernel grid step: the
    bf16 one-hot block (DF*B*NC) plus the packed f32 accumulator and
    its dot_general result (2 x DF*B*S*6) — these dominate; row-chunk
    inputs and the slot-mask rhs are < 1 MB."""
    return df * num_bins * (_NC * 2 + num_slots * 6 * 4 * 2)


def _multi_df(num_slots: int, num_bins: int, d: int = 1 << 30) -> int | None:
    """Feature block for the multi-plane kernel: as large as the
    kernel's VMEM-resident set allows (bigger blocks amortize the
    slot-mask rhs; measured +11% at S=32), but never wider than the
    feature count needs (padding a d=4 input to a 32-wide block would
    4x the one-hot work on sentinel rows).

    The budget is 2/3 of the Mosaic ceiling :func:`_tpu_compiler_params`
    sets (same env knob), leaving headroom for double-buffered input DMA
    and Mosaic's own scratch. Returns ``None`` when not even the
    smallest block fits — the caller must use the scatter lowering
    (e.g. thousands of slots at 256 bins)."""
    budget = _hist_vmem_mb() * 2 // 3 << 20
    d_need = max(8, ((d + 7) // 8) * 8)
    best = None
    for df in sorted({32, 16, 8, _DF}, reverse=True):
        if _multi_resident_bytes(df, num_slots, num_bins) > budget:
            continue
        # compare resulting PADDED widths: a wider block that pads to the
        # same width does the same one-hot work in fewer grid steps (fewer
        # slot-mask rebuilds), so prefer it
        pad_w = ((d_need + df - 1) // df) * df
        if best is None or pad_w < best[0] or (pad_w == best[0] and df > best[1]):
            best = (pad_w, df)
    return best[1] if best else None


def _multi_plane_pallas(
    bins: jnp.ndarray, stats: jnp.ndarray, slot: jnp.ndarray, num_slots: int,
    num_bins: int = NUM_BINS, df: int | None = None,
) -> jnp.ndarray:
    import functools as _ft

    import jax.experimental.pallas as pl

    n, d = bins.shape
    b = num_bins
    _df_m = df if df is not None else _multi_df(num_slots, b, d)
    assert _df_m is not None, "no feature block fits VMEM; use scatter"
    d_pad = ((d + _df_m - 1) // _df_m) * _df_m
    n_pad = ((n + _NC - 1) // _NC) * _NC
    sentinel = b
    bins = jnp.where((bins >= 0) & (bins < b), bins, sentinel)
    if d_pad != d:
        bins = jnp.pad(bins, ((0, 0), (0, d_pad - d)), constant_values=sentinel)
    if n_pad != n:
        bins = jnp.pad(bins, ((0, n_pad - n), (0, 0)), constant_values=sentinel)
        stats = jnp.pad(stats, ((0, n_pad - n), (0, 0)))
        slot = jnp.pad(slot, (0, n_pad - n), constant_values=num_slots)
    packed = pl.pallas_call(
        _ft.partial(_multi_kernel, num_slots=num_slots, num_bins=b),
        grid=(d_pad // _df_m, n_pad // _NC),
        in_specs=[
            pl.BlockSpec((_df_m, _NC), lambda f, r: (f, r)),
            pl.BlockSpec((_NC, 3), lambda f, r: (r, 0)),
            pl.BlockSpec((1, _NC), lambda f, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((_df_m * b, num_slots * 6), lambda f, r: (f, 0)),
        out_shape=jax.ShapeDtypeStruct((d_pad * b, num_slots * 6), jnp.float32),
        interpret=jax.default_backend() == "cpu",
        compiler_params=_tpu_compiler_params(),
    )(
        bins.T.astype(jnp.int32),
        stats.astype(jnp.float32),
        slot.astype(jnp.int32)[None, :],
    )
    # (f*B+v, s*6+j) -> (s, f*B+v, j), summing hi/lo halves
    un = packed.reshape(d_pad * b, num_slots, 6)
    out = jnp.transpose(un[..., :3] + un[..., 3:], (1, 0, 2))
    return out[:, : d * b]


def _multi_plane_scatter(
    bins: jnp.ndarray, stats: jnp.ndarray, slot: jnp.ndarray, num_slots: int,
    num_bins: int = NUM_BINS,
) -> jnp.ndarray:
    n, d = bins.shape
    b = num_bins
    plane_idx = (jnp.arange(d, dtype=jnp.int32) * b)[None, :] + bins   # (n, d)
    flat = slot[:, None] * (d * b) + plane_idx
    oob = (
        (bins < 0) | (bins >= b) | (slot[:, None] < 0) | (slot[:, None] >= num_slots)
    )
    flat = jnp.where(oob, num_slots * d * b, flat)
    contrib = jnp.broadcast_to(stats[:, None, :], (n, d, 3))
    out = (
        jnp.zeros((num_slots * d * b, 3), jnp.float32)
        .at[flat]
        .add(contrib, mode="drop")
    )
    return out.reshape(num_slots, d * b, 3)


def multi_plane_histogram(
    bins: jnp.ndarray,
    stats: jnp.ndarray,
    slot: jnp.ndarray,
    num_slots: int,
    num_bins: int = NUM_BINS,
    mesh=None,
    shard_axis: str | None = None,
) -> jnp.ndarray:
    """Histogram planes for MANY leaves in one pass over the rows.

    ``slot``: (n,) int leaf-plane index per row; out-of-range = the row
    contributes to no plane. Returns (num_slots, d*NUM_BINS, 3). This is
    the depthwise grower's workhorse: one row pass per LEVEL instead of
    one per leaf, with the bin one-hot (the VPU-bound part) amortized
    across all the level's leaves. ``mesh``/``shard_axis`` as in
    :func:`plane_histogram` (per-shard kernel + psum of the cube).

    When the slot count is so large that no feature block fits the
    kernel's VMEM budget (thousands of planes at 256 bins — see
    :func:`_multi_df`), the scatter lowering is used regardless of
    backend: slower, but it compiles instead of tripping Mosaic's
    scoped-VMEM ceiling."""
    df_fit = _multi_df(num_slots, num_bins, bins.shape[1])
    if df_fit is not None and _rows_sharded(mesh, shard_axis) and _pallas_enabled():
        from jax.sharding import PartitionSpec as P

        def local(b, s, sl):
            cube = _multi_plane_pallas(
                b.astype(jnp.int32), s, sl.astype(jnp.int32), num_slots,
                num_bins, df=df_fit,
            )
            return jax.lax.psum(cube, shard_axis)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(P(shard_axis, None), P(shard_axis, None), P(shard_axis)),
            out_specs=P(),
            check_vma=False,
        )(bins, stats, slot)
    if df_fit is not None and use_pallas():
        return _multi_plane_pallas(
            bins.astype(jnp.int32), stats, slot.astype(jnp.int32), num_slots,
            num_bins, df=df_fit,
        )
    # scatter path; under a sharded trace GSPMD partitions the scatter
    # and inserts the allreduce automatically
    return _multi_plane_scatter(
        bins.astype(jnp.int32), stats, slot.astype(jnp.int32), num_slots,
        num_bins,
    )


def _plane_histogram_scatter(
    bins: jnp.ndarray, stats: jnp.ndarray, num_bins: int = NUM_BINS
) -> jnp.ndarray:
    n, d = bins.shape
    b = num_bins
    plane_idx = (jnp.arange(d, dtype=jnp.int32) * b)[None, :] + bins  # (n, d)
    # out-of-range bins contribute nowhere (a negative bin would otherwise
    # alias into the previous feature's stripe; matches the Pallas lowering)
    plane_idx = jnp.where((bins >= 0) & (bins < b), plane_idx, d * b)
    contrib = jnp.broadcast_to(stats[:, None, :], (n, d, 3))
    return (
        jnp.zeros((d * b, 3), jnp.float32).at[plane_idx].add(contrib, mode="drop")
    )


def _plane_histogram_shard_map(
    bins: jnp.ndarray, stats: jnp.ndarray, mesh, shard_axis: str,
    num_bins: int,
) -> jnp.ndarray:
    """Per-shard Pallas kernel + explicit psum of the planes — LightGBM
    data_parallel's per-iteration histogram allreduce over ICI
    (TrainUtils.scala:496-512), MXU kernel intact on every chip."""
    from jax.sharding import PartitionSpec as P

    def local(b: jnp.ndarray, s: jnp.ndarray) -> jnp.ndarray:
        h = _plane_histogram_pallas(b.astype(jnp.int32), s, num_bins)
        return jax.lax.psum(h, shard_axis)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(shard_axis, None), P(shard_axis, None)),
        out_specs=P(),
        check_vma=False,
    )(bins, stats)


def plane_histogram(
    bins: jnp.ndarray, stats: jnp.ndarray, mask: jnp.ndarray | None = None,
    num_bins: int = NUM_BINS, mesh=None, shard_axis: str | None = None,
) -> jnp.ndarray:
    """(d * NUM_BINS, 3) gradient-histogram plane of the masked rows.

    ``bins``: (n, d) int bin codes; ``stats``: (n, 3) per-row (g, h, count);
    ``mask``: optional (n,) row selector (0 rows contribute nothing).
    ``mesh``/``shard_axis``: when the rows are sharded over that mesh axis,
    run the Pallas kernel per shard under shard_map and psum the planes
    (falls back to the GSPMD-partitioned scatter when Pallas is off).
    """
    if mask is not None:
        stats = stats * mask[:, None]
    if _rows_sharded(mesh, shard_axis) and _pallas_enabled():
        return _plane_histogram_shard_map(
            bins, stats, mesh, shard_axis, num_bins
        )
    if use_pallas():
        return _plane_histogram_pallas(bins.astype(jnp.int32), stats, num_bins)
    return _plane_histogram_scatter(bins.astype(jnp.int32), stats, num_bins)
