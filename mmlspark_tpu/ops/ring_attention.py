"""Ring attention — sequence-parallel exact attention over the mesh.

The long-context primitive (SURVEY §5.7): when a sequence is sharded over
a mesh axis, each device holds one Q/K/V block and K/V blocks rotate
around the ring with ``lax.ppermute`` (one neighbor hop per step — the
collective rides ICI). Per-block scores fold into the running output with
the online-softmax update (running max + rescaled accumulator), so the
result is EXACT attention over the full sequence while no device ever
materializes more than its own block pair — memory O(seq/devices) per
device, communication seq_len * d_model per ring lap.

This is the jax expression of Ring Attention (Liu et al. 2023) /
blockwise-parallel attention; causal masking uses global block offsets so
the rotated blocks mask correctly. Single-device meshes degenerate to
plain (still blockwise-stable) attention.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.parallel.compat import shard_map
from mmlspark_tpu.parallel.mesh import get_mesh

SEQ_AXIS = "data"  # default: ride the batch axis of the standard mesh


def _block_attend(
    q: jnp.ndarray,          # (B, Tq, H, D)
    k: jnp.ndarray,          # (B, Tk, H, D)
    v: jnp.ndarray,          # (B, Tk, H, D)
    o: jnp.ndarray,          # (B, Tq, H, D) running (unnormalized) output
    m: jnp.ndarray,          # (B, Tq, H) running max
    l: jnp.ndarray,          # (B, Tq, H) running sum
    q_off: jnp.ndarray,      # scalar: global offset of this q block
    k_off: jnp.ndarray,      # scalar: global offset of this k block
    scale: float,
    causal: bool,
    kv_mask: Optional[jnp.ndarray] = None,  # (B, Tk) bool; False = pad key
) -> tuple:
    """Fold one K/V block into the online-softmax accumulators."""
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * scale  # (B, Tq, H, Tk)
    if causal:
        qi = q_off + jnp.arange(q.shape[1])
        ki = k_off + jnp.arange(k.shape[1])
        mask = qi[:, None] >= ki[None, :]            # (Tq, Tk)
        s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
    if kv_mask is not None:
        # padding keys receive no attention; the accumulator math below
        # already tolerates fully-masked blocks (running max stays -inf)
        s = jnp.where(kv_mask[:, None, None, :], s, -jnp.inf)
    blk_m = s.max(axis=-1)                           # (B, Tq, H)
    new_m = jnp.maximum(m, blk_m)
    # fully-masked blocks: new_m stays -inf; exp(-inf - -inf) guards below
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    new_l = l * corr + p.sum(axis=-1)
    new_o = o * corr[..., None] + jnp.einsum("bqhk,bkhd->bqhd", p, v)
    return new_o, new_m, new_l


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Optional[Any] = None,
    axis: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Exact attention with the SEQUENCE dim sharded over ``mesh[axis]``.

    ``q``/``k``/``v``: (batch, seq, heads, head_dim), seq sharded over the
    axis (shard_map reshards if needed). Returns the attention output in
    the same layout/sharding. ``causal=True`` applies the autoregressive
    mask with GLOBAL positions (each shard knows its ring offset).
    ``kv_mask``: optional (batch, seq) bool — False keys receive no
    attention. This is how padded sequences shard cleanly: pad to a
    multiple of the axis size, mask the tail (the pad mask rides the
    same ring rotation as its K/V block)."""
    mesh = mesh or get_mesh()
    n_shards = dict(mesh.shape).get(axis, 1)
    sc = scale if scale is not None else q.shape[-1] ** -0.5
    has_mask = kv_mask is not None

    def local(ql, kl, vl, mk) -> jnp.ndarray:
        B, Tq, H, D = ql.shape
        my = jax.lax.axis_index(axis)
        o = jnp.zeros_like(ql)
        m = jnp.full((B, Tq, H), -jnp.inf, ql.dtype)
        l = jnp.zeros((B, Tq, H), ql.dtype)
        q_off = my * Tq

        def step(i: int, carry: tuple) -> tuple:
            o, m, l, kc, vc, mc = carry
            # the block currently held arrived from shard (my + i) % n
            src = (my + i) % n_shards
            o, m, l = _block_attend(
                ql, kc, vc, o, m, l, q_off, src * kc.shape[1], sc, causal,
                mc,
            )
            # rotate K/V (and the pad mask) one hop around the ring
            perm = [(j, (j - 1) % n_shards) for j in range(n_shards)]
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            if mc is not None:
                mc = jax.lax.ppermute(mc, axis, perm)
            return o, m, l, kc, vc, mc

        # n-1 rotated steps; the LAST block attends outside the loop so the
        # ring never pays a final hop whose result would be discarded
        o, m, l, kc, vc, mc = jax.lax.fori_loop(
            0, n_shards - 1, step, (o, m, l, kl, vl, mk)
        )
        last_src = (my + n_shards - 1) % n_shards
        o, m, l = _block_attend(
            ql, kc, vc, o, m, l, q_off, last_src * kc.shape[1], sc, causal,
            mc,
        )
        # rows with no visible keys (can't happen with causal diag) -> 0
        return o / jnp.maximum(l, 1e-30)[..., None]

    if n_shards == 1:
        # degenerate single-shard mesh: same math, no collectives
        B, T, H, D = q.shape
        o = jnp.zeros_like(q)
        m = jnp.full((B, T, H), -jnp.inf, q.dtype)
        l = jnp.zeros((B, T, H), q.dtype)
        o, m, l = _block_attend(
            q, k, v, o, m, l, jnp.int32(0), jnp.int32(0), sc, causal,
            kv_mask,
        )
        return o / jnp.maximum(l, 1e-30)[..., None]

    spec = P(None, axis, None, None)
    mspec = P(None, axis)
    if has_mask:
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec, spec, mspec),
            out_specs=spec,
            check_vma=False,
        )(q, k, v, kv_mask)
    return shard_map(
        lambda a, b, c: local(a, b, c, None),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def dense_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    causal: bool = False, scale: Optional[float] = None,
    kv_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Reference single-device attention (the golden for ring tests)."""
    sc = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k) * sc
    if causal:
        T, S = s.shape[1], s.shape[3]
        mask = jnp.arange(T)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p, v)
