// Native host kernels for mmlspark_tpu.
//
// The reference ships its native engines as prebuilt JNI jars
// (build.sbt:32-39); this library is the equivalent host-side native layer
// for the TPU framework: hot host loops (hashing, CSV parsing, feature
// binning) that feed device programs. Built by ops/native_loader.py with
// g++ -O3.

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <thread>
#include <vector>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6b;
  h ^= h >> 13;
  h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

// Canonical MurmurHash3_x86_32.
static uint32_t murmur3_32(const uint8_t* data, int32_t len, uint32_t seed) {
  const int nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51;
  const uint32_t c2 = 0x1b873593;

  const uint32_t* blocks = (const uint32_t*)(data);
  for (int i = 0; i < nblocks; i++) {
    uint32_t k1;
    memcpy(&k1, blocks + i, 4);
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= tail[1] << 8; [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= (uint32_t)len;
  return fmix32(h1);
}

static int n_threads_for(int64_t work) {
  unsigned hw = std::thread::hardware_concurrency();
  if (hw == 0) hw = 4;
  int64_t by_work = work / 16384;  // don't spawn threads for tiny jobs
  if (by_work < 1) by_work = 1;
  return (int)(by_work < (int64_t)hw ? by_work : (int64_t)hw);
}

extern "C" {

void mml_murmur3_batch(const char** strings, const int32_t* lengths,
                       int64_t n, uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = murmur3_32((const uint8_t*)strings[i], lengths[i], seed);
  }
}

// Feature binning (LightGBM BinMapper.transform hot loop): for each cell,
// out = 1 + (# edges < value), NaN -> 0 (missing bin). `edges` is the
// concatenation of per-feature ascending edge arrays; `edge_offsets` has
// d+1 entries delimiting them. Row-major x (n, d), threads split rows.
void mml_bin_features(const float* x, int64_t n, int64_t d,
                      const double* edges, const int64_t* edge_offsets,
                      uint8_t* out) {
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++) {
      const float* row = x + r * d;
      uint8_t* orow = out + r * d;
      for (int64_t f = 0; f < d; f++) {
        float v = row[f];
        if (std::isnan(v)) {
          orow[f] = 0;
          continue;
        }
        const double* e = edges + edge_offsets[f];
        int64_t m = edge_offsets[f + 1] - edge_offsets[f];
        // branchless-ish binary search: first index with e[idx] >= v
        int64_t lo_i = 0, hi_i = m;
        while (lo_i < hi_i) {
          int64_t mid = (lo_i + hi_i) >> 1;
          if (e[mid] < (double)v) lo_i = mid + 1; else hi_i = mid;
        }
        orow[f] = (uint8_t)(lo_i + 1);
      }
    }
  };
  int t = n_threads_for(n * d);
  if (t <= 1) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + t - 1) / t;
  for (int i = 0; i < t; i++) {
    int64_t lo = i * chunk, hi = lo + chunk;
    if (lo >= n) break;
    if (hi > n) hi = n;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// C-locale handle so float parsing ignores the process's LC_NUMERIC.
static locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
  return loc;
}

// Parse one bounded field [fs, fe) as a double; whitespace-only or
// non-numeric -> NaN. Copies into a stack buffer (heap for over-long
// fields) so strtod can never walk past the field (newlines, next row)
// and long numeric literals parse exactly like the Python fallback.
// strtod accepted a prefix; the whole field must be consumed (bar
// trailing whitespace) or it's not a number — matches float() semantics.
static inline bool only_ws_after(const char* p) {
  while (*p == ' ' || *p == '\t' || *p == '\r') p++;
  return *p == '\0';
}

static double parse_field(const char* fs, const char* fe) {
  char buf[64];
  size_t flen = (size_t)(fe - fs);
  if (flen == 0) return NAN;
  char* fend = nullptr;
  if (flen < sizeof(buf)) {
    memcpy(buf, fs, flen);
    buf[flen] = '\0';
    double v = strtod_l(buf, &fend, c_locale());
    if (fend == buf || !only_ws_after(fend)) return NAN;
    return v;
  }
  std::string big(fs, flen);
  double v = strtod_l(big.c_str(), &fend, c_locale());
  if (fend == big.c_str() || !only_ws_after(fend)) return NAN;
  return v;
}

static inline bool is_ws(char ch) { return ch == ' ' || ch == '\t' || ch == '\r'; }

// Numeric CSV parse: comma-separated float rows, '\n' terminated. Empty or
// unparseable fields become NaN; whitespace-only lines are skipped (matching
// mml_csv_dims). Returns rows actually parsed; the caller sizes `out` as
// n_rows * n_cols from a prior mml_csv_dims call.
int64_t mml_parse_csv(const char* buf, int64_t len, int64_t n_cols,
                      double* out, int64_t max_rows) {
  int64_t row = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end && row < max_rows) {
    // skip whitespace-only lines
    const char* probe = p;
    while (probe < end && is_ws(*probe)) probe++;
    if (probe < end && *probe == '\n') {
      p = probe + 1;
      continue;
    }
    if (probe >= end) break;
    double* orow = out + row * n_cols;
    for (int64_t c = 0; c < n_cols; c++) {
      if (p >= end || *p == '\n') {
        orow[c] = NAN;  // short row: pad
        continue;
      }
      const char* fs = p;
      while (p < end && *p != ',' && *p != '\n') p++;
      const char* fe = p;
      while (fe > fs && is_ws(fe[-1])) fe--;  // trim trailing \r / spaces
      orow[c] = parse_field(fs, fe);
      if (p < end && *p == ',') p++;
    }
    // consume to end of line (extra fields beyond n_cols are dropped)
    while (p < end && *p != '\n') p++;
    if (p < end) p++;
    row++;
  }
  return row;
}

// Count rows (lines with non-whitespace content) and columns (commas in the
// first data line + 1).
void mml_csv_dims(const char* buf, int64_t len, int64_t* n_rows,
                  int64_t* n_cols) {
  int64_t rows = 0, cols = 1;
  bool first_line = true, line_has_data = false;
  for (int64_t i = 0; i < len; i++) {
    char ch = buf[i];
    if (ch == '\n') {
      if (line_has_data) {
        rows++;
        first_line = false;
      }
      line_has_data = false;
    } else if (!is_ws(ch)) {
      line_has_data = true;
      if (first_line && ch == ',') cols++;
    }
  }
  if (line_has_data) rows++;
  *n_rows = rows;
  *n_cols = cols;
}

}  // extern "C"
