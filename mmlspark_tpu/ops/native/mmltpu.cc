// Native host kernels for mmlspark_tpu.
//
// The reference ships its native engines as prebuilt JNI jars
// (build.sbt:32-39); this library is the equivalent host-side native layer
// for the TPU framework: hot host loops (hashing, CSV parse, binning) that
// feed device programs. Built by ops/native_loader.py with g++ -O3.

#include <cstdint>
#include <cstring>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6b;
  h ^= h >> 13;
  h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

// Canonical MurmurHash3_x86_32.
static uint32_t murmur3_32(const uint8_t* data, int32_t len, uint32_t seed) {
  const int nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51;
  const uint32_t c2 = 0x1b873593;

  const uint32_t* blocks = (const uint32_t*)(data);
  for (int i = 0; i < nblocks; i++) {
    uint32_t k1;
    memcpy(&k1, blocks + i, 4);
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= tail[1] << 8; [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= (uint32_t)len;
  return fmix32(h1);
}

extern "C" {

void mml_murmur3_batch(const char** strings, const int32_t* lengths,
                       int64_t n, uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = murmur3_32((const uint8_t*)strings[i], lengths[i], seed);
  }
}

}  // extern "C"
