from mmlspark_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    cluster_summary,
    data_sharding,
    device_count,
    get_mesh,
    make_mesh,
    replicated,
    set_mesh,
)
from mmlspark_tpu.parallel.sharding import pad_batch, replicate, shard_batch
from mmlspark_tpu.parallel import collectives, distributed

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "get_mesh",
    "set_mesh",
    "device_count",
    "cluster_summary",
    "data_sharding",
    "replicated",
    "pad_batch",
    "shard_batch",
    "replicate",
    "collectives",
    "distributed",
]
