"""Batch/param placement helpers for SPMD execution.

Where the reference broadcasts native models to executors and maps rows per
partition (cntk/CNTKModel.scala:411-413,515-520), here weights are
*replicated* onto the mesh once and batches are *batch-sharded* over the
``data`` axis; XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mmlspark_tpu.parallel.mesh import DATA_AXIS, get_mesh


def pad_batch(arr: np.ndarray, multiple: int) -> tuple:
    """Pad axis 0 up to a multiple (fixed shapes avoid XLA recompiles — the
    load-bearing TPU analogue of FixedMiniBatchTransformer). Returns
    (padded, real_n)."""
    n = arr.shape[0]
    target = max(multiple, ((n + multiple - 1) // multiple) * multiple)
    if target == n:
        return arr, n
    pad_width = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width), n


def shard_batch(tree: Any, mesh: Optional[Mesh] = None, axis: str = DATA_AXIS) -> Any:
    """Place a pytree of host arrays batch-sharded over the mesh.

    Axis-0 of every leaf must divide by the mesh axis size (use
    ``pad_batch`` first)."""
    mesh = mesh or get_mesh()

    def put(x: Any) -> Any:
        x = np.asarray(x)
        sh = NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
        return jax.device_put(x, sh)

    return jax.tree_util.tree_map(put, tree)


def replicate(tree: Any, mesh: Optional[Mesh] = None) -> Any:
    """Replicate a pytree (weights) across the mesh — the broadcast analogue."""
    mesh = mesh or get_mesh()
    sh = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)
