"""Batch/param placement helpers for SPMD execution.

Where the reference broadcasts native models to executors and maps rows per
partition (cntk/CNTKModel.scala:411-413,515-520), here weights are
*replicated* onto the mesh once and batches are *batch-sharded* over the
``data`` axis; XLA inserts the collectives.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mmlspark_tpu.parallel.mesh import DATA_AXIS, get_mesh


def pad_batch(arr: np.ndarray, multiple: int) -> tuple:
    """Pad axis 0 up to a multiple (fixed shapes avoid XLA recompiles — the
    load-bearing TPU analogue of FixedMiniBatchTransformer). Returns
    (padded, real_n)."""
    n = arr.shape[0]
    target = max(multiple, ((n + multiple - 1) // multiple) * multiple)
    if target == n:
        return arr, n
    pad_width = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width), n


def shard_batch(tree: Any, mesh: Optional[Mesh] = None, axis: str = DATA_AXIS) -> Any:
    """Place a pytree of host arrays batch-sharded over the mesh.

    Axis-0 of every leaf must divide by the mesh axis size (use
    ``pad_batch`` first)."""
    mesh = mesh or get_mesh()

    def put(x: Any) -> Any:
        x = np.asarray(x)
        sh = NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
        return jax.device_put(x, sh)

    return jax.tree_util.tree_map(put, tree)


def multihost_pad_target(n_local: int) -> int:
    """Common per-process row count so every process contributes an equal
    shard to a global array: max local count across processes, rounded up
    to the local device count. Assumes the data axis spans all devices
    (the default ``get_mesh()`` layout)."""
    import jax.experimental.multihost_utils as mhu

    counts = mhu.process_allgather(np.asarray([n_local], np.int64))
    ldc = jax.local_device_count()
    m = int(np.max(counts))
    return ((m + ldc - 1) // ldc) * ldc


def shard_batch_multihost(
    tree: Any, mesh: Optional[Mesh] = None, axis: str = DATA_AXIS
) -> Any:
    """Process-LOCAL rows -> one global row-sharded array per leaf.

    Each process contributes its local block; the global shape stacks the
    blocks in process order (jax.make_array_from_process_local_data). The
    multi-host counterpart of :func:`shard_batch` — the reference's
    per-machine native dataset build before its socket allreduce
    (TrainUtils.scala:26-66)."""
    mesh = mesh or get_mesh()
    nproc = jax.process_count()

    def put(x: Any) -> Any:
        x = np.asarray(x)
        global_shape = (x.shape[0] * nproc,) + x.shape[1:]
        sh = NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))
        return jax.make_array_from_process_local_data(sh, x, global_shape=global_shape)

    return jax.tree_util.tree_map(put, tree)


def replicate(tree: Any, mesh: Optional[Mesh] = None) -> Any:
    """Replicate a pytree (weights) across the mesh — the broadcast analogue."""
    mesh = mesh or get_mesh()
    sh = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)
