"""Version-portable ``shard_map``.

The framework targets the modern top-level ``jax.shard_map`` (whose
replication-check kwarg is ``check_vma``); older jax releases (< 0.5,
including the baked-in toolchain here) only ship
``jax.experimental.shard_map.shard_map`` with the equivalent kwarg named
``check_rep``. Every sharded-program lowering (collectives.shard_apply,
the histogram plane psum, ring attention, the PV-Tree voting grower —
and through them distributed VW) was failing on old jax for this reason
alone; route all of them through this shim.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax


def axis_size(axis_name: str) -> int:
    """``jax.lax.axis_size`` (new jax) with the classic ``psum(1, axis)``
    fallback — a unit-literal psum constant-folds to the static size."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast(x: Any, axis_name: str, to: Optional[str] = None) -> Any:
    """``jax.lax.pcast`` (new jax varying-axis typing) — an identity on
    old jax, whose ``check_rep`` tracker does not type casts; pair with
    ``check_vma=False``/``check_rep=False`` shard_maps."""
    if hasattr(jax.lax, "pcast"):
        if to is not None:
            return jax.lax.pcast(x, axis_name, to=to)
        return jax.lax.pcast(x, axis_name)
    return x


def shard_map(
    f: Callable,
    mesh: Optional[Any] = None,
    in_specs: Any = None,
    out_specs: Any = None,
    check_vma: Optional[bool] = None,
    **kw: Any,
) -> Callable:
    if hasattr(jax, "shard_map"):
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    if check_vma is not None:
        kw["check_rep"] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )
