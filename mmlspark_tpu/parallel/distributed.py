"""Multi-host rendezvous and gang launch.

Replaces the reference's driver TCP rendezvous server
(LightGBMUtils.scala:116-185) and handshake protocol
(LightGBMConstants.scala:34-40, TrainUtils.scala:453-494) with
``jax.distributed`` over DCN: one coordinator address, every host calls
``initialize`` and the JAX runtime forms the global device mesh; SPMD
launch provides the gang semantics that the reference got from Spark
barrier execution mode (LightGBMBase.scala:122-131).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host gang. No-ops for single-process runs and when
    already initialized (so library code can call it unconditionally).

    Environment fallbacks (set by the launcher): MMLSPARK_TPU_COORDINATOR,
    MMLSPARK_TPU_NUM_PROCESSES, MMLSPARK_TPU_PROCESS_ID.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("MMLSPARK_TPU_COORDINATOR")
    if coordinator_address is None:
        _initialized = True  # single-host mode
        return
    num_processes = num_processes or int(os.environ.get("MMLSPARK_TPU_NUM_PROCESSES", "1"))
    process_id = (
        process_id
        if process_id is not None
        else int(os.environ.get("MMLSPARK_TPU_PROCESS_ID", "0"))
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def is_coordinator() -> bool:
    return jax.process_index() == 0


def barrier(name: str = "mmlspark_tpu_barrier") -> None:
    """Host-level sync point. On multi-host this rides a tiny psum over the
    global mesh; single-host it is a no-op."""
    if jax.process_count() == 1:
        return
    import jax.numpy as jnp

    # A cross-host collective is the barrier: every host must contribute.
    jax.block_until_ready(
        jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            jnp.ones((jax.local_device_count(),))
        )
    )
