"""Multi-host rendezvous and gang launch.

Replaces the reference's driver TCP rendezvous server
(LightGBMUtils.scala:116-185) and handshake protocol
(LightGBMConstants.scala:34-40, TrainUtils.scala:453-494) with
``jax.distributed`` over DCN: one coordinator address, every host calls
``initialize`` and the JAX runtime forms the global device mesh; SPMD
launch provides the gang semantics that the reference got from Spark
barrier execution mode (LightGBMBase.scala:122-131).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional, Sequence

import jax

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults

_initialized = False

_M_BARRIER_WAIT = obs.histogram(
    "mmlspark_parallel_barrier_wait_seconds",
    "Time spent inside gang barriers, by barrier name", labels=("name",),
)
_M_BARRIER_TIMEOUTS = obs.counter(
    "mmlspark_parallel_barrier_timeouts_total",
    "Barriers abandoned by timeout", labels=("name",),
)


class BarrierTimeoutError(TimeoutError):
    """A gang sync point that did not complete in time — carries enough
    diagnostics to name the culprit instead of hanging forever."""

    def __init__(
        self,
        name: str,
        timeout_s: float,
        missing: Sequence[str] = (),
        process_index: int = 0,
        process_count: int = 1,
    ):
        self.name = name
        self.timeout_s = timeout_s
        self.missing = list(missing)
        msg = (
            f"barrier {name!r} timed out after {timeout_s:g}s on process "
            f"{process_index}/{process_count}"
        )
        if self.missing:
            msg += f"; missing hosts: {', '.join(self.missing)}"
        else:
            msg += (
                "; no roster provided — pass expected=/alive= to barrier() "
                "to identify the missing host"
            )
        super().__init__(msg)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-host gang. No-ops for single-process runs and when
    already initialized (so library code can call it unconditionally).

    Environment fallbacks (set by the launcher): MMLSPARK_TPU_COORDINATOR,
    MMLSPARK_TPU_NUM_PROCESSES, MMLSPARK_TPU_PROCESS_ID.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("MMLSPARK_TPU_COORDINATOR")
    if coordinator_address is None:
        _initialized = True  # single-host mode
        return
    num_processes = num_processes or int(os.environ.get("MMLSPARK_TPU_NUM_PROCESSES", "1"))
    process_id = (
        process_id
        if process_id is not None
        else int(os.environ.get("MMLSPARK_TPU_PROCESS_ID", "0"))
    )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def is_coordinator() -> bool:
    return jax.process_index() == 0


def _barrier_collective() -> None:
    if jax.process_count() == 1:
        return
    import jax.numpy as jnp

    # A cross-host collective is the barrier: every host must contribute.
    jax.block_until_ready(
        jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
            jnp.ones((jax.local_device_count(),))
        )
    )


def barrier(
    name: str = "mmlspark_tpu_barrier",
    timeout_s: Optional[float] = None,
    expected: Optional[Sequence[str]] = None,
    alive: Optional[Callable[[], Sequence[str]]] = None,
) -> None:
    """Host-level sync point. On multi-host this rides a tiny psum over the
    global mesh; single-host it is a no-op.

    ``timeout_s``: instead of blocking forever on a slow/dead host (the
    failure the reference's Spark barrier stage would eventually kill),
    raise :class:`BarrierTimeoutError` after this many seconds. The
    abandoned collective keeps waiting on a daemon thread — XLA offers no
    cancellation — but the caller gets control back with a diagnosis.

    ``expected``/``alive``: optional roster for the diagnosis — the full
    gang's host names and a callable returning the currently-live ones
    (e.g. a TTL'd DriverRegistry roster, serving/registry.py); the error
    then names exactly which hosts never arrived.

    Fault point ``parallel.barrier``: an injected delay simulates the slow
    host; an injected error simulates local rendezvous failure."""

    def _wait() -> None:
        faults.inject("parallel.barrier", context={"name": name})
        _barrier_collective()

    t0 = time.perf_counter()

    def _observe() -> None:
        _M_BARRIER_WAIT.labels(name=name).observe(time.perf_counter() - t0)

    if timeout_s is None:
        with obs.span("parallel.barrier"):
            _wait()
        _observe()
        return
    done = threading.Event()
    errs: list = []

    def _run() -> None:
        try:
            _wait()
        except BaseException as e:  # noqa: BLE001 — relayed to the caller
            errs.append(e)
        finally:
            done.set()

    threading.Thread(
        target=_run, name=f"barrier-{name}", daemon=True
    ).start()
    if not done.wait(timeout_s):
        _M_BARRIER_TIMEOUTS.labels(name=name).inc()
        _observe()  # the timeout IS the observed wait — the tail must show
        missing: list = []
        if expected is not None and alive is not None:
            try:
                missing = sorted(set(expected) - set(alive()))
            except Exception:  # noqa: BLE001 — roster is best-effort
                missing = []
        raise BarrierTimeoutError(
            name, timeout_s, missing,
            process_index=jax.process_index(),
            process_count=jax.process_count(),
        )
    _observe()
    if errs:
        raise errs[0]
