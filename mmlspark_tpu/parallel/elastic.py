"""Elastic self-healing distributed training: survive host loss mid-round,
re-shard, and resume bit-identically.

The training-plane sibling of the serving supervisor (PR 5): the paper's
headline rebuild of LightGBM's gang-scheduled socket allreduce previously
died on the first lost host — ``barrier()`` named the missing process and
raised, and the run was over until a human restarted it from a checkpoint.
This module closes the detect -> react loop:

- **Gang membership** rides the existing DriverRegistry heartbeats: every
  trainer registers under ``<service>-gang`` and heartbeats; a host whose
  beats stop vanishes from the TTL'd roster.
- **Detection**: a lost host surfaces either as a TTL expiry seen at a
  round boundary (:meth:`GangContext.on_round`) or as a gang allreduce
  whose peer frames never arrive mid-round (the socket-level failure the
  reference's ``allreduce`` hit, recoverable here instead of fatal).
- **Reaction**: survivors abort the in-flight round (state through the
  last checkpoint stands), agree on a new epoch/world through a
  **registry-stamped generation** record, re-shard the data partitions
  contiguously over the shrunk gang, and resume from the latest round
  checkpoint — all in-process, no operator action.
- **Contract**: the resumed booster on ``k-1`` hosts is **bit-identical**
  to a fresh ``k-1``-host run started from that same checkpoint (the
  reshard snapshots the checkpoint it resumed from so the claim is
  auditable; tests/test_elastic.py proves it byte-for-byte).
- **Grow-back**: a supervisor-restarted host re-registers and rejoins at
  the next checkpoint boundary (generation bump with reason ``grow``)
  instead of being lost for the run.
- **Stragglers**: per-host round-time EWMAs ride the heartbeat payload;
  the generation coordinator flags sustained-slow hosts
  (:class:`StragglerTracker`) and can evict them through the same resize
  path (reason ``straggler``).

Data plane: within a generation the gang trains the existing GBDT loop
(``models/gbdt/train.py``, unsharded per host) with the PR-8 host growers'
histograms **summed across members** by :class:`TcpReducer` — the literal
LightGBM data-parallel pattern (local histogram + allreduce + identical
split decisions everywhere), carried over plain TCP so a dead peer is a
recoverable socket timeout, not an uncancellable XLA collective. Every
member grows the identical tree; the booster is SPMD-identical across the
gang.

Global row order is world-invariant: partitions are contiguous row blocks
of the common dataset and members take contiguous partition runs in
sorted-name order, so the gathered checkpoint scores mean the same thing
at every world size — the property the bit-identity contract rests on.

Fault points (docs/robustness.md): ``elastic.detect`` fires at every
detection check (a payload forces a named host "lost" without killing
anything), ``elastic.reshard`` as a reshard commit is attempted (an error
is "the coordinator refused", retried), ``train.round_abort`` as an
in-flight round is aborted (a delay stalls the abort -> reshard
turnaround, visible in the detection-latency metric).
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import socket
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

import numpy as np

from mmlspark_tpu import obs
from mmlspark_tpu.core import faults
from mmlspark_tpu.obs import watchdog
from mmlspark_tpu.parallel.distributed import BarrierTimeoutError

_M_GEN = obs.gauge(
    "mmlspark_elastic_generation_count",
    "Current training-gang generation (bumped by every reshard)",
)
_M_MEMBERS = obs.gauge(
    "mmlspark_elastic_members_count", "Live members of the training gang",
)
_M_RESHARDS = obs.counter(
    "mmlspark_elastic_reshards_total",
    "Generation bumps: world changed and partitions were re-assigned",
    labels=("reason",),
)
_M_DETECT = obs.histogram(
    "mmlspark_elastic_detect_seconds",
    "Host-loss detection latency: last heartbeat seen -> loss declared",
)
_M_ROUND_EWMA = obs.gauge(
    "mmlspark_elastic_round_ewma_seconds",
    "Per-host boosting-round wall-time EWMA (straggler signal)",
    labels=("host",),
)
_M_STRAGGLERS = obs.gauge(
    "mmlspark_elastic_stragglers_count",
    "Members currently flagged sustained-slow by the coordinator",
)
_M_ABORTS = obs.counter(
    "mmlspark_elastic_round_aborts_total",
    "In-flight rounds abandoned because the gang changed under them",
)
_M_ALLREDUCE = obs.histogram(
    "mmlspark_elastic_allreduce_seconds",
    "Gang histogram-allreduce wall time (ring reduce-scatter + "
    "allgather by default; mode=mesh keeps the full-mesh baseline)",
)
_M_CRC_DROPS = obs.counter(
    "mmlspark_elastic_crc_failures_total",
    "Allreduce frames dropped because their payload CRC32 did not match "
    "— wire corruption detected instead of silently summed",
)
_M_RETRANSMITS = obs.counter(
    "mmlspark_elastic_retransmits_total",
    "Allreduce frames re-sent after a peer's corruption NACK",
)
_M_RING_STEPS = obs.counter(
    "mmlspark_elastic_ring_steps_total",
    "Ring-collective steps executed (each moves O(payload/world) bytes)",
    labels=("phase",),
)
_M_PAYLOAD_BYTES = obs.counter(
    "mmlspark_elastic_payload_bytes_total",
    "Allreduce payload bytes put on the wire (frame heads excluded)",
    labels=("mode",),
)
_M_OVERLAP_BLOCKS = obs.counter(
    "mmlspark_elastic_overlap_blocks_total",
    "Histogram feature blocks built while an earlier block's allreduce "
    "was in flight (the compute/communication pipeline)",
)
_M_VOTE_ROUNDS = obs.counter(
    "mmlspark_elastic_vote_rounds_total",
    "Voting-parallel exchanges: a (d,) ballot sum + top-2K candidate "
    "columns instead of the full histogram plane",
)
_M_PARKS = obs.counter(
    "mmlspark_elastic_parks_total",
    "Members that parked (stopped training, kept heartbeating) because "
    "they lost registry quorum or lost a generation CAS race — the "
    "minority side of a partition parking instead of split-braining",
    labels=("reason",),
)
_M_FENCED = obs.counter(
    "mmlspark_elastic_fenced_writes_total",
    "Writes refused because the writer's adopted epoch was superseded "
    "(a fenced-out zombie cannot persist, publish, or advertise)",
    labels=("plane",),
)


# -- the allreduce wire frame --------------------------------------------------
#
# v2 head (32 bytes): gen(q) seq(q) nonce(I) crc(I) name_len(i) nbytes(i).
# ``crc`` is the payload's CRC32 — v1 (`<qqIii`) carried NO checksum, so
# one flipped bit on the wire was silently summed into every member's
# identical histograms (the worst possible failure: bit-identical and
# wrong everywhere). A receiver that sees a CRC mismatch DROPS the frame,
# counts it, and answers with a NACK control frame (nbytes == -1, no
# payload); the sender retransmits from its recent-frame cache. A frame
# that stays missing past the allreduce timeout is the ordinary peer-loss
# path — corruption can delay a round or evict a peer, never corrupt a sum.
_FRAME_HEAD = "<qqIIii"
_FRAME_HEAD_LEN = struct.calcsize(_FRAME_HEAD)
_NACK_NBYTES = -1
# sanity bounds: a bit-flip inside the HEAD desyncs the stream — refuse
# to interpret absurd lengths and drop the connection instead (the
# sender reconnects; the frame re-requests or times out into peer-loss).
# 1 GiB is far above any real histogram frame but well below int32 max,
# so a high-bit flip in nbytes cannot command a giant blocking read
_MAX_NAME_LEN = 256
_MAX_FRAME_BYTES = 1 << 30


class HostLostError(RuntimeError):
    """A gang member stopped answering mid-run; carries the culprits."""

    def __init__(self, lost: list, gen: int = 0, detail: str = ""):
        self.lost = sorted(set(lost))
        self.gen = gen
        msg = (
            f"training gang generation {gen} lost host(s): "
            f"{', '.join(self.lost) or '?'}"
        )
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


class WorldChangedError(RuntimeError):
    """Another member committed a newer generation — re-form, don't die."""

    def __init__(self, gen: int):
        self.gen = gen
        super().__init__(f"training gang moved to generation {gen}")


class QuorumLostError(RuntimeError):
    """This member cannot reach a strict majority of the registries —
    it may be on the minority side of a partition. The only safe move is
    to PARK (stop training, keep heartbeating, commit nothing): a
    minority that reshards to its own world double-writes the epoch."""

    def __init__(self, detail: str = ""):
        super().__init__(
            "lost registry quorum" + (f": {detail}" if detail else "")
        )


class GenerationConflictError(RuntimeError):
    """A generation commit lost its compare-and-swap race: another
    member already committed a conflicting epoch. Carries the winning
    record (when the registry returned it) so the loser can park and
    rejoin the winning generation after heal."""

    def __init__(self, gen: int, current: Optional["Generation"] = None):
        self.gen = gen
        self.current = current
        msg = f"generation {gen} commit rejected by CAS"
        if current is not None:
            msg += (
                f" (registry holds gen {current.gen} "
                f"members={current.members})"
            )
        super().__init__(msg)


# -- deterministic partition assignment ---------------------------------------


def partition_bounds(n_rows: int, n_partitions: int) -> list:
    """Contiguous ``(lo, hi)`` row slices of the global dataset."""
    p = max(1, int(n_partitions))
    return [
        (i * n_rows // p, (i + 1) * n_rows // p) for i in range(p)
    ]


def assign_partitions(n_partitions: int, members: list) -> dict:
    """Member name -> list of partition ids. Members take CONTIGUOUS
    partition runs in sorted-name order, so the concatenation of every
    member's rows is the global dataset in its original order at every
    world size — the invariance the checkpoint bit-identity contract
    needs (a round-robin assignment would permute rows per world)."""
    names = sorted(members)
    m = len(names)
    out = {}
    for j, name in enumerate(names):
        out[name] = list(range(j * n_partitions // m,
                               (j + 1) * n_partitions // m))
    return out


def member_row_slice(
    n_rows: int, n_partitions: int, members: list, me: str
) -> tuple:
    """This member's contiguous ``(lo, hi)`` global row range."""
    parts = assign_partitions(n_partitions, members)[me]
    bounds = partition_bounds(n_rows, n_partitions)
    if not parts:
        return (0, 0)
    return (bounds[parts[0]][0], bounds[parts[-1]][1])


# -- straggler policy ---------------------------------------------------------


class StragglerTracker:
    """Flag members whose round-time EWMA stays ``factor`` x the gang
    median for ``sustain`` consecutive observations. Pure policy — the
    coordinator feeds it roster EWMAs and acts on the flags."""

    def __init__(self, factor: float = 3.0, sustain: int = 3):
        self.factor = float(factor)
        self.sustain = max(1, int(sustain))
        self._slow_streak: dict = {}

    def observe(self, ewmas: dict) -> list:
        """``{host: ewma_seconds}`` -> hosts flagged sustained-slow."""
        vals = [v for v in ewmas.values() if v and v > 0]
        if len(vals) < 2:
            self._slow_streak.clear()
            return []
        median = float(np.median(vals))
        flagged = []
        for host, v in ewmas.items():
            if v and median > 0 and v > self.factor * median:
                self._slow_streak[host] = self._slow_streak.get(host, 0) + 1
                if self._slow_streak[host] >= self.sustain:
                    flagged.append(host)
            else:
                self._slow_streak.pop(host, None)
        for host in list(self._slow_streak):
            if host not in ewmas:
                self._slow_streak.pop(host)
        return sorted(flagged)


# -- generation record over the registry --------------------------------------


@dataclass
class Generation:
    """One agreed (epoch, world): who trains, and from where."""

    gen: int
    members: list
    reason: str = "init"
    resume_round: int = 0
    snapshot: Optional[str] = None
    # content-addressed identity of the resume snapshot (serving/
    # artifacts.py): a member whose LOCAL disk lacks the snapshot path
    # pulls these exact bytes over HTTP from any advertising peer —
    # per-host checkpoint dirs stop being fatal
    snapshot_digest: Optional[str] = None
    committer: str = ""
    detect_latency_s: float = 0.0
    stamp: float = 0.0          # registry-side registration ts
    # straggler evictions: name -> boot stamp at eviction. Grow-back
    # re-admits an evicted host only once it re-registers with a NEW
    # boot (a restarted process gets a clean slate; the same slow
    # process does not bounce straight back in)
    evicted: dict = field(default_factory=dict)

def _post_json(url: str, payload: dict, timeout: float = 5.0) -> bool:
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    resp = send_request(
        HTTPRequestData(
            url, "POST", {"Content-Type": "application/json"},
            json.dumps(payload),
        ),
        timeout=timeout,
    )
    return resp["status_code"] == 200


def _post_json_status(
    url: str, payload: dict, timeout: float = 5.0
) -> tuple:
    """POST returning ``(status_code, decoded_body)`` — the CAS commit
    path needs the 409 body (it carries the winning record), not just a
    success bool."""
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    resp = send_request(
        HTTPRequestData(
            url, "POST", {"Content-Type": "application/json"},
            json.dumps(payload),
        ),
        timeout=timeout,
    )
    try:
        body = json.loads(resp["entity"])
    except (ValueError, TypeError):
        body = {}
    return resp["status_code"], body


def _generation_from_entry(e: dict) -> "Generation":
    """Roster generation entry (``host="generation"``) -> Generation."""
    return Generation(
        gen=int(e.get("port", 0)),
        members=list(e.get("members", [])),
        reason=e.get("reason", ""),
        resume_round=int(e.get("resume_round", 0)),
        snapshot=e.get("snapshot"),
        snapshot_digest=e.get("snapshot_digest"),
        committer=e.get("committer", ""),
        detect_latency_s=float(e.get("detect_latency_s", 0.0)),
        stamp=float(e.get("ts", 0.0)),
        evicted=dict(e.get("evicted") or {}),
    )


def _get_roster(url: str, timeout: float = 5.0) -> Optional[dict]:
    from mmlspark_tpu.io.clients import send_request
    from mmlspark_tpu.io.http_schema import HTTPRequestData

    resp = send_request(
        HTTPRequestData(url.rstrip("/") + "/", "GET"), timeout=timeout
    )
    if resp["status_code"] != 200:
        return None
    try:
        return json.loads(resp["entity"])
    except ValueError:
        return None


# -- gang membership ----------------------------------------------------------


class GangMember:
    """One training host's registry presence: heartbeat registration,
    TTL'd roster reads, and the registry-stamped generation record.

    The member's heartbeat carries its allreduce listener port and its
    round-time EWMA; it also re-posts the member's currently-adopted
    generation record each beat so the record outlives the registry TTL
    for as long as anyone still believes in it."""

    def __init__(
        self,
        registry_urls: Any,
        name: str,
        service: str = "train",
        advertise_host: str = "127.0.0.1",
        heartbeat_s: float = 1.0,
        artifact_store: Any = None,
        listen_port: int = 0,
        advertise_port: Optional[int] = None,
    ):
        """``artifact_store`` (serving/artifacts.py ArtifactStore): when
        given, this member also runs a tiny artifact ingress (ranged
        ``GET /artifacts/<digest>``) and advertises the store's contents
        on every heartbeat — checkpoint snapshots become pullable from
        any surviving peer, so the gang no longer needs a shared
        checkpoint directory.

        ``listen_port``/``advertise_port``: fix the allreduce listener
        port and/or advertise a DIFFERENT port on the roster — how a
        member's allreduce link is pointed through a chaos proxy (peers
        dial the advertised port; chaos/wire.py) or through real NAT."""
        from mmlspark_tpu.serving.fleet import split_registry_urls

        self.registry_urls = split_registry_urls(registry_urls)
        if not self.registry_urls:
            raise ValueError("elastic training needs at least one --registry")
        self.name = name
        self.service = service
        self.advertise_host = advertise_host
        self.heartbeat_s = float(heartbeat_s)
        self.boot = time.time()
        self.ewma_s = 0.0
        self.artifact_store = artifact_store
        self._artifact_srv: Any = None
        self.artifact_port: Optional[int] = None
        if artifact_store is not None:
            from mmlspark_tpu.serving import artifacts as artifacts_mod
            from mmlspark_tpu.serving.server import WorkerServer

            srv = WorkerServer(
                host="0.0.0.0", port=0, name=f"{service}-artifacts"
            )
            artifacts_mod.attach(srv, artifact_store)
            info = srv.start()
            self._artifact_srv = srv
            self.artifact_port = info.port
        self.last_seen: dict = {}   # member -> MONOTONIC ts last on roster
        self._adopted: Optional[Generation] = None
        # registry reachability (monotonic ts of each registry's last
        # answer): the quorum signal — a member whose majority-reachable
        # age exceeds ``quorum_grace_s`` is on the minority side of a
        # partition and must park rather than reshard
        self._reg_seen: dict = {}
        self._boot_mono = time.monotonic()
        self.quorum_grace_s = max(2.0, 5.0 * self.heartbeat_s)
        self.commit_acks = 0            # registries acking the last commit
        self.committed_gens: list = []  # gens THIS member CAS-committed
        self._stop = threading.Event()
        # allreduce frame listener (one across generations; the port is
        # what peers learn from the roster)
        self._inbox: dict = {}          # (gen, nonce, seq, sender) -> bytes
        self._inbox_cond = threading.Condition()
        # CRC accounting: frames dropped for checksum mismatch; the keys
        # stay recorded so the waiting allreduce re-NACKs until the
        # retransmit lands (a lost NACK must not strand the round)
        self.crc_drops = 0
        self._crc_dropped: set = set()
        # the active TcpReducer (if any): the read loop's back-channel
        # for NACK-triggered retransmits
        self._reducer: Any = None
        self._srv = socket.create_server(("0.0.0.0", int(listen_port)))
        self._srv.settimeout(0.5)
        self.port = self._srv.getsockname()[1]
        self.advertise_port = (
            int(advertise_port) if advertise_port else self.port
        )
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"gang-listen-{name}", daemon=True
        )
        self._accept_thread.start()
        self._beat_thread = threading.Thread(
            target=self._beat_loop, name=f"gang-beat-{name}", daemon=True
        )
        self._beat_thread.start()

    # -- listener ------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True
            ).start()

    def _read_loop(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(None)
            f = conn.makefile("rb")
            while not self._stop.is_set():
                head = f.read(_FRAME_HEAD_LEN)
                if len(head) < _FRAME_HEAD_LEN:
                    return
                gen, seq, nonce, crc, name_len, nbytes = struct.unpack(
                    _FRAME_HEAD, head
                )
                if not 0 < name_len <= _MAX_NAME_LEN or nbytes > \
                        _MAX_FRAME_BYTES or (
                            nbytes < 0 and nbytes != _NACK_NBYTES
                        ):
                    # a bit-flip inside the HEAD desyncs the stream:
                    # refuse to interpret garbage lengths — drop the
                    # connection (the sender reconnects; the missing
                    # frame re-requests or times out into peer-loss)
                    self.crc_drops += 1
                    _M_CRC_DROPS.inc()
                    return
                sender = f.read(name_len).decode("utf-8", "replace")
                if nbytes == _NACK_NBYTES:
                    # corruption NACK: the peer received our (gen, seq)
                    # frame torn — retransmit from the reducer's cache
                    red = self._reducer
                    if red is not None:
                        red.handle_nack(sender, gen, nonce, seq)
                    continue
                payload = f.read(nbytes)
                if len(payload) < nbytes:
                    return
                key = (gen, nonce, seq, sender)
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    # detected wire corruption: a dropped frame (and a
                    # NACK back), NEVER a silently wrong sum
                    self.crc_drops += 1
                    _M_CRC_DROPS.inc()
                    with self._inbox_cond:
                        self._crc_dropped.add(key)
                    red = self._reducer
                    if red is not None:
                        red.send_nack(sender, gen, nonce, seq)
                    continue
                with self._inbox_cond:
                    self._inbox[key] = payload
                    self._crc_dropped.discard(key)
                    self._inbox_cond.notify_all()
        except Exception:  # noqa: BLE001 — a dead peer's conn just ends
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def take_frame(
        self, gen: int, nonce: int, seq: int, sender: str, timeout_s: float
    ) -> Optional[bytes]:
        deadline = time.monotonic() + timeout_s
        with self._inbox_cond:
            while True:
                buf = self._inbox.pop((gen, nonce, seq, sender), None)
                if buf is not None:
                    return buf
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._inbox_cond.wait(min(remaining, 0.05))

    def drop_stale_frames(self, current_gen: int) -> None:
        with self._inbox_cond:
            for key in [k for k in self._inbox if k[0] < current_gen]:
                del self._inbox[key]
            for key in [k for k in self._crc_dropped if k[0] < current_gen]:
                self._crc_dropped.discard(key)

    def crc_dropped(self, key: tuple) -> bool:
        """Was ``(gen, nonce, seq, sender)`` dropped for a bad CRC (and
        not yet replaced by a clean retransmit)? The allreduce waiter
        re-NACKs such keys each roster check — a lost NACK must not
        strand the round until the timeout."""
        with self._inbox_cond:
            return key in self._crc_dropped

    def _attach_reducer(self, reducer: Any) -> None:
        self._reducer = reducer

    def _detach_reducer(self, reducer: Any) -> None:
        if self._reducer is reducer:
            self._reducer = None

    # -- registration ---------------------------------------------------------

    def _registration(self) -> dict:
        reg = {
            "name": f"{self.service}-gang",
            "host": self.name,
            "port": self.advertise_port,
            "addr": self.advertise_host,
            "boot": self.boot,
            "ewma_ms": round(self.ewma_s * 1e3, 3),
        }
        if self.artifact_store is not None:
            # advertise name@sha256 refs + the ingress serving them, so
            # peers resolve checkpoint pulls straight off the roster
            reg["artifact_port"] = self.artifact_port
            reg["artifacts"] = self.artifact_store.refs()
        return reg

    def artifact_peers(self, digest: str) -> list:
        """Gang members currently advertising ``digest`` -> artifact
        base URLs (the fetch failover order is sorted-name, matching the
        rest of the gang's determinism conventions)."""
        ros = self.roster() or {}
        suffix = "@" + digest
        peers = []
        for name in sorted(ros):
            if name == self.name:
                continue
            e = ros[name]
            port = e.get("artifact_port")
            if port and any(
                a.endswith(suffix) for a in e.get("artifacts") or ()
            ):
                peers.append(f"http://{e.get('addr', '127.0.0.1')}:{port}")
        return peers

    def artifact_holders(self, members: Any = None) -> list:
        """Gang members running an artifact ingress -> base URLs (the
        push targets for snapshot replicate-before-commit); ``members``
        narrows to a generation's roster. Unlike
        :meth:`artifact_peers`, holders need not already advertise a
        digest — they are where the digest is going."""
        ros = self.roster() or {}
        urls = []
        for name in sorted(ros):
            if name == self.name:
                continue
            if members is not None and name not in members:
                continue
            e = ros[name]
            port = e.get("artifact_port")
            if port:
                urls.append(f"http://{e.get('addr', '127.0.0.1')}:{port}")
        return urls

    def heartbeat(self) -> None:
        """One registration beat to every registry (also refreshes the
        adopted generation record's TTL).

        Conflict rule: the registry's copy of a generation is
        authoritative (last writer wins — one entry per gen number). If
        the current record for our adopted gen carries DIFFERENT members
        (racing survivors with divergent lost-sets each committed), we
        ADOPT the registry's copy instead of re-posting ours, so the
        record converges instead of flapping; the training loop notices
        the membership change at its next round boundary."""
        gen = self._adopted
        if gen is not None:
            cur = self.read_generation()
            if cur is not None and cur.gen >= gen.gen and (
                cur.gen > gen.gen
                or sorted(cur.members) != sorted(gen.members)
            ):
                self._adopted = gen = cur
        # explicit short per-call budget: a blackholed registry must cost
        # a bounded slice of the beat, never park the heartbeat thread
        # (pinned by the chaos-proxy blackhole test)
        from mmlspark_tpu.serving.fleet import beat_timeout

        timeout = beat_timeout(self.heartbeat_s, factor=2.0)
        for url in self.registry_urls:
            try:
                if _post_json(url, self._registration(), timeout=timeout):
                    self._reg_seen[url] = time.monotonic()
                if gen is not None:
                    # the registry monotone-guards generation re-posts: a
                    # 409 here means OUR copy is the superseded one (the
                    # heartbeat conflict rule above adopts the winner at
                    # the next beat) — never last-writer-wins
                    _post_json(url, self._gen_payload(gen), timeout=timeout)
            except Exception:  # noqa: BLE001 — registry may be restarting
                pass

    def _beat_loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat()
            self._stop.wait(self.heartbeat_s)

    def roster(self) -> Optional[dict]:
        """Live gang members (TTL-filtered by the registry): name ->
        entry, or **None when no registry answered** — blindness is not
        evidence of death (a restarting registry must not make every
        survivor declare every peer lost and split-brain the gang).
        Tracks ``last_seen`` MONOTONIC times for the loss grace and the
        detection-latency metric (wall clock steps must not distort
        either). The first live registry answers (registry HA)."""
        for url in self.registry_urls:
            data = _get_roster(url)
            if data is None:
                continue
            self._reg_seen[url] = time.monotonic()
            entries = {
                e.get("host"): e for e in data.get(f"{self.service}-gang", [])
            }
            now = time.monotonic()
            for host in entries:
                self.last_seen[host] = now
            return entries
        return None

    # -- registry quorum -------------------------------------------------------

    def majority(self) -> int:
        """Strict majority of the configured registries (majority-of-1
        for single-registry deployments)."""
        return len(self.registry_urls) // 2 + 1

    def quorum_age_s(self) -> float:
        """Seconds since a strict majority of the registries was last
        reachable from here (0-ish while healthy). The park trigger:
        an age beyond ``quorum_grace_s`` means this member may be on
        the minority side of a partition — it must stop training and
        commit nothing, because the majority side is entitled to
        declare it dead and reshard without it."""
        times = sorted(
            (self._reg_seen.get(u, self._boot_mono)
             for u in self.registry_urls),
            reverse=True,
        )
        return time.monotonic() - times[self.majority() - 1]

    # -- generation record -----------------------------------------------------

    def _gen_payload(self, g: Generation) -> dict:
        return {
            "name": f"{self.service}-gen",
            # the (host, port) identity key: one entry per generation,
            # re-posts replace (heartbeat refresh), max port wins on read
            "host": "generation",
            "port": int(g.gen),
            "members": list(g.members),
            "reason": g.reason,
            "resume_round": int(g.resume_round),
            "snapshot": g.snapshot,
            "snapshot_digest": g.snapshot_digest,
            "committer": g.committer,
            "detect_latency_s": g.detect_latency_s,
            "evicted": dict(g.evicted),
        }

    def declared_dead(
        self, candidates: list, ros: Optional[dict], grace_s: float
    ) -> list:
        """THE loss policy, shared by round-boundary detection and the
        allreduce wait (one implementation — the two sites must never
        drift): a candidate is dead only when the roster is NOT blind
        (some registry answered AND it has collected our own heartbeat
        — a freshly-restarted registry's empty roster is blindness, not
        mass death), the candidate is absent, and its last sighting is
        older than the grace (debounces the re-registration race).

        Sighting ages are MONOTONIC deltas: a wall-clock step (NTP slew,
        manual date set) must neither mass-declare death nor mask a real
        one — pinned by the clock-step test."""
        if not candidates or ros is None or self.name not in ros:
            return []
        now = time.monotonic()
        return [
            c for c in candidates
            if c not in ros
            and now - self.last_seen.get(c, self._boot_mono) >= grace_s
        ]

    def read_generation(self) -> Optional[Generation]:
        # consult EVERY answering registry and take the highest
        # generation (registry HA: a just-restarted registry may answer
        # with an empty roster while a peer still holds the record)
        entries: list = []
        for url in self.registry_urls:
            data = _get_roster(url)
            if data is None:
                continue
            self._reg_seen[url] = time.monotonic()
            entries.extend(data.get(f"{self.service}-gen", []))
        if entries:
            e = max(
                entries,
                key=lambda x: (x.get("port", 0), x.get("ts", 0.0)),
            )
            return _generation_from_entry(e)
        return None

    def commit_generation(
        self, g: Generation, expected_gen: Optional[int] = None,
    ) -> Generation:
        """Quorum compare-and-swap commit: POST the record to EVERY
        registry's ``/generation/commit`` with the predecessor claim
        (``expected_gen``, derived from the adopted generation when not
        given) and count acks. Succeeds only when a strict majority
        acks (majority-of-1 for single-registry fleets); raises

        - :class:`GenerationConflictError` when a registry rejects the
          CAS because a conflicting epoch already won (carries the
          winner so the loser can park and rejoin it), and
        - :class:`QuorumLostError` when fewer than a majority of
          registries ack — including the zero-ack case (a dead or
          partitioned registry list must never read as success; the
          old code swallowed every POST failure and proceeded as
          committed).
        """
        g.committer = self.name
        if expected_gen is None:
            if self._adopted is not None:
                expected_gen = int(self._adopted.gen)
            else:
                cur0 = self.read_generation()
                expected_gen = int(cur0.gen) if cur0 is not None else 0
        payload = {
            "name": f"{self.service}-gen",
            "gen": int(g.gen),
            "expected_gen": int(expected_gen),
            "record": self._gen_payload(g),
        }
        acks = 0
        conflict: Optional[Generation] = None
        conflict_gen = -1
        for url in self.registry_urls:
            try:
                status, body = _post_json_status(
                    url.rstrip("/") + "/generation/commit", payload
                )
            except Exception:  # noqa: BLE001 — unreachable: not an ack
                continue
            self._reg_seen[url] = time.monotonic()
            if status == 200:
                acks += 1
            elif status == 404:
                # pre-CAS registry: fall back to the plain roster POST
                try:
                    if _post_json(url, self._gen_payload(g)):
                        acks += 1
                except Exception:  # noqa: BLE001
                    pass
            elif status == 409:
                cur = body.get("current") if isinstance(body, dict) else None
                cg = int(body.get("current_gen", 0)) if isinstance(
                    body, dict
                ) else 0
                if cg > conflict_gen:
                    conflict_gen = cg
                    conflict = (
                        _generation_from_entry(cur) if cur else None
                    )
        self.commit_acks = acks
        if acks < self.majority():
            # a minority of acks is NOT a commit, whatever the mix of
            # rejections and silence — but a CAS rejection is the more
            # specific diagnosis (it carries the winning epoch to park
            # against); plain blindness is quorum loss
            if conflict_gen >= 0:
                raise GenerationConflictError(int(g.gen), conflict)
            raise QuorumLostError(
                f"generation {g.gen} commit acked by {acks} of "
                f"{len(self.registry_urls)} registries "
                f"(majority is {self.majority()})"
            )
        self.committed_gens.append(int(g.gen))
        self._adopted = g
        _M_GEN.set(g.gen)
        _M_MEMBERS.set(len(g.members))
        return g

    def adopt(self, g: Generation) -> None:
        self._adopted = g
        _M_GEN.set(g.gen)
        _M_MEMBERS.set(len(g.members))

    def fenced_out(self, plane: str) -> bool:
        """Is this member's adopted epoch superseded by a committed
        generation that EXCLUDES it? The committed gen is the fencing
        token: a fenced-out writer must refuse to persist or advertise
        on ``plane`` (counted in ``mmlspark_elastic_fenced_writes_total``)
        — a SIGSTOP'd zombie coordinator that wakes after the survivors
        resharded cannot roll the fleet back. Blindness is NOT fencing
        (the quorum park path owns that side); only a registry-confirmed
        newer world fences."""
        g = self._adopted
        if g is None:
            return False
        cur = self.read_generation()
        if cur is None:
            return False
        superseded = cur.gen > g.gen or (
            cur.gen == g.gen and sorted(cur.members) != sorted(g.members)
        )
        if superseded and self.name not in cur.members:
            _M_FENCED.labels(plane=plane).inc()
            return True
        return False

    def await_generation(
        self,
        world_size: int,
        timeout_s: float = 60.0,
        min_gen: int = 0,
        poll_s: float = 0.1,
    ) -> Generation:
        """Adopt the current generation once it includes this member; if
        none exists, the lowest-named of the first ``world_size``
        registrants commits generation 1."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            g = self.read_generation()
            if g is not None and g.gen > min_gen and self.name in g.members:
                self.adopt(g)
                return g
            if min_gen == 0 and self._adopted is None:
                # bootstrap only before EVER adopting a generation: a
                # parked or resharded member re-awaiting must not fork a
                # fresh gen-1 world while blind to the winner's record.
                # Generation records are DURABLE (no TTL): a brand-new
                # gang may take over a committed gen only when every
                # incumbent member is gone from the roster — it then
                # CONTINUES the sequence (gen+1, CAS on the incumbent
                # gen), never rewinds it; a single live incumbent blocks
                # the takeover (grow-back owns joining a live gang)
                ros = self.roster()
                names = sorted(ros or {})
                incumbent_alive = g is not None and any(
                    m in (ros or {}) for m in g.members
                )
                if (
                    not incumbent_alive
                    and self.name in names
                    and len(names) >= world_size
                    and self.name == names[0]
                ):
                    base = g.gen if g is not None else 0
                    try:
                        return self.commit_generation(
                            Generation(
                                gen=base + 1, members=names[:world_size]
                            ),
                            expected_gen=base,
                        )
                    except (QuorumLostError, GenerationConflictError):
                        pass  # lost the race or the quorum: keep polling
            time.sleep(poll_s)
        raise TimeoutError(
            f"member {self.name!r}: no generation including me appeared "
            f"within {timeout_s:g}s (world_size={world_size}, "
            f"current={self.read_generation()})"
        )

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        if self._artifact_srv is not None:
            try:
                self._artifact_srv.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        from mmlspark_tpu.io.clients import send_request
        from mmlspark_tpu.io.http_schema import HTTPRequestData

        for url in self.registry_urls:
            try:
                send_request(
                    HTTPRequestData(
                        url, "DELETE", {"Content-Type": "application/json"},
                        json.dumps({
                            "name": f"{self.service}-gang",
                            "host": self.name, "port": self.advertise_port,
                        }),
                    ),
                    timeout=5.0,
                )
            except Exception:  # noqa: BLE001 — registry may be gone
                pass


# -- the TCP allreduce --------------------------------------------------------


class _PendingReduce:
    """Handle for an in-flight :meth:`TcpReducer.allreduce_async`."""

    def __init__(self) -> None:
        self._ev = threading.Event()
        self._val: Any = None
        self._exc: Optional[BaseException] = None

    def _set(self, val: Any = None, exc: Optional[BaseException] = None):
        self._val, self._exc = val, exc
        self._ev.set()

    def result(self, timeout_s: Optional[float] = None) -> np.ndarray:
        if not self._ev.wait(timeout_s):
            raise TimeoutError("allreduce_async result not ready")
        if self._exc is not None:
            raise self._exc
        return self._val


class TcpReducer:
    """Framed-TCP sum-allreduce among one generation's members.

    Two wire patterns, both accumulating in f64 in **sorted-member
    order** so every member computes the bit-identical total:

    - ``mode="ring"`` (default): chunked ring reduce-scatter +
      allgather. The flat payload splits into ``world`` contiguous
      segments (``partition_bounds`` — the same math that slices the
      dataset); member ``i`` owns segment ``i``. Scatter phase: each
      member sends every OTHER owner's segment of its local contribution
      (raw input dtype — an f32 contribution upcasts to f64 exactly, so
      the wire carries half the bytes with zero precision loss); the
      owner, holding all ``world`` contributions of its segment, sums
      them in sorted-member order in f64. Gather phase: each owner sends
      its summed f64 segment to every peer. 2(w-1) steps of
      O(payload/world) each — per-member bytes drop from ``(w-1) * 8n``
      to ``(w-1)/w * (itemsize + 8) * n``, strictly less at every world
      size for f32 payloads and ~2x/w of full mesh for large worlds.
    - ``mode="mesh"``: the original everyone-sends-everything exchange,
      kept as the A/B baseline (bit-identical results by construction;
      tests pin ring == mesh byte-for-byte).

    Every member executes the identical sequence of collectives (the host
    growers are SPMD over the gang), so monotonically increasing ``seq``
    numbers pair frames without negotiation (a ring op consumes two: one
    per phase). :meth:`allreduce_async` runs the exchange on a dedicated
    worker thread so the growers can overlap the NEXT histogram block's
    build with this block's wire time — seqs are allocated on the
    calling thread, keeping the SPMD frame pairing deterministic.

    A peer whose frame never arrives AND whose registry heartbeats have
    lapsed raises :class:`HostLostError` — the socket-level failure the
    reference's LightGBM allreduce dies on becomes the detection signal.
    """

    def __init__(
        self,
        member: GangMember,
        generation: Generation,
        timeout_s: float = 60.0,
        connect_timeout_s: float = 10.0,
        mode: str = "ring",
    ):
        if mode not in ("ring", "mesh"):
            raise ValueError(f"unknown reduce mode {mode!r}")
        self.member = member
        self.gen = generation.gen
        self.members = sorted(generation.members)
        self.me = member.name
        self.mode = mode
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        # same loss debounce as GangContext.on_round: a freshly
        # restarted registry's empty roster must not read as mass death
        self.loss_grace_s = max(1.0, 2.0 * member.heartbeat_s)
        # incarnation nonce: a content hash of the generation record,
        # identical on every member that adopted the SAME record —
        # frames from an aborted same-gen-number incarnation (the
        # membership-conflict path) key differently and can never be
        # consumed as this incarnation's sums
        self.nonce = zlib.crc32(json.dumps(
            [generation.gen, sorted(generation.members),
             generation.resume_round, generation.committer],
        ).encode()) & 0xFFFFFFFF
        self.seq = 0
        self._conns: dict = {}
        self._send_lock = threading.Lock()
        # recent outgoing frames, keyed (gen, nonce, seq, peer): the
        # retransmit source when a peer NACKs a CRC-torn frame (ring
        # frames differ per peer — each owner gets its own segment). The
        # gang is SPMD-lockstep, so peers only ever NACK recent seqs;
        # the cap covers a couple of in-flight overlapped ops
        self._sent_frames: dict = {}
        self._sent_cap = max(16, 6 * len(self.members))
        # (seq, peer) frames whose send transiently failed — retried at
        # each roster check (a dropped send must not wedge the PEER)
        self._unsent: set = set()
        self.retransmits = 0
        self.payload_bytes_sent = 0
        self.ring_steps = 0
        self.ops = 0
        self.world = len(self.members)
        self._rank = self.members.index(self.me) if self.me in self.members else 0
        # async worker: one thread, FIFO — started on first use
        self._jobs: Any = None
        self._worker: Optional[threading.Thread] = None
        self._failed: Optional[BaseException] = None
        member.drop_stale_frames(self.gen)
        member._attach_reducer(self)

    def _conn(self, peer: str) -> socket.socket:
        c = self._conns.get(peer)
        if c is not None:
            return c
        ros = self.member.roster()
        if ros is None:
            # blind (no registry answered) is transient, not a death
            raise OSError("no registry reachable for peer lookup")
        e = ros.get(peer)
        if e is None:
            raise HostLostError([peer], self.gen, "peer not on roster")
        c = socket.create_connection(
            (e.get("addr", "127.0.0.1"), int(e["port"])),
            timeout=self.connect_timeout_s,
        )
        c.settimeout(None)
        self._conns[peer] = c
        return c

    # -- frame bookkeeping ----------------------------------------------------

    def _post_frames(self, seq: int, payloads: dict) -> None:
        """Build, cache and (best-effort) send one frame per peer.
        ``payloads``: peer -> payload bytes. A payload OBJECT shared by
        several peers (the whole mesh exchange; the ring gather phase)
        serializes into ONE frame that every cache entry references —
        w-1 identical multi-MB frames would otherwise be copied and
        retained per collective. Failed sends land in ``_unsent`` and
        are retried at every roster check."""
        name = self.me.encode()
        frame_for: dict = {}  # id(payload) -> built frame
        with self._send_lock:
            for peer, payload in payloads.items():
                frame = frame_for.get(id(payload))
                if frame is None:
                    head = struct.pack(
                        _FRAME_HEAD, self.gen, seq, self.nonce,
                        zlib.crc32(payload) & 0xFFFFFFFF,
                        len(name), len(payload),
                    )
                    frame = head + name + payload
                    frame_for[id(payload)] = frame
                self._sent_frames[(self.gen, self.nonce, seq, peer)] = frame
                while len(self._sent_frames) > self._sent_cap:
                    del self._sent_frames[next(iter(self._sent_frames))]
                try:
                    self._conn(peer).sendall(frame)
                    self.payload_bytes_sent += len(payload)
                    _M_PAYLOAD_BYTES.labels(mode=self.mode).inc(len(payload))
                except (OSError, HostLostError):
                    # a dead socket is not yet a dead HOST: the roster
                    # decides at the next check (may be mid-restart)
                    self._conns.pop(peer, None)
                    self._unsent.add((seq, peer))

    def _resend_unsent(self) -> None:
        with self._send_lock:
            for seq, peer in list(self._unsent):
                frame = self._sent_frames.get(
                    (self.gen, self.nonce, seq, peer)
                )
                if frame is None:
                    self._unsent.discard((seq, peer))
                    continue
                try:
                    self._conn(peer).sendall(frame)
                    self._unsent.discard((seq, peer))
                    n = len(frame) - _FRAME_HEAD_LEN - len(self.me.encode())
                    self.payload_bytes_sent += n
                    _M_PAYLOAD_BYTES.labels(mode=self.mode).inc(n)
                except (OSError, HostLostError):
                    self._conns.pop(peer, None)

    def _collect(self, seq: int, senders: list) -> dict:
        """Wait for one frame from each of ``senders`` at ``seq``.
        Shared loss machinery of both modes: re-send transiently-failed
        frames, re-NACK CRC-dropped keys, consult the roster's loss
        policy, and surface wedged peers at the timeout."""
        got: dict = {}
        deadline = time.monotonic() + self.timeout_s
        next_roster_check = time.monotonic() + 0.5
        while len(got) < len(senders):
            missing = [p for p in senders if p not in got]
            buf = self.member.take_frame(
                self.gen, self.nonce, seq, missing[0], 0.05
            )
            if buf is not None:
                got[missing[0]] = buf
                continue
            now = time.monotonic()
            if now >= next_roster_check:
                next_roster_check = now + 0.5
                self._resend_unsent()
                for p in missing:
                    # a frame we dropped for bad CRC: re-NACK until the
                    # clean retransmit lands (the first NACK — sent by
                    # the read loop — may itself have been lost)
                    if self.member.crc_dropped(
                        (self.gen, self.nonce, seq, p)
                    ):
                        self.send_nack(p, self.gen, self.nonce, seq)
                # one shared loss policy with on_round (blindness is
                # not death; grace debounces): GangMember.declared_dead
                dead = self.member.declared_dead(
                    missing, self.member.roster(), self.loss_grace_s
                )
                if dead:
                    now_m = time.monotonic()
                    latency = [
                        now_m - self.member.last_seen.get(p, now_m)
                        for p in dead
                    ]
                    for lat in latency:
                        _M_DETECT.observe(max(0.0, lat))
                    raise HostLostError(
                        dead, self.gen,
                        f"allreduce seq {seq}: no frame, heartbeats lapsed "
                        f"(detect latency ~{max(latency):.2f}s)",
                    )
                g = self.member.read_generation()
                if g is not None and g.gen > self.gen:
                    raise WorldChangedError(g.gen)
                # the minority side of a partition: peers AND registries
                # unreachable. Waiting out the full allreduce timeout
                # would leave a zombie training long after the majority
                # resharded — park as soon as the quorum grace lapses
                if self.member.quorum_age_s() > self.member.quorum_grace_s:
                    raise QuorumLostError(
                        f"allreduce seq {seq}: no registry majority for "
                        f"{self.member.quorum_age_s():.1f}s"
                    )
            if now >= deadline:
                raise HostLostError(
                    missing, self.gen,
                    f"allreduce seq {seq} timed out after "
                    f"{self.timeout_s:g}s with live heartbeats — wedged "
                    "peer(s)",
                )
        return got

    # -- the collectives ------------------------------------------------------

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        """Sum ``arr`` across the gang; returns the same dtype/shape.
        World 1 is an exact no-op (bit-identical to unsharded training)."""
        if self.world <= 1:
            return arr
        if self._failed is not None:
            raise self._failed
        with self._send_lock:
            seq = self.seq
            self.seq += 2 if self.mode == "ring" else 1
        return self._allreduce_at(arr, seq)

    def allreduce_async(self, arr: np.ndarray) -> _PendingReduce:
        """Start an allreduce on the reducer's worker thread and return
        a handle. Seqs are allocated HERE, on the calling thread — every
        member submits the identical op sequence, so frames pair even
        though the wire work happens off-thread. The caller overlaps the
        next histogram block's build with this block's wire time."""
        p = _PendingReduce()
        if self.world <= 1:
            p._set(val=arr)
            return p
        if self._failed is not None:
            p._set(exc=self._failed)
            return p
        with self._send_lock:
            seq = self.seq
            self.seq += 2 if self.mode == "ring" else 1
            if self._jobs is None:
                import queue as _queue

                self._jobs = _queue.Queue()
                self._worker = threading.Thread(
                    target=self._work_loop,
                    name=f"reduce-{self.me}-g{self.gen}", daemon=True,
                )
                self._worker.start()
        self._jobs.put((arr, seq, p))
        return p

    def _work_loop(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            arr, seq, pending = job
            if self._failed is not None:
                # once the gang broke, later queued ops must fail fast,
                # not each burn a full timeout
                pending._set(exc=self._failed)
                continue
            try:
                pending._set(val=self._allreduce_at(arr, seq))
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                self._failed = e
                pending._set(exc=e)

    def _allreduce_at(self, arr: np.ndarray, seq: int) -> np.ndarray:
        t0 = time.perf_counter()
        self.ops += 1
        src = np.asarray(arr)
        if self.mode == "ring":
            out = self._allreduce_ring(src, seq)
        else:
            out = self._allreduce_mesh(src, seq)
        _M_ALLREDUCE.observe(time.perf_counter() - t0)
        return out

    def _allreduce_mesh(self, src: np.ndarray, seq: int) -> np.ndarray:
        """The legacy full-mesh exchange: every member sends its full
        f64 contribution to every peer; everyone sums locally."""
        x = np.ascontiguousarray(src.astype(np.float64))
        peers = [m for m in self.members if m != self.me]
        payload = x.tobytes()  # serialized ONCE; _post_frames shares it
        self._post_frames(seq, {p: payload for p in peers})
        got = self._collect(seq, peers)
        bufs = {self.me: x.reshape(-1)}
        for p, buf in got.items():
            bufs[p] = np.frombuffer(buf, np.float64)
        total = bufs[self.members[0]].astype(np.float64, copy=True)
        for m in self.members[1:]:
            total = total + bufs[m]
        return total.reshape(x.shape).astype(src.dtype)

    def _allreduce_ring(self, src: np.ndarray, seq: int) -> np.ndarray:
        """Ring reduce-scatter + allgather; seq (scatter) and seq+1
        (gather). Accumulation order per element is members[0..w-1] in
        f64 — bit-identical to the mesh exchange's sum."""
        # contributions travel in the input dtype when that upcasts to
        # f64 exactly (f32/f64); anything else is cast to f64 up front,
        # exactly like the mesh path
        wire_dtype = src.dtype if src.dtype in (
            np.dtype(np.float32), np.dtype(np.float64)
        ) else np.dtype(np.float64)
        flat = np.ascontiguousarray(src.astype(wire_dtype)).reshape(-1)
        w = self.world
        bounds = partition_bounds(flat.size, w)
        rank = self._rank
        # fault point elastic.ring_step: fires before each ring step on
        # each member (context names phase/step); a delay stalls the
        # pipeline (visible in allreduce seconds), an error kills the
        # trainer — the supervisor-restart path
        # -- scatter: send every other owner its segment of my contribution
        payloads = {}
        for t in range(1, w):
            j = (rank + t) % w
            peer = self.members[j]
            faults.inject(
                "elastic.ring_step",
                context={"phase": "scatter", "step": t, "peer": peer},
            )
            lo, hi = bounds[j]
            payloads[peer] = flat[lo:hi].tobytes()
            self.ring_steps += 1
            _M_RING_STEPS.labels(phase="scatter").inc()
        self._post_frames(seq, payloads)
        peers = [m for m in self.members if m != self.me]
        got = self._collect(seq, peers)
        # -- owner sum: all w contributions of MY segment, sorted order
        lo, hi = bounds[rank]
        seg_len = hi - lo
        contrib = {self.me: flat[lo:hi]}
        for p, buf in got.items():
            piece = np.frombuffer(buf, wire_dtype)
            if piece.size != seg_len:
                # only reachable through a CRC-colliding corruption of a
                # resized frame — refuse to sum garbage
                raise HostLostError(
                    [p], self.gen,
                    f"ring segment from {p} has {piece.size} elements, "
                    f"expected {seg_len}",
                )
            contrib[p] = piece
        total_seg = contrib[self.members[0]].astype(np.float64, copy=True)
        for m in self.members[1:]:
            total_seg = total_seg + contrib[m]
        # -- allgather: every owner distributes its summed f64 segment
        seg_bytes = np.ascontiguousarray(total_seg).tobytes()
        payloads = {}
        for t in range(1, w):
            peer = self.members[(rank + t) % w]
            faults.inject(
                "elastic.ring_step",
                context={"phase": "gather", "step": t, "peer": peer},
            )
            payloads[peer] = seg_bytes
            self.ring_steps += 1
            _M_RING_STEPS.labels(phase="gather").inc()
        self._post_frames(seq + 1, payloads)
        got = self._collect(seq + 1, peers)
        out = np.empty(flat.size, np.float64)
        out[lo:hi] = total_seg
        for j, m in enumerate(self.members):
            if m == self.me:
                continue
            jlo, jhi = bounds[j]
            piece = np.frombuffer(got[m], np.float64)
            if piece.size != jhi - jlo:
                raise HostLostError(
                    [m], self.gen,
                    f"ring gather segment from {m} has {piece.size} "
                    f"elements, expected {jhi - jlo}",
                )
            out[jlo:jhi] = piece
        return out.reshape(src.shape).astype(src.dtype)

    def send_nack(self, peer: str, gen: int, nonce: int, seq: int) -> None:
        """Tell ``peer`` its (gen, seq) frame arrived torn — control
        frame with ``nbytes == -1``; the peer retransmits from its
        recent-frame cache. Best-effort: a lost NACK is re-sent by the
        waiting allreduce at its next roster check."""
        head = struct.pack(
            _FRAME_HEAD, gen, seq, nonce, 0,
            len(self.me.encode()), _NACK_NBYTES,
        )
        with self._send_lock:
            try:
                self._conn(peer).sendall(head + self.me.encode())
            except (OSError, HostLostError):
                self._conns.pop(peer, None)

    def handle_nack(self, peer: str, gen: int, nonce: int, seq: int) -> None:
        """A peer reported our frame corrupt: retransmit it. Called from
        the member's read loop thread; a frame no longer cached (ancient
        seq, different incarnation) is ignored — the peer's timeout path
        handles it as peer-loss."""
        with self._send_lock:
            frame = self._sent_frames.get((gen, nonce, seq, peer))
            if frame is None:
                return
            try:
                self._conn(peer).sendall(frame)
            except (OSError, HostLostError):
                self._conns.pop(peer, None)
                return
        self.retransmits += 1
        _M_RETRANSMITS.inc()

    def close(self) -> None:
        self.member._detach_reducer(self)
        if self._jobs is not None:
            self._jobs.put(None)
        for c in self._conns.values():
            try:
                c.close()
            except OSError:
                pass
        self._conns.clear()


# -- the per-generation training context --------------------------------------


class GangContext:
    """What ``train()`` and the host growers consult while a generation
    trains. Installed process-globally with :func:`activate` (the host
    growers run on callback threads, so a thread-local would miss)."""

    def __init__(
        self,
        member: GangMember,
        generation: Generation,
        n_rows: int,
        n_partitions: int,
        checkpoint_every: int = 10,
        reducer: Optional[TcpReducer] = None,
        stragglers: Optional[StragglerTracker] = None,
        evict_stragglers: bool = False,
        min_world: int = 1,
        allow_growback: bool = True,
        global_rows: Optional[np.ndarray] = None,
        ckpt_dir: Optional[str] = None,
        all_write: bool = False,
        voting_top_k: Optional[int] = None,
    ):
        """``global_rows``: the full global feature matrix when the host
        already has it (the ``fleet train`` data model: every host loads
        the same ``--data``) — :meth:`binning_rows` then avoids
        allreducing the entire dataset just to re-fit bin bounds.

        ``all_write``: every member writes checkpoints to its own (host-
        local) ``ckpt_dir`` instead of only the coordinator writing a
        shared one — the artifact-mode data model, where checkpoint
        bytes replicate by content-addressed pull, not by shared mount.
        The gather collective still runs on every member either way, so
        the written state is bit-identical across the gang."""
        self.member = member
        self.generation = generation
        self.members = sorted(generation.members)
        self.world = len(self.members)
        self.global_n = int(n_rows)
        self.n_partitions = int(n_partitions)
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.reducer = reducer
        self.straggler_tracker = stragglers
        self.evict_stragglers = evict_stragglers
        self.min_world = max(1, int(min_world))
        self.allow_growback = allow_growback
        self.global_rows = global_rows
        self.ckpt_dir = ckpt_dir
        self.all_write = bool(all_write)
        # voting-parallel (PV-Tree): the host growers exchange only the
        # top-2K candidate features' histogram columns instead of the
        # full plane; None = full data-parallel
        self.voting_top_k = (
            int(voting_top_k) if voting_top_k else None
        )
        # loss debounce: a peer missing from the roster is only declared
        # dead once its last sighting is older than this — an
        # answering-but-freshly-restarted registry returns an EMPTY
        # roster, and that window must not read as "everyone died"
        self.loss_grace_s = max(1.0, 2.0 * member.heartbeat_s)
        self.lo, self.hi = member_row_slice(
            n_rows, n_partitions, self.members, member.name
        )
        self.lost: list = []
        self.world_changed: Optional[int] = None
        self.quorum_lost = False
        self.rounds_seen = 0
        self._round_t = time.monotonic()
        self._last_it = 0
        self.started_t = time.monotonic()
        self.first_round_done_t: Optional[float] = None
        self._join_seq = 0
        self.flagged_stragglers: list = []
        # where replicate-before-commit bookkeeping lands (the owning
        # ElasticTrainer points this at its status dict)
        self.status_sink: Optional[dict] = None

    # -- data movement --------------------------------------------------------

    @property
    def is_coordinator(self) -> bool:
        """The generation coordinator (lowest-named member): runs the
        grow-back / straggler policy at checkpoint boundaries and is the
        shared-dir mode's sole checkpoint writer."""
        return self.member.name == self.members[0]

    @property
    def is_writer(self) -> bool:
        """Does THIS member persist checkpoints? Shared-dir mode: only
        the coordinator (two writers on one mount would race). Artifact
        mode (``all_write``): everyone — each host's dir is its own, and
        the bytes are bit-identical by the gather-collective contract.
        Every member participates in the gather either way."""
        return self.all_write or self.is_coordinator

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        if self.reducer is None or self.world <= 1:
            return arr
        try:
            return self.reducer.allreduce(arr)
        except HostLostError as e:
            self.lost = e.lost
            raise
        except WorldChangedError as e:
            self.world_changed = e.gen
            raise
        except QuorumLostError:
            self.quorum_lost = True
            raise

    def allreduce_blocks(self, builders: list) -> list:
        """Compute/communication overlap: ``builders`` are zero-arg
        callables producing arrays (e.g. per-feature-block histograms).
        Block ``i``'s allreduce rides the reducer's worker thread while
        block ``i+1`` is still being BUILT — double-buffered (at most
        two blocks in flight), critical-path ordered (results return in
        submission order). Elementwise sums are blocking-invariant, so
        the concatenated result is bit-identical to one whole-plane
        allreduce."""
        if self.reducer is None or self.world <= 1:
            return [b() for b in builders]
        try:
            out: list = []
            pending: list = []
            for b in builders:
                if pending:
                    # this block's build runs while the previous
                    # block(s) are on the wire — the overlap the
                    # counter advertises
                    _M_OVERLAP_BLOCKS.inc()
                arr = b()
                pending.append(self.reducer.allreduce_async(arr))
                if len(pending) >= 2:
                    # true double-buffer: harvest the older op before
                    # building a third block, bounding peak memory to
                    # two blocks (cube + wire frames) at any moment
                    out.append(pending.pop(0).result())
            while pending:
                out.append(pending.pop(0).result())
            return out
        except HostLostError as e:
            self.lost = e.lost
            raise
        except WorldChangedError as e:
            self.world_changed = e.gen
            raise
        except QuorumLostError:
            self.quorum_lost = True
            raise

    def all_rows(self, local: np.ndarray) -> np.ndarray:
        """Local rows -> the (global_n, ...) array in global row order
        (scatter + sum-allreduce: every element is one member's value
        plus zeros, so the result is EXACT at any wire dtype). f32
        payloads stay f32 on the scatter wire — half the checkpoint
        gather's bytes at zero precision cost; the ring's owner still
        accumulates in f64. The collective every member runs at
        checkpoint time."""
        local = np.asarray(local)
        if self.world <= 1:
            return local
        wire = (
            np.float32 if local.dtype == np.float32 else np.float64
        )
        out = np.zeros((self.global_n,) + local.shape[1:], wire)
        out[self.lo:self.hi] = local
        return self.allreduce(out).astype(local.dtype)

    def take_local(self, global_arr: np.ndarray) -> np.ndarray:
        return np.asarray(global_arr)[self.lo:self.hi]

    def binning_rows(self, local: np.ndarray) -> np.ndarray:
        """The global rows bin bounds are fitted on. When the host holds
        the full dataset (``global_rows``), hand it over directly —
        bit-identical to the gather, with zero network traffic; the
        allreduce path remains for gangs whose members only hold their
        own slice."""
        if self.global_rows is not None:
            return np.asarray(self.global_rows, local.dtype)
        return self.all_rows(local)

    # -- round boundary hooks --------------------------------------------------

    def on_round(self, it: int) -> None:
        """Called by the training loop entering round/chunk ``it``:
        update the straggler EWMA, run the detection check (fault point
        ``elastic.detect``), and — on checkpoint boundaries, coordinator
        only — grow-back and straggler policy. Raises
        :class:`HostLostError` / :class:`WorldChangedError` to abort."""
        # stall forensics: a round that never reaches the next boundary
        # (e.g. an allreduce wedged on a dead peer's half-open socket)
        # auto-dumps all-thread stacks after the deadline
        watchdog.tick("elastic.round")
        now = time.monotonic()
        if self.rounds_seen > 0:
            # boundaries are CHUNK boundaries on the scan-fused path and
            # ROUND boundaries on the per-iteration path: amortize over
            # the rounds actually elapsed since the last boundary
            dt = (now - self._round_t) / max(1, it - self._last_it)
            a = 0.3
            self.member.ewma_s = (
                dt if self.member.ewma_s == 0.0
                else a * dt + (1 - a) * self.member.ewma_s
            )
            _M_ROUND_EWMA.labels(host=self.member.name).set(
                self.member.ewma_s
            )
            if self.first_round_done_t is None:
                self.first_round_done_t = now
        self._round_t = now
        self._last_it = it
        self.rounds_seen += 1
        if (
            self.world > 1 and self.rounds_seen == 2
            and self.reducer is not None
            and self.reducer.seq <= self._join_seq
        ):
            raise RuntimeError(
                "elastic gang trained a round without a single gang "
                "allreduce — the host histogram lowering was not selected "
                "(elastic training requires the CPU host growers: "
                "shard=False and MMLSPARK_TPU_HIST_HOST!=0)"
            )
        # fault point elastic.detect: a payload names a member to declare
        # lost without killing anything (chaos for the reshard path); an
        # injected error is the detector itself failing
        forced = faults.inject(
            "elastic.detect", context={"gen": self.generation.gen, "it": it}
        )
        ros = self.member.roster()
        # roster None = every registry unreachable; a roster that lacks
        # even OUR OWN entry is a registry that just restarted and has
        # not collected heartbeats yet. Blindness in either form is not
        # evidence of death — hold rather than split-brain the gang.
        # For visible peers, a miss only counts once the last sighting
        # is older than the loss grace (debounces the re-register race).
        # Sustained blindness past the quorum grace is different from a
        # blip: this member is (at best) on the minority side of a
        # partition, and in a multi-member gang it must PARK rather than
        # train into an epoch the majority is entitled to reshard away.
        if (
            self.world > 1
            and self.member.quorum_age_s() > self.member.quorum_grace_s
        ):
            self.quorum_lost = True
            raise QuorumLostError(
                f"round {it}: no registry majority for "
                f"{self.member.quorum_age_s():.1f}s"
            )
        now_m = time.monotonic()
        lost = self.member.declared_dead(
            [m for m in self.members if m != self.member.name],
            ros, self.loss_grace_s,
        )
        if isinstance(forced, str) and forced in self.members:
            lost.append(forced)
        if lost:
            for m in lost:
                _M_DETECT.observe(
                    max(0.0, now_m - self.member.last_seen.get(m, now_m))
                )
            self.lost = sorted(set(lost))
            raise HostLostError(self.lost, self.generation.gen,
                                "heartbeats lapsed at round boundary")
        g = self.member.read_generation()
        if g is not None and (
            g.gen > self.generation.gen
            or (
                # same gen number, DIFFERENT members: racing survivors
                # with divergent lost-sets committed conflicting records
                # and the registry's last writer won — defer to it
                g.gen == self.generation.gen
                and sorted(g.members) != self.members
            )
        ):
            self.world_changed = g.gen
            raise WorldChangedError(g.gen)
        if (
            it % self.checkpoint_every == 0 and self.is_coordinator
            and ros is not None
        ):
            self._coordinate(ros, it)

    def _freeze_resume(self, next_gen: int, it: int) -> tuple:
        """Artifact-mode resume point for a grow/straggler reshard:
        freeze the latest checkpoint, ``put()`` it as a content-
        addressed artifact, and return ``(snapshot, digest,
        resume_round)`` — so a joiner with an empty (host-local) dir can
        pull the exact agreed bytes over HTTP. Shared-dir mode returns
        ``(None, None, it)``: members resume from the shared LATEST as
        before."""
        store = self.member.artifact_store
        if store is None or not self.ckpt_dir:
            return None, None, it
        if self.member.fenced_out("artifact"):
            # the epoch moved past us while we were deciding to resize:
            # a fenced-out writer must not persist or advertise snapshot
            # bytes (the commit below would lose its CAS anyway — this
            # refuses the WRITE, not just the record)
            cur = self.member.read_generation()
            self.world_changed = (
                cur.gen if cur is not None else self.generation.gen + 1
            )
            raise WorldChangedError(self.world_changed)
        snap, resume_round = snapshot_checkpoint(self.ckpt_dir, next_gen)
        if snap is None:
            return None, None, it
        try:
            ref = store.put(snap, name=os.path.basename(snap))
        except Exception:  # noqa: BLE001 — a refused put degrades to
            # shared-dir semantics rather than blocking the resize
            return snap, None, resume_round
        return snap, ref.digest, resume_round

    def _coordinate(self, ros: dict, it: int) -> None:
        """Checkpoint-boundary duties of the generation coordinator:
        grow-back (admit re-registered hosts) and straggler policy."""
        joiners = sorted(
            j for j in set(ros) - set(self.members)
            # an evicted straggler only re-enters with a fresh boot (a
            # restarted process); the same slow process stays out
            if self.generation.evicted.get(j) != ros[j].get("boot")
        )
        # capacity: every member must own at least one partition — a
        # 0-row member would gang-sum empty-gradient NaNs into everyone
        joiners = joiners[:max(0, self.n_partitions - self.world)]
        if joiners and self.allow_growback and it > 0:
            snap, digest, resume_round = self._freeze_resume(
                self.generation.gen + 1, it
            )
            g = Generation(
                gen=self.generation.gen + 1,
                members=sorted(set(self.members) | set(joiners)),
                reason="grow",
                resume_round=resume_round,
                snapshot=snap,
                snapshot_digest=digest,
            )
            # replicate-before-commit: the joiners (and any survivor
            # that outlives this host) must be able to pull the agreed
            # resume bytes even if this host dies right after the CAS
            replicate_snapshot(
                self.member, digest, g.members, status=self.status_sink
            )
            self.member.commit_generation(g)
            _M_RESHARDS.labels(reason="grow").inc()
            self.world_changed = g.gen
            raise WorldChangedError(g.gen)
        if self.straggler_tracker is not None and self.world > 1:
            ewmas = {
                m: float(ros[m].get("ewma_ms", 0.0)) / 1e3
                for m in self.members if m in ros
            }
            flagged = self.straggler_tracker.observe(ewmas)
            self.flagged_stragglers = flagged
            _M_STRAGGLERS.set(len(flagged))
            evictable = [m for m in flagged if m != self.member.name]
            if (
                self.evict_stragglers and evictable
                and self.world - len(evictable) >= self.min_world
            ):
                snap, digest, resume_round = self._freeze_resume(
                    self.generation.gen + 1, it
                )
                g = Generation(
                    gen=self.generation.gen + 1,
                    members=[m for m in self.members if m not in evictable],
                    reason="straggler",
                    resume_round=resume_round,
                    snapshot=snap,
                    snapshot_digest=digest,
                    evicted={
                        **self.generation.evicted,
                        **{m: ros.get(m, {}).get("boot") for m in evictable},
                    },
                )
                replicate_snapshot(
                    self.member, digest, g.members, status=self.status_sink
                )
                self.member.commit_generation(g)
                _M_RESHARDS.labels(reason="straggler").inc()
                self.world_changed = g.gen
                raise WorldChangedError(g.gen)

    # -- abort classification --------------------------------------------------

    def abort_reason(self, exc: BaseException) -> Optional[Exception]:
        """Was ``exc`` a gang change? In-callback failures surface as
        ``XlaRuntimeError`` with the real cause recorded on this context,
        so classify by state, not by exception type."""
        if isinstance(exc, (
            HostLostError, WorldChangedError,
            QuorumLostError, GenerationConflictError,
        )):
            return exc
        if self.lost:
            return HostLostError(self.lost, self.generation.gen)
        if self.world_changed is not None:
            return WorldChangedError(self.world_changed)
        if self.quorum_lost:
            return QuorumLostError("recorded on gang context")
        return None

    def join(self, timeout_s: float = 30.0) -> None:
        """Generation-formation barrier: one tiny allreduce proves every
        member's transport before any training work. A member that died
        between commit and join surfaces as a
        :class:`~mmlspark_tpu.parallel.distributed.BarrierTimeoutError`
        naming the missing host (the same diagnostic shape the SPMD
        barrier raises)."""
        if self.reducer is None or self.world <= 1:
            return
        old = self.reducer.timeout_s
        self.reducer.timeout_s = timeout_s
        try:
            total = self.reducer.allreduce(np.ones(1))
            if int(round(float(total[0]))) != self.world:
                raise RuntimeError(
                    f"gen {self.generation.gen} join barrier summed "
                    f"{total[0]} != world {self.world}"
                )
        except HostLostError as e:
            raise BarrierTimeoutError(
                f"elastic-gen-{self.generation.gen}", timeout_s,
                missing=e.lost,
            ) from e
        finally:
            self.reducer.timeout_s = old
            self._join_seq = self.reducer.seq

    def healthy(self) -> bool:
        return not self.lost and self.world_changed is None

    def close(self) -> None:
        watchdog.disarm("elastic.round")  # a finished gang is not a stall
        if self.reducer is not None:
            self.reducer.close()


# -- process-global active gang (callback threads must see it) ---------------

_ACTIVE_GANG: Optional[GangContext] = None


def active_gang() -> Optional[GangContext]:
    return _ACTIVE_GANG


@contextlib.contextmanager
def activate(gang: GangContext) -> Iterator[GangContext]:
    global _ACTIVE_GANG
    if _ACTIVE_GANG is not None:
        raise RuntimeError("one elastic gang per process")
    _ACTIVE_GANG = gang
    try:
        yield gang
    finally:
        _ACTIVE_GANG = None


def gang_sum() -> Optional[Callable[[np.ndarray], np.ndarray]]:
    """The host growers' hook: a summing callable when a multi-member
    gang is active, else None (the common case costs one global read)."""
    g = _ACTIVE_GANG
    if g is None or g.world <= 1:
        return None
    return g.allreduce


def gang_blocks() -> Optional[Callable[[list], list]]:
    """The host growers' overlap hook: a callable summing a LIST of
    lazily-built arrays with block ``i``'s wire time hidden behind block
    ``i+1``'s build (GangContext.allreduce_blocks), else None."""
    g = _ACTIVE_GANG
    if g is None or g.world <= 1 or g.reducer is None:
        return None
    return g.allreduce_blocks


def gang_voting_k() -> Optional[int]:
    """Voting-parallel hook: the PV-Tree ``top_k`` when the active gang
    trains in voting mode (host growers exchange ballots + top-2K
    candidate columns instead of the full plane), else None."""
    g = _ACTIVE_GANG
    if g is None or g.world <= 1 or g.reducer is None:
        return None
    return g.voting_top_k


def note_vote_round() -> None:
    """Growers report one completed voting exchange (metrics only)."""
    _M_VOTE_ROUNDS.inc()


# -- checkpoint snapshot (the bit-identity audit trail) -----------------------


def snapshot_checkpoint(ckpt_dir: str, gen: int) -> tuple:
    """Copy the LATEST complete checkpoint into
    ``<ckpt_dir>/reshard-g<gen>`` so the exact state a reshard resumed
    from survives later checkpoints — a fresh shrunk-world run from this
    snapshot must reproduce the survivor's booster bit-for-bit. Returns
    ``(snapshot_dir, resume_round)``; ``(None, 0)`` when no checkpoint
    exists yet (the reshard then restarts from round 0)."""
    latest = os.path.join(ckpt_dir, "LATEST")
    if not os.path.exists(latest):
        return None, 0
    with open(latest) as f:
        name = f.read().strip()
    src = os.path.join(ckpt_dir, name)
    # the round rides the snapshot name: a leftover same-gen snapshot
    # from an earlier run of this ckpt_dir can never be silently reused
    # for a different resume point, and racing survivors whose LATEST
    # reads were skewed publish DISTINCT snapshots, each self-consistent
    # with the (snapshot, resume_round) pair its generation record names
    snap = os.path.join(ckpt_dir, f"reshard-g{gen:04d}-{name}")
    if not os.path.isdir(snap):
        # build in a private tmp, publish with one atomic rename —
        # racing survivors (divergent lost-sets can slip two committers
        # past the lowest-survivor gate) then FIRST-WIN cleanly instead
        # of interleaving rmtree/copytree on the same path
        tmp = snap + f".tmp-{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        shutil.copytree(src, os.path.join(tmp, name))
        with open(os.path.join(tmp, "LATEST"), "w") as f:
            f.write(name)
        try:
            os.rename(tmp, snap)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)  # a racer won
    with open(os.path.join(snap, "LATEST")) as f:
        committed = f.read().strip()
    return snap, int(committed.split("-")[-1])


# -- the elastic trainer ------------------------------------------------------


def replicate_snapshot(
    member: GangMember,
    digest: Optional[str],
    members: list,
    status: Optional[dict] = None,
    timeout_s: float = 30.0,
) -> int:
    """Replicate-before-commit for the training plane: push a frozen
    snapshot to the other artifact ingresses of the generation about to
    be committed, so the committed record never names bytes only the
    coordinator's host holds — a coordinator SIGKILLed right after the
    commit leaves the resume point pullable from the survivors. Quorum
    target: a majority of the NEW world counting the local copy
    (``len(members) // 2`` remote confirms). Below quorum this DEGRADES
    (the commit proceeds; the shortfall is recorded in ``status``)
    instead of raising: a lone survivor must still be able to reshard,
    and a missed replica costs a re-pull from the coordinator or at
    worst a retrainable round — strict replication-before-ack lives on
    the publish planes (Publisher, experiments winner) where a lost
    blob means a lost model."""
    store = member.artifact_store
    if digest is None or store is None:
        return 0
    holders = member.artifact_holders(members)
    majority = len(members) // 2
    need = min(majority, len(holders))
    confirmed = 0
    if need > 0:
        try:
            confirmed = len(store.replicate(
                digest, holders, need=need, timeout_s=timeout_s,
                backoffs_ms=(100, 300),
            ))
        except Exception:  # noqa: BLE001 — below quorum / refused round
            confirmed = 0
    if status is not None:
        status["snapshot_replicas"] = confirmed
        if confirmed < majority:
            status["snapshot_replica_shortfalls"] = (
                status.get("snapshot_replica_shortfalls", 0) + 1
            )
    return confirmed


class ElasticTrainer:
    """Drive one host's share of an elastic GBDT training run.

    All hosts run this same loop (SPMD at the control plane): join the
    gang, adopt/form a generation, load the contiguous partition run
    assigned for that world, and train through ``models/gbdt/train.py``
    with gang-summed histograms. A lost host aborts the in-flight round,
    re-shards, and resumes from the latest checkpoint; a re-registered
    host is grown back at the next checkpoint boundary."""

    def __init__(
        self,
        registry_urls: Any,
        name: str,
        x: np.ndarray,
        y: np.ndarray,
        cfg: Any,
        ckpt_dir: str,
        n_partitions: int = 8,
        world_size: int = 1,
        service: str = "train",
        checkpoint_every: int = 2,
        heartbeat_s: float = 0.5,
        gen_timeout_s: float = 120.0,
        allreduce_timeout_s: float = 120.0,
        resume_from: Optional[str] = None,
        advertise_host: str = "127.0.0.1",
        straggler_factor: float = 3.0,
        straggler_rounds: int = 3,
        evict_stragglers: bool = False,
        min_world: int = 1,
        status_file: Optional[str] = None,
        allow_growback: bool = True,
        artifact_dir: Optional[str] = None,
        allreduce_port: int = 0,
        advertise_allreduce_port: Optional[int] = None,
        reduce_mode: str = "ring",
        stream: Optional[Callable[[], Iterator]] = None,
        n_rows: Optional[int] = None,
        n_features: Optional[int] = None,
        sketch_bits: int = 16,
        on_complete: Optional[Callable[[Any], None]] = None,
    ):
        """``artifact_dir``: enables **artifact mode** — ``ckpt_dir`` is
        treated as HOST-LOCAL (every member writes its own checkpoints),
        reshard snapshots are published as content-addressed artifacts
        out of an :class:`~mmlspark_tpu.serving.artifacts.ArtifactStore`
        rooted here, and a member whose disk lacks the agreed resume
        snapshot pulls it over HTTP from any surviving peer. Without it,
        the original shared-``ckpt_dir`` data model is unchanged.

        ``reduce_mode``: the gang allreduce wire pattern — ``ring``
        (chunked reduce-scatter + allgather, the default) or ``mesh``
        (the legacy everyone-sends-everything baseline). Bit-identical
        results either way; only bytes-on-the-wire differ.

        ``stream``: **out-of-core mode** — instead of in-memory ``x``/
        ``y``, a re-invocable factory yielding ``(x_chunk, y_chunk)``
        pairs in global row order (``load_streaming_data`` builds one
        from a spec; StreamingDataFrame adapts via
        ``stream_from_dataframe``). Each generation, the member streams
        its row slice twice: pass 1 feeds a per-host quantile sketch
        whose counts merge across the gang THROUGH THE REDUCER (bin
        bounds come out identical on every member at every world size,
        no global gather), pass 2 bins the slice into a uint8 matrix.
        The full float matrix never exists in memory; requires
        ``n_rows``/``n_features``."""
        self.registry_urls = registry_urls
        self.name = name
        self._stream = stream
        if stream is not None:
            if n_rows is None or n_features is None:
                raise ValueError(
                    "stream mode requires n_rows and n_features"
                )
            if x is not None or y is not None:
                raise ValueError("pass either x/y or stream, not both")
            self.x = self.y = None
            self.n = int(n_rows)
            self.n_features = int(n_features)
        else:
            self.x = np.asarray(x)
            self.y = np.asarray(y)
            self.n = len(self.x)
            self.n_features = int(self.x.shape[1])
        self.sketch_bits = int(sketch_bits)
        self.reduce_mode = reduce_mode
        self.cfg = cfg
        self.ckpt_dir = ckpt_dir
        self.n_partitions = int(n_partitions)
        self.world_size = int(world_size)
        self.service = service
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.heartbeat_s = heartbeat_s
        self.gen_timeout_s = gen_timeout_s
        self.allreduce_timeout_s = allreduce_timeout_s
        self.resume_from = resume_from
        self.advertise_host = advertise_host
        self.straggler_factor = straggler_factor
        self.straggler_rounds = straggler_rounds
        self.evict_stragglers = evict_stragglers
        self.min_world = min_world
        self.status_file = status_file
        self.allow_growback = allow_growback
        # runs with the finished booster BEFORE the done status flush:
        # anything a status-file watcher will read the moment it sees
        # ``done`` (e.g. the exported model file) must be durable first
        self.on_complete = on_complete
        self.artifact_dir = artifact_dir
        # chaos-proxy/NAT support: bind the allreduce listener to a fixed
        # port and/or advertise a different one on the roster (peers dial
        # the advertised port — e.g. a ChaosProxy in front of this host)
        self.allreduce_port = int(allreduce_port)
        self.advertise_allreduce_port = advertise_allreduce_port
        self._member: Any = None
        self._store: Any = None
        if artifact_dir:
            from mmlspark_tpu.serving.artifacts import ArtifactStore

            self._store = ArtifactStore(artifact_dir)
        if self.world_size > self.n_partitions:
            # every member must own >= 1 partition (a 0-row member's
            # gang-summed empty gradients would poison the whole gang)
            raise ValueError(
                f"world_size {self.world_size} > n_partitions "
                f"{self.n_partitions}: every member needs at least one "
                "partition"
            )
        self.status: dict = {
            "name": name, "gen": 0, "members": [], "round": 0,
            "reshards": 0, "reshard_reasons": [], "resume_round": 0,
            "snapshot": None, "detect_latency_s": None,
            "reshard_to_first_round_s": None, "rounds_per_s_pre": None,
            "rounds_per_s_post": None, "done": False,
            "artifact_fetches": 0, "crc_drops": 0, "retransmits": 0,
            "reduce_mode": reduce_mode, "payload_bytes": 0,
            "ingest_payload_bytes": 0, "ring_steps": 0,
            "allreduce_ops": 0,
            # split-brain stance: parked == currently refusing to train
            # (minority side / lost CAS race); committed_gens are the
            # epochs THIS member won the commit for — the invariant
            # checker's at-most-one-writer law joins these across the
            # fleet's status files
            "parked": False, "parks": 0, "park_reasons": [],
            "committed_gens": [], "commit_acks": 0,
            # replicate-before-commit bookkeeping: confirmed replica
            # pushes of the latest frozen snapshot, and commits that
            # went ahead despite a replication shortfall (liveness
            # outranks strictness on the training plane)
            "snapshot_replicas": 0, "snapshot_replica_shortfalls": 0,
        }

    # -- status ---------------------------------------------------------------

    def _write_status(self) -> None:
        if not self.status_file:
            return
        if self._member is not None:
            self.status["crc_drops"] = self._member.crc_drops
            self.status["committed_gens"] = list(
                self._member.committed_gens
            )
            self.status["commit_acks"] = self._member.commit_acks
        tmp = self.status_file + f".tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(self.status, f)
            os.replace(tmp, self.status_file)
        except OSError:
            pass

    # -- the loop -------------------------------------------------------------

    def run(self) -> Any:
        from mmlspark_tpu.ops.histogram import use_host_hist

        # the gang data plane lives in the host growers' histograms —
        # refuse to train "distributed" through a lowering that would
        # silently never call the gang allreduce
        if not use_host_hist():
            raise RuntimeError(
                "elastic gang training requires the host histogram "
                "lowering (MMLSPARK_TPU_HIST_HOST)"
            )
        member = GangMember(
            self.registry_urls, self.name, service=self.service,
            advertise_host=self.advertise_host,
            heartbeat_s=self.heartbeat_s,
            artifact_store=self._store,
            listen_port=self.allreduce_port,
            advertise_port=self.advertise_allreduce_port,
        )
        self._member = member
        try:
            self._resolve_resume_from(member)
            gen = member.await_generation(
                self.world_size, timeout_s=self.gen_timeout_s
            )
            while True:
                booster = self._train_generation(member, gen)
                if booster is not None:
                    if self.on_complete is not None:
                        self.on_complete(booster)
                    self.status["done"] = True
                    self._write_status()
                    return booster
                g = member.read_generation()
                if (
                    g is not None and self.name not in g.members
                    and g.evicted.get(self.name) == member.boot
                ):
                    # evicted as a straggler: exit so a supervisor
                    # restart (fresh boot) can grow this host back in
                    raise HostLostError(
                        [self.name], g.gen,
                        "evicted as sustained straggler",
                    )
                # min_gen = gen - 1: a membership CONFLICT resolves to a
                # record with the SAME generation number (the registry's
                # last writer), which must still be adoptable
                gen = member.await_generation(
                    self.world_size, timeout_s=self.gen_timeout_s,
                    min_gen=gen.gen - 1,
                )
        finally:
            member.close()

    def _train_generation(self, member: GangMember, gen: Generation):
        """Train under one generation. Returns the booster on completion
        or None when the gang changed (the caller re-forms)."""
        from mmlspark_tpu.models.gbdt.train import train

        lo, hi = member_row_slice(
            self.n, self.n_partitions, gen.members, self.name
        )
        if hi <= lo:
            raise RuntimeError(
                f"member {self.name!r} holds no partitions at world "
                f"{len(gen.members)} (n_partitions={self.n_partitions})"
            )
        reducer = (
            TcpReducer(
                member, gen, timeout_s=self.allreduce_timeout_s,
                mode=self.reduce_mode,
            )
            if len(gen.members) > 1 else None
        )
        gang = GangContext(
            member, gen, n_rows=self.n,
            n_partitions=self.n_partitions,
            checkpoint_every=self.checkpoint_every, reducer=reducer,
            global_rows=self.x,
            stragglers=StragglerTracker(
                self.straggler_factor, self.straggler_rounds
            ),
            evict_stragglers=self.evict_stragglers,
            min_world=self.min_world,
            allow_growback=self.allow_growback,
            ckpt_dir=self.ckpt_dir,
            all_write=self._store is not None,
            voting_top_k=(
                self.cfg.top_k
                if getattr(self.cfg, "parallelism", "") == "voting_parallel"
                else None
            ),
        )
        gang.status_sink = self.status
        self.status.update(
            gen=gen.gen, members=sorted(gen.members), parked=False,
        )
        # per-round cost changes with the WORLD (a survivor histograms
        # twice the rows after a 2->1 shrink): a fresh generation gets a
        # fresh EWMA, so the straggler signal and the recorded
        # rounds-per-second never blend two world sizes (the r08->r12
        # throughput comparison depends on this honesty)
        member.ewma_s = 0.0
        self._write_status()
        # the agreed resume point: a reshard's snapshot when there is
        # one (every survivor resumes from the SAME state even if the
        # writer's live dir ran one chunk ahead), else the live dir
        # (crash-loop-safe auto-resume for supervisor-restarted hosts).
        # An explicit --resume-from only seeds the run BEFORE it has a
        # checkpoint of its own: later generations (grow/straggler carry
        # no snapshot) must resume from the run's LATEST, not roll the
        # whole gang back to the stale seed
        has_own_ckpt = os.path.exists(os.path.join(self.ckpt_dir, "LATEST"))
        snap = self._resolve_snapshot(member, gen)
        resume = snap or (
            self.resume_from if not has_own_ckpt else None
        ) or self.ckpt_dir
        resume_t0 = time.monotonic()
        try:
            gang.join(timeout_s=self.gen_timeout_s)
            if self._stream is not None:
                # out-of-core: two streaming passes over this member's
                # slice — sketch (merged via the reducer, a collective
                # EVERY member of the generation runs) then uint8 bins.
                # Per-generation by design: the merged counts are a pure
                # function of the global rows, so every generation (and
                # every world size) derives the identical mapper
                x_arg, y_arg = self._ingest_stream(reducer, lo, hi)
                if reducer is not None:
                    # the sketch merge consumed seqs; re-anchor the
                    # trained-without-allreduce guard at the loop start,
                    # and record the one-off ingestion wire cost so the
                    # bench's per-round payload math can subtract it
                    gang._join_seq = reducer.seq
                    self.status["ingest_payload_bytes"] += (
                        reducer.payload_bytes_sent
                    )
            else:
                x_arg, y_arg = self.x[lo:hi], self.y[lo:hi]
            with activate(gang):
                booster = train(
                    x_arg, y_arg, self.cfg, shard=False,
                    checkpoint_dir=self.ckpt_dir,
                    checkpoint_every=self.checkpoint_every,
                    resume_from=resume,
                )
            if gang.first_round_done_t is not None and gen.gen > 1:
                # generation adopted -> first completed round of the new
                # world: the reshard-to-first-new-round recovery time
                self.status["reshard_to_first_round_s"] = round(
                    gang.first_round_done_t - resume_t0, 4
                )
            self.status["round"] = int(self.cfg.num_iterations)
            if member.ewma_s:
                self.status["rounds_per_s_post"] = round(
                    1.0 / member.ewma_s, 3
                )
            self._write_status()
            return booster
        except BaseException as e:  # noqa: BLE001 — classify, then decide
            abort = gang.abort_reason(e)
            if abort is None:
                if isinstance(e, BarrierTimeoutError) and e.missing:
                    abort = HostLostError(e.missing, gen.gen, "join barrier")
                else:
                    raise
            # fault point train.round_abort: fires as the in-flight round
            # is abandoned; an injected delay stalls the abort -> reshard
            # turnaround (shows up in recovery timings), an error kills
            # the trainer (the supervisor-restart path)
            faults.inject(
                "train.round_abort",
                context={"gen": gen.gen, "cause": type(abort).__name__},
            )
            _M_ABORTS.inc()
            if member.ewma_s:
                # throughput at the old world size, as of the abort —
                # the denominator of "throughput retained after shrink"
                self.status["rounds_per_s_pre"] = round(
                    1.0 / member.ewma_s, 3
                )
            if isinstance(abort, HostLostError):
                try:
                    self._reshard(member, gen, abort)
                except (QuorumLostError, GenerationConflictError) as pe:
                    # the reshard commit could not win a majority (or
                    # lost the CAS): this member is the minority — park,
                    # never fork a minority world
                    self._park(member, gen, pe)
            elif isinstance(
                abort, (QuorumLostError, GenerationConflictError)
            ):
                self._park(member, gen, abort)
            return None
        finally:
            if reducer is not None:
                self.status["retransmits"] += reducer.retransmits
                self.status["payload_bytes"] += reducer.payload_bytes_sent
                self.status["ring_steps"] += reducer.ring_steps
                self.status["allreduce_ops"] += reducer.ops
            gang.close()

    def _ingest_stream(self, reducer: Optional[TcpReducer], lo: int, hi: int):
        """Out-of-core ingestion of this member's ``[lo, hi)`` slice.

        Pass 1 streams the slice through a :class:`QuantileSketch`
        (fixed d x 2^bits counts); the counts are summed across the gang
        by the reducer — the ONLY network the binning costs, chunked
        through the ring like any histogram — and every member derives
        the identical bin bounds. Pass 2 re-streams and bins the slice
        straight into a preallocated uint8 matrix. Peak memory is
        chunk + bins + sketch; the float matrix never materializes."""
        from mmlspark_tpu.models.gbdt.binning import BinnedDataset
        from mmlspark_tpu.models.gbdt.sketch import QuantileSketch

        def slice_chunks(pass_name: str, with_y: bool):
            """Yield ``(x_slice, y_slice_or_None, row0)`` for the parts
            of each chunk inside [lo, hi); shared by both passes so the
            slice arithmetic and the completeness guard can never
            diverge (``with_y`` skips the f64 label conversion on the
            binning pass, which discards labels). A short pass (a
            one-shot generator exhausted by pass 1, a source shrinking
            between passes) fails loudly — np.empty bins would
            otherwise train a garbage model silently."""
            cursor = 0
            for x_chunk, y_chunk in self._stream():
                c0, c1 = cursor, cursor + len(x_chunk)
                cursor = c1
                s0, s1 = max(lo, c0), min(hi, c1)
                if s1 > s0:
                    yield (
                        np.asarray(x_chunk[s0 - c0:s1 - c0]),
                        np.asarray(y_chunk[s0 - c0:s1 - c0], np.float64)
                        if with_y else None,
                        s0 - lo,
                    )
            if cursor != self.n:
                raise RuntimeError(
                    f"stream yielded {cursor} rows on the {pass_name} "
                    f"pass, expected n_rows={self.n} (the source must "
                    "be re-iterable and stable across passes)"
                )

        d = self.n_features
        sk = QuantileSketch(d, bits=self.sketch_bits)
        y_local = np.empty(hi - lo, np.float64)
        for x_sl, y_sl, row0 in slice_chunks("sketch", with_y=True):
            sk.update(x_sl)
            y_local[row0:row0 + len(y_sl)] = y_sl
        mapper = sk.to_binmapper(
            self.cfg.max_bin,
            reduce=reducer.allreduce if reducer is not None else None,
        )
        bins = np.empty((hi - lo, d), np.uint8)
        for x_sl, _y, row0 in slice_chunks("binning", with_y=False):
            mapper.transform_into(x_sl, bins, row0)
        return BinnedDataset(bins, mapper), y_local

    def _resolve_resume_from(self, member: GangMember) -> None:
        """An ``--resume-from artifact:<name>@<digest>[@peer,...]`` seed
        is pulled over HTTP (hash-verified) and unpacked into this
        host's checkpoint dir before the run starts — a fresh host can
        warm-start from a checkpoint it has never had on disk."""
        spec = self.resume_from
        if not spec or not str(spec).startswith("artifact:"):
            return
        if self._store is None:
            raise RuntimeError(
                "--resume-from artifact:… requires --artifact-dir"
            )
        from mmlspark_tpu.serving.artifacts import parse_spec, unpack_dir

        _scheme, name, digest, hints = parse_spec(spec)
        peers = list(hints) + [
            p for p in member.artifact_peers(digest) if p not in hints
        ]
        if not peers:
            # no spec-embedded hint and nobody advertising yet: wait out
            # the heartbeat window before giving up
            peers = self._await_peers(member, digest)
        path = self._store.fetch(
            digest, peers, name=name, timeout_s=self.gen_timeout_s,
        )
        os.makedirs(self.ckpt_dir, exist_ok=True)
        local = os.path.join(self.ckpt_dir, f"pulled-{digest[:16]}")
        unpack_dir(path, local)
        self.status["artifact_fetches"] += 1
        self.resume_from = local

    def _resolve_snapshot(
        self, member: GangMember, gen: Generation
    ) -> Optional[str]:
        """The local directory to resume this generation from, or None
        when the record names no snapshot.

        Shared-dir mode: the recorded path, trusted as before. Artifact
        mode: a path is only *mine* when it lives under MY ``ckpt_dir``
        (per-host disks: the committer's path means nothing here even if
        it happens to be readable); anyone else pulls the content-
        addressed bytes over HTTP from an advertising peer, verifies,
        and unpacks into its own checkpoint dir — the grow-back victim's
        whole recovery story."""
        if not gen.snapshot and not gen.snapshot_digest:
            return None
        if self._store is None:
            return gen.snapshot
        own_root = os.path.realpath(self.ckpt_dir) + os.sep
        if gen.snapshot and os.path.realpath(
            gen.snapshot
        ).startswith(own_root) and os.path.isdir(gen.snapshot):
            return gen.snapshot
        if not gen.snapshot_digest:
            return None
        digest = gen.snapshot_digest
        os.makedirs(self.ckpt_dir, exist_ok=True)
        local = os.path.join(self.ckpt_dir, f"pulled-{digest[:16]}")
        if os.path.isdir(local):
            return local
        try:
            # the committer itself advertises the snapshot; so may other
            # members that pulled it already (replication widens the
            # fan-in). Its advertisement rides the NEXT heartbeat, so an
            # empty peer list right after the commit is a race, not an
            # absence — wait it out before fetching
            peers = self._await_peers(member, digest)
            self._store.fetch(
                digest, peers,
                name=os.path.basename(gen.snapshot or f"ckpt-{digest[:12]}"),
                timeout_s=self.gen_timeout_s,
            )
        except Exception:
            # last resort before dying mid-recovery: this member's OWN
            # checkpoint stream (all_write mode: every member persists)
            # is bit-identical content — but only the EXACT agreed round
            # is safe to stand in for the snapshot (a member resuming
            # from a different round would diverge the gang's sums)
            own = self._own_ckpt_round()
            if own is not None and own == int(gen.resume_round):
                return None  # fall through to resume = self.ckpt_dir
            raise
        from mmlspark_tpu.serving.artifacts import unpack_dir

        unpack_dir(self._store.path(digest), local)
        self.status["artifact_fetches"] += 1
        self._write_status()
        return local

    def _await_peers(self, member: GangMember, digest: str) -> list:
        """Poll the roster until someone advertises ``digest`` (bounded
        by the generation timeout) — debounces the commit-to-heartbeat
        advertisement window."""
        deadline = time.monotonic() + max(
            10.0 * self.heartbeat_s, 5.0
        )
        peers = member.artifact_peers(digest)
        while not peers and time.monotonic() < deadline:
            time.sleep(self.heartbeat_s)
            peers = member.artifact_peers(digest)
        return peers

    def _own_ckpt_round(self) -> Optional[int]:
        try:
            with open(os.path.join(self.ckpt_dir, "LATEST")) as f:
                return int(f.read().strip().rsplit("-", 1)[-1])
        except (OSError, ValueError):
            return None

    def _park(
        self, member: GangMember, gen: Generation, err: Exception,
    ) -> None:
        """The minority-side stance after losing quorum or a CAS race:
        stop training, commit NOTHING, keep heartbeating (the member's
        beat thread runs on), and wait in ``await_generation`` to rejoin
        the winning epoch once the partition heals (grow-back re-admits
        us at the majority coordinator's next checkpoint boundary)."""
        reason = (
            "conflict" if isinstance(err, GenerationConflictError)
            else "quorum"
        )
        faults.inject(
            "elastic.park", context={"gen": gen.gen, "reason": reason}
        )
        _M_PARKS.labels(reason=reason).inc()
        self.status["parked"] = True
        self.status["parks"] += 1
        self.status["park_reasons"].append(reason)
        self._write_status()

    def _reshard(
        self, member: GangMember, gen: Generation, err: HostLostError
    ) -> None:
        """Commit (coordinator) or await the shrunk generation."""
        survivors = sorted(set(gen.members) - set(err.lost))
        if self.name not in survivors:
            return  # evicted/forced out: wait for grow-back
        detect_latency = max(
            (
                time.monotonic() - member.last_seen[m]
                for m in err.lost if m in member.last_seen
            ),
            default=0.0,
        )
        self.status["reshards"] += 1
        self.status["reshard_reasons"].append("lost")
        self.status["detect_latency_s"] = round(detect_latency, 3)
        self._write_status()
        cur = member.read_generation()
        if cur is not None and cur.gen > gen.gen:
            return  # another survivor already committed the next world
        if self.name == survivors[0]:
            # fault point elastic.reshard: an injected error is "the
            # commit refused" — retried until the plan relents
            for attempt in range(100):
                try:
                    faults.inject(
                        "elastic.reshard",
                        context={"gen": gen.gen + 1, "attempt": attempt},
                    )
                    break
                except Exception:  # noqa: BLE001 — injected refusal
                    time.sleep(self.heartbeat_s)
            if member.fenced_out("checkpoint"):
                # the fleet moved past us while we were deciding (a
                # SIGSTOP'd zombie waking after the survivors resharded
                # lands here): refuse to persist the snapshot or commit
                return
            snap, resume_round = snapshot_checkpoint(
                self.ckpt_dir, gen.gen + 1
            )
            digest = None
            if snap is not None and self._store is not None:
                # publish the frozen resume point as a content-addressed
                # artifact: fellow survivors (and the grow-back victim,
                # later) pull these exact bytes over HTTP instead of
                # needing this host's disk mounted
                try:
                    ref = self._store.put(snap, name=os.path.basename(snap))
                    digest = ref.digest
                except Exception:  # noqa: BLE001 — a refused put degrades
                    # to shared-dir semantics rather than blocking recovery
                    digest = None
            self.status.update(snapshot=snap, resume_round=resume_round)
            # replicate-before-commit: fellow survivors hold the frozen
            # resume point BEFORE the shrunk generation is committed —
            # this host dying post-commit strands nothing
            replicate_snapshot(member, digest, survivors, status=self.status)
            member.commit_generation(Generation(
                gen=gen.gen + 1, members=survivors, reason="lost",
                resume_round=resume_round, snapshot=snap,
                snapshot_digest=digest,
                detect_latency_s=round(detect_latency, 3),
            ))
            _M_RESHARDS.labels(reason="lost").inc()
        self._write_status()


# -- data specs for the fleet `train` role ------------------------------------


def load_training_data(spec: str) -> tuple:
    """``synth:<n>x<d>:<seed>`` — the deterministic toy binary dataset
    every host regenerates identically; ``npz:<path>`` — ``x``/``y``
    arrays on a shared filesystem."""
    if spec.startswith("synth:"):
        shape, _, seed = spec[len("synth:"):].partition(":")
        n, _, d = shape.partition("x")
        n, d, seed = int(n), int(d), int(seed or 0)
        r = np.random.default_rng(seed)
        x = r.normal(size=(n, d)).astype(np.float32)
        y = (
            x[:, 0] + 0.5 * x[:, 1] + 0.1 * r.normal(size=n) > 0
        ).astype(np.float64)
        return x, y
    if spec.startswith("npz:"):
        with np.load(spec[len("npz:"):]) as z:
            return np.asarray(z["x"]), np.asarray(z["y"])
    raise ValueError(f"unknown training data spec {spec!r}")


def is_streaming_spec(spec: str) -> bool:
    return str(spec).startswith(("stream-synth:", "stream-csv:"))


def load_streaming_data(spec: str) -> tuple:
    """Out-of-core data specs -> ``(chunk_factory, n_rows, n_features)``.

    - ``stream-synth:<n>x<d>:<seed>[:<chunk>]`` — the synth dataset
      generated chunk-by-chunk: chunk ``i`` draws from
      ``default_rng([seed, i])``, so every host produces the identical
      global row stream without ever holding it (default chunk 65536).
    - ``stream-csv:<path>:<label>[:<chunk>]`` — a CSV streamed through
      :class:`~mmlspark_tpu.io.stream.StreamingDataFrame` (label column
      named; every other numeric column is a feature). ``n``/``d`` come
      from one counting pre-pass (the file is on disk; rows are never
      all resident).
    """
    if spec.startswith("stream-synth:"):
        body = spec[len("stream-synth:"):]
        parts = body.split(":")
        shape = parts[0]
        seed = int(parts[1]) if len(parts) > 1 and parts[1] else 0
        chunk = int(parts[2]) if len(parts) > 2 and parts[2] else 65536
        n_s, _, d_s = shape.partition("x")
        n, d = int(n_s), int(d_s)

        def factory() -> Iterator:
            done = 0
            i = 0
            while done < n:
                c = min(chunk, n - done)
                r = np.random.default_rng([seed, i])
                x = r.normal(size=(c, d)).astype(np.float32)
                y = (
                    x[:, 0] + 0.5 * x[:, 1] + 0.1 * r.normal(size=c) > 0
                ).astype(np.float64)
                yield x, y
                done += c
                i += 1

        return factory, n, d
    if spec.startswith("stream-csv:"):
        from mmlspark_tpu.io.stream import StreamingDataFrame

        body = spec[len("stream-csv:"):]
        parts = body.rsplit(":", 2)
        if len(parts) == 3 and parts[2].isdigit():
            path, label, chunk = parts[0], parts[1], int(parts[2])
        else:
            path, _, label = body.rpartition(":")
            chunk = 65536
        sdf = StreamingDataFrame.from_csv(
            path, chunk_rows=chunk, numeric_only=True
        )
        factory, n, d = stream_from_dataframe(sdf, label)
        return factory, n, d
    raise ValueError(f"unknown streaming data spec {spec!r}")


def stream_from_dataframe(sdf: Any, label_col: str) -> tuple:
    """Adapt a :class:`~mmlspark_tpu.io.stream.StreamingDataFrame` into
    an elastic-trainer chunk factory: every column except ``label_col``
    becomes a feature (sorted-name order, so every host agrees on the
    layout). Returns ``(factory, n_rows, n_features)``; the counting
    pre-pass touches only chunk SHAPES, never accumulates rows."""
    feat_cols: list = []
    n = 0
    for chunk in sdf.iter_chunks():
        if not feat_cols:
            feat_cols = sorted(c for c in chunk.columns if c != label_col)
        n += len(chunk)

    def factory() -> Iterator:
        for chunk in sdf.iter_chunks():
            x = np.stack(
                [np.asarray(chunk[c], np.float32) for c in feat_cols],
                axis=1,
            )
            y = np.asarray(chunk[label_col], np.float64)
            yield x, y

    return factory, n, len(feat_cols)


__all__ = [
    "ElasticTrainer",
    "GangContext",
    "GangMember",
    "Generation",
    "GenerationConflictError",
    "HostLostError",
    "QuorumLostError",
    "StragglerTracker",
    "TcpReducer",
    "WorldChangedError",
    "active_gang",
    "activate",
    "assign_partitions",
    "gang_blocks",
    "gang_sum",
    "gang_voting_k",
    "is_streaming_spec",
    "load_streaming_data",
    "load_training_data",
    "member_row_slice",
    "partition_bounds",
    "replicate_snapshot",
    "snapshot_checkpoint",
    "stream_from_dataframe",
]
