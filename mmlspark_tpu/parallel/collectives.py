"""Collective wrappers over ICI/DCN.

The TPU-native replacement for all three of the reference's communication
backends (SURVEY.md §5.8): LightGBM's socket ring allreduce
(TrainUtils.scala:496-512), VW's driver spanning tree
(VowpalWabbitBase.scala:401-429) and the hand-rolled driver TCP rendezvous
(LightGBMUtils.scala:116-185) all collapse into XLA collectives on a named
mesh axis — gang semantics come from SPMD program launch, not barriers.

Use inside ``shard_map``-ped / ``pmap``-ped functions.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from mmlspark_tpu.parallel.compat import axis_size, shard_map
from mmlspark_tpu.parallel.mesh import DATA_AXIS, get_mesh


def allreduce_sum(x: Any, axis: str = DATA_AXIS) -> Any:
    return jax.lax.psum(x, axis_name=axis)


def allreduce_mean(x: Any, axis: str = DATA_AXIS) -> Any:
    return jax.lax.pmean(x, axis_name=axis)


def allreduce_max(x: Any, axis: str = DATA_AXIS) -> Any:
    return jax.lax.pmax(x, axis_name=axis)


def all_gather(x: Any, axis: str = DATA_AXIS, tiled: bool = True) -> Any:
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x: Any, axis: str = DATA_AXIS) -> Any:
    return jax.lax.psum_scatter(x, axis_name=axis, tiled=True)


def ring_permute(x: Any, axis: str = DATA_AXIS, shift: int = 1) -> Any:
    """Neighbor exchange on the ring (building block for ring attention /
    pipelined allreduce)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str = DATA_AXIS) -> jnp.ndarray:
    return jax.lax.axis_index(axis)


def shard_apply(
    fn: Callable,
    mesh: Optional[Mesh] = None,
    in_specs: Any = P(DATA_AXIS),
    out_specs: Any = P(DATA_AXIS),
) -> Callable:
    """``shard_map`` convenience wrapper bound to the default mesh.

    Replication checking is off (as at every other shard_map site here):
    the pmean-in-scan-carry pattern (vw/learner.py) legitimately moves
    arrays between replicated and varying, which the old-jax ``check_rep``
    tracker cannot type."""
    mesh = mesh or get_mesh()
    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
