"""Device mesh management — the cluster-topology layer.

Replaces the reference's ``ClusterUtil`` executor/task discovery
(core/utils/ClusterUtil.scala:13-177): where MMLSpark sizes its gang by
querying the BlockManager for executors x cores, the TPU framework sizes
SPMD programs by the JAX device mesh (hosts x chips over ICI/DCN).

Axis conventions:
- ``data``  — batch (data-parallel) axis; collectives ride ICI.
- ``model`` — tensor-parallel axis for backbones exceeding one chip's HBM.
A 1-D ``data`` mesh is the default, matching the reference's rows-only
parallelism (SURVEY.md §2.18).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

_default_mesh: Optional[Mesh] = None


def make_mesh(
    shape: Optional[dict] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """Build a mesh. ``shape`` maps axis name -> size; one size may be -1
    (inferred). Default: all devices on a 1-D ``data`` axis."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not shape:
        shape = {DATA_AXIS: n}
    names = list(shape.keys())
    sizes = list(shape.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if -1 in sizes:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} != {n} devices")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, axis_names=tuple(names))


def get_mesh() -> Mesh:
    """The process-wide default mesh (created on first use)."""
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


def device_count() -> int:
    return len(jax.devices())


def local_device_count() -> int:
    return jax.local_device_count()


def cluster_summary() -> dict:
    """Topology report (the ``ClusterUtil.getExecutors`` analogue)."""
    devs = jax.devices()
    hosts: dict = {}
    for d in devs:
        hosts.setdefault(d.process_index, []).append(d.id)
    return {
        "platform": devs[0].platform,
        "num_devices": len(devs),
        "num_hosts": jax.process_count(),
        "host_devices": {str(k): v for k, v in sorted(hosts.items())},
        "process_index": jax.process_index(),
    }


def data_sharding(mesh: Mesh, ndim: int, axis: str = DATA_AXIS) -> NamedSharding:
    """Sharding that splits axis 0 (batch) over ``axis``, replicating the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
