"""mmlspark_tpu: a TPU-native ML pipeline framework.

A ground-up rebuild of the capabilities of MMLSpark (Microsoft Machine
Learning for Apache Spark) designed for TPU hardware: DataFrame pipelines
whose compute stages lower to jitted XLA programs, distributed via
``jax.sharding`` meshes and ICI/DCN collectives instead of JVM sockets.

Reference capability map: see SURVEY.md at the repo root. The reference
(``/root/reference``, MMLSpark ~1.0.0-rc2) provides SparkML-compatible
estimators/transformers embedding native engines (CNTK, LightGBM, VW,
OpenCV); here those engines are rebuilt TPU-first (JAX/XLA/Pallas) with a
lightweight partitioned-columnar DataFrame as the dataflow substrate.
"""

from mmlspark_tpu.version import __version__

from mmlspark_tpu.core.dataframe import DataFrame, Row
from mmlspark_tpu.core.pipeline import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    Transformer,
    load_stage,
)

__all__ = [
    "__version__",
    "DataFrame",
    "Row",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "load_stage",
]
