"""Plotting helpers (reference: src/main/python/mmlspark/plot/plot.py —
confusion matrix + feature importance; ROC added since
ComputeModelStatistics emits the curve).

Matplotlib is imported lazily so headless/serving deployments never pay
for it; every function accepts an optional ``ax`` and returns it.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np


def _ax(ax: Any = None) -> Any:
    # never touch the global backend — the caller's session owns that choice
    import matplotlib.pyplot as plt

    return ax if ax is not None else plt.subplots()[1]


def confusion_matrix(
    y_true: Sequence,
    y_pred: Sequence,
    labels: Optional[Sequence] = None,
    normalize: bool = False,
    ax: Any = None,
) -> Any:
    """Heatmap of the confusion matrix with counts annotated."""
    y_true = np.asarray(y_true)
    y_pred = np.asarray(y_pred)
    if labels is None:
        labels = np.unique(np.concatenate([y_true, y_pred]))
    k = len(labels)
    label_arr = np.asarray(labels)
    # vectorized lookup that respects the caller's label ORDER: search the
    # sorted view, then map positions back through the sorter
    sorter = np.argsort(label_arr, kind="stable")
    sl = label_arr[sorter]
    tpos = np.clip(np.searchsorted(sl, y_true), 0, k - 1)
    ppos = np.clip(np.searchsorted(sl, y_pred), 0, k - 1)
    # pairs outside the explicit label list are skipped (sklearn behavior)
    ok = (sl[tpos] == y_true) & (sl[ppos] == y_pred)
    cm = np.zeros((k, k), np.float64)
    np.add.at(cm, (sorter[tpos][ok], sorter[ppos][ok]), 1.0)
    if normalize:
        cm = cm / np.maximum(cm.sum(axis=1, keepdims=True), 1)

    ax = _ax(ax)
    im = ax.imshow(cm, cmap="Blues")
    ax.figure.colorbar(im, ax=ax)
    ax.set_xticks(range(k), [str(v) for v in labels], rotation=45)
    ax.set_yticks(range(k), [str(v) for v in labels])
    ax.set_xlabel("predicted")
    ax.set_ylabel("actual")
    thresh = cm.max() / 2 if cm.size else 0
    for i in range(k):
        for j in range(k):
            val = f"{cm[i, j]:.2f}" if normalize else f"{int(cm[i, j])}"
            ax.text(j, i, val, ha="center",
                    color="white" if cm[i, j] > thresh else "black")
    ax.set_title("confusion matrix")
    return ax


def feature_importance(
    importances: Sequence[float],
    feature_names: Optional[Sequence[str]] = None,
    top_n: int = 20,
    ax: Any = None,
) -> Any:
    """Horizontal bar chart of the top-N most important features
    (pairs with ``LightGBM*Model.get_feature_importances``)."""
    imp = np.asarray(importances, np.float64)
    if feature_names is None:
        feature_names = [f"f{i}" for i in range(len(imp))]
    order = np.argsort(-imp)[:top_n][::-1]
    ax = _ax(ax)
    ax.barh(range(len(order)), imp[order])
    ax.set_yticks(range(len(order)), [str(feature_names[i]) for i in order])
    ax.set_xlabel("importance")
    ax.set_title("feature importance")
    return ax


def roc_curve(
    y_true: Sequence[int],
    scores: Sequence[float],
    ax: Any = None,
) -> Any:
    """ROC curve with AUC in the legend (binary labels, higher score =
    positive)."""
    y = np.asarray(y_true).astype(int)
    s = np.asarray(scores, np.float64)
    order = np.argsort(-s)
    y, s = y[order], s[order]
    tps = np.cumsum(y)
    fps = np.cumsum(1 - y)
    # collapse tied scores to one operating point (the curve is defined per
    # threshold, not per row — tie groups otherwise distort the AUC)
    last_of_group = np.concatenate([s[1:] != s[:-1], [True]])
    tps, fps = tps[last_of_group], fps[last_of_group]
    p, n = max(int(y.sum()), 1), max(int((1 - y).sum()), 1)
    tpr = np.concatenate([[0.0], tps / p])
    fpr = np.concatenate([[0.0], fps / n])
    auc = float(np.trapezoid(tpr, fpr))
    ax = _ax(ax)
    ax.plot(fpr, tpr, label=f"AUC = {auc:.3f}")
    ax.plot([0, 1], [0, 1], linestyle="--", linewidth=0.8)
    ax.set_xlabel("false positive rate")
    ax.set_ylabel("true positive rate")
    ax.legend()
    ax.set_title("ROC")
    return ax
