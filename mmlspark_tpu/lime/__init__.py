"""Model interpretability — LIME (reference: lime/, SURVEY.md §2.13).

The reference explains predictions by sampling perturbed inputs, scoring
them with the model, and fitting a local sparse linear surrogate per row
(LIME.scala:30-41, LassoUtils.lasso at BreezeUtils.scala:112). Here the
whole local problem is device-resident: mask/sample generation, image
censoring, and the lasso solve are jitted (the lasso is ISTA under
``lax.scan``, vmappable over explanation rows); only the inner model call
crosses back through the pipeline API.
"""

from mmlspark_tpu.lime.lasso import lasso, batched_lasso
from mmlspark_tpu.lime.superpixel import Superpixel, SuperpixelTransformer, slic
from mmlspark_tpu.lime.lime import ImageLIME, TabularLIME, TabularLIMEModel

__all__ = [
    "lasso",
    "batched_lasso",
    "slic",
    "Superpixel",
    "SuperpixelTransformer",
    "TabularLIME",
    "TabularLIMEModel",
    "ImageLIME",
]
