"""Device lasso solver for LIME local surrogates.

Reference: ``LassoUtils.lasso`` (lime/BreezeUtils.scala:112) solves one
small dense lasso per explained row on the JVM. TPU version: ISTA with a
Lipschitz step from the Gram spectral bound, fixed iteration count under
``lax.scan`` (static shapes, no data-dependent control flow), and a
``vmap`` wrapper so a whole batch of per-row problems solves as one
compiled program.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(3,))
def lasso(x: jnp.ndarray, y: jnp.ndarray, lam: float, iters: int = 300) -> jnp.ndarray:
    """min_b 0.5/n ||x b - y||^2 + lam ||b||_1  (returns b, shape (d,)).

    Columns are used as-is (LIME states are already comparable scales:
    binary masks or standardized features).
    """
    n = x.shape[0]
    # center columns and targets = fit an (unpenalized) intercept, so a
    # constant model output attributes zero weight everywhere
    x = x - x.mean(axis=0, keepdims=True)
    y = y - y.mean()
    gram = x.T @ x / n
    xty = x.T @ y / n
    # power iteration for the Lipschitz constant (largest gram eigenvalue)
    def pow_step(v, _):
        v = gram @ v
        return v / (jnp.linalg.norm(v) + 1e-12), None

    v0 = jnp.ones((x.shape[1],), x.dtype) / jnp.sqrt(x.shape[1])
    v, _ = jax.lax.scan(pow_step, v0, None, length=16)
    lip = jnp.maximum(v @ (gram @ v), 1e-6)
    step = 1.0 / lip

    def ista_step(b, _):
        g = gram @ b - xty
        b = b - step * g
        b = jnp.sign(b) * jnp.maximum(jnp.abs(b) - step * lam, 0.0)
        return b, None

    b0 = jnp.zeros((x.shape[1],), x.dtype)
    b, _ = jax.lax.scan(ista_step, b0, None, length=iters)
    return b


batched_lasso = jax.jit(
    jax.vmap(lasso, in_axes=(0, 0, None, None)), static_argnums=(3,)
)
