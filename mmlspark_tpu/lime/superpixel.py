"""SLIC superpixel clustering + transformer stage.

Reference: ``Superpixel`` (lime/Superpixel.scala) does SLIC-style
clustering in the JVM, one pixel-walk at a time; ``SuperpixelTransformer``
attaches the clustering as a column. TPU version: fixed-iteration SLIC as
one jitted program — grid-seeded centers, joint (position, color) distance,
``segment_sum`` center updates — so every pixel-to-center distance rides
the VPU/MXU and the loop is ``lax.scan``.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param
from mmlspark_tpu.core.pipeline import Transformer


@partial(jax.jit, static_argnums=(1, 2, 3))
def slic(
    image: jnp.ndarray, n_segments: int = 64, compactness: float = 10.0, iters: int = 10
) -> jnp.ndarray:
    """SLIC over one (H, W, C) image -> (H, W) int32 label map.

    Joint feature = [compactness/S * (y, x), channels]; centers seeded on a
    sqrt(n_segments) grid; `iters` rounds of assign + segment-mean update.
    """
    h, w, c = image.shape
    img = image.astype(jnp.float32)
    gy = int(np.sqrt(n_segments))
    gx = int(np.ceil(n_segments / gy))
    k = gy * gx
    s = float(np.sqrt(h * w / k))  # nominal superpixel spacing

    yy, xx = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij")
    spatial_scale = compactness / s
    feats = jnp.concatenate(
        [
            (yy * spatial_scale)[..., None],
            (xx * spatial_scale)[..., None],
            img,
        ],
        axis=-1,
    ).reshape(h * w, c + 2)

    cy = (jnp.arange(gy, dtype=jnp.float32) + 0.5) * (h / gy)
    cx = (jnp.arange(gx, dtype=jnp.float32) + 0.5) * (w / gx)
    cyy, cxx = jnp.meshgrid(cy, cx, indexing="ij")
    ci = jnp.clip(cyy.reshape(-1).astype(jnp.int32), 0, h - 1)
    cj = jnp.clip(cxx.reshape(-1).astype(jnp.int32), 0, w - 1)
    centers = feats.reshape(h, w, c + 2)[ci, cj]  # (k, c+2)

    def step(centers: jnp.ndarray, _: Any) -> tuple:
        # ||f - c||^2 via the matmul expansion: (P, k) memory, MXU compute
        d2 = (
            (feats**2).sum(-1)[:, None]
            + (centers**2).sum(-1)[None, :]
            - 2.0 * feats @ centers.T
        )
        assign = jnp.argmin(d2, axis=1)
        sums = jax.ops.segment_sum(feats, assign, num_segments=k)
        counts = jax.ops.segment_sum(jnp.ones((h * w,), jnp.float32), assign, num_segments=k)
        new_centers = sums / jnp.maximum(counts, 1.0)[:, None]
        # empty clusters keep their previous center
        new_centers = jnp.where((counts > 0)[:, None], new_centers, centers)
        return new_centers, assign

    centers, assigns = jax.lax.scan(step, centers, None, length=iters)
    return assigns[-1].reshape(h, w).astype(jnp.int32)


class Superpixel:
    """Host-facing helper mirroring the reference's Superpixel object:
    cluster one image and mask it by per-cluster on/off states."""

    @staticmethod
    def cluster(
        image: np.ndarray, n_segments: int = 64, compactness: float = 10.0, iters: int = 10
    ) -> np.ndarray:
        return np.asarray(slic(jnp.asarray(image), n_segments, compactness, iters))

    @staticmethod
    def mask_image(image: np.ndarray, labels: np.ndarray, states: np.ndarray) -> np.ndarray:
        """Keep pixels whose superpixel state is on; censor the rest to 0
        (the reference blacks out off clusters)."""
        on = np.asarray(states, bool)[np.asarray(labels)]
        return np.where(on[..., None], image, 0).astype(image.dtype)


class SuperpixelTransformer(Transformer, HasInputCol, HasOutputCol):
    """Attach an (H, W) superpixel label map for each image row
    (lime/SuperpixelTransformer in the reference)."""

    cell_size = Param("approximate superpixel diameter in pixels", default=16.0, type_=float)
    compactness = Param("SLIC compactness (spatial vs color weight)", default=10.0, type_=float)
    iters = Param("SLIC iterations", default=10, type_=int)

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "output_col" not in self._paramMap:
            self.set(output_col="superpixels")

    def transform(self, df: DataFrame) -> DataFrame:
        ic, oc = self.get_or_fail("input_col"), self.get("output_col")
        cell = self.get("cell_size")

        def fn(p: dict) -> dict:
            images = p[ic]
            out = np.empty(len(images), dtype=object)
            for i, img in enumerate(images):
                img = np.asarray(img)
                n_seg = max(1, int((img.shape[0] * img.shape[1]) / (cell * cell)))
                out[i] = Superpixel.cluster(
                    img, n_seg, self.get("compactness"), self.get("iters")
                )
            q = dict(p)
            q[oc] = out
            return q

        return df.map_partitions(fn, parallel=False)
