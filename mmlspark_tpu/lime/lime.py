"""TabularLIME / ImageLIME stages.

Reference flow (lime/LIME.scala:30-106): per explained row, sample
perturbed inputs, score them with the wrapped model (held in a
TransformerParam), fit a lasso from perturbation states to predictions,
emit the coefficient vector. TabularLIME samples feature vectors from
per-column train statistics; ImageLIME samples binary on/off states over
superpixels and censors the image accordingly.

TPU-first: sampling, censoring, and the lasso are device programs with
static shapes (n_samples fixed at param level); the inner model sees ONE
DataFrame of all samples per partition batch, so its own jitted stages see
large uniform batches instead of per-row trickles.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.core.params import (
    ComplexParam,
    HasInputCol,
    HasOutputCol,
    HasPredictionCol,
    Param,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.lime.lasso import batched_lasso, lasso
from mmlspark_tpu.lime.superpixel import Superpixel, slic
from functools import partial


@partial(jax.jit, static_argnums=(3,))
def _make_tabular_samples(
    key: jnp.ndarray, rows: jnp.ndarray, stds: jnp.ndarray, n_samp: int
) -> tuple:
    """(R, d) rows -> (R, S, d) gaussian perturbations + standardized states."""
    eps = jax.random.normal(key, (rows.shape[0], n_samp, rows.shape[1]), jnp.float32)
    samples = rows[:, None, :] + eps * stds[None, None, :]
    return samples, eps


@jax.jit
def _censor_images(img: jnp.ndarray, labels: jnp.ndarray, states: jnp.ndarray) -> jnp.ndarray:
    # states: (S, K) {0,1}; labels: (H, W) -> (S, H, W, C) censored
    on = states[:, labels]  # (S, H, W)
    return img[None] * on[..., None]


class _LIMEParams(HasInputCol, HasOutputCol, HasPredictionCol):
    model = ComplexParam("inner Transformer to explain")
    n_samples = Param("perturbed samples per explained row", default=512, type_=int)
    regularization = Param("lasso L1 strength", default=0.001, type_=float)
    seed = Param("PRNG seed", default=0, type_=int)

    def _predict_samples(self, samples_df: DataFrame) -> np.ndarray:
        """Run the wrapped model; reduce its prediction column to (n,) floats."""
        inner = self.get_or_fail("model")
        scored = inner.transform(samples_df)
        # follow the wrapped model's own prediction column unless overridden
        if self.is_set("prediction_col"):
            pc = self.get("prediction_col")
        else:
            try:
                pc = inner.get("prediction_col") or self.get("prediction_col")
            except KeyError:  # inner stage declares no prediction_col param
                pc = self.get("prediction_col")
        pred = np.asarray(scored[pc])
        if pred.ndim == 2:  # probability vector: explain class 1 like the reference
            pred = pred[:, min(1, pred.shape[1] - 1)]
        return pred.astype(np.float32)


class TabularLIME(Estimator, _LIMEParams):
    """fit() learns per-column sampling statistics (mean/std of each
    feature over the train set); the model does the per-row explanations."""

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "output_col" not in self._paramMap:
            self.set(output_col="weights")

    def fit(self, df: DataFrame) -> "TabularLIMEModel":
        x = np.asarray(df[self.get_or_fail("input_col")], np.float64)
        m = TabularLIMEModel(**{k: v for k, v in self._paramMap.items()})
        m.set(feature_stds=(x.std(axis=0) + 1e-9).astype(np.float32))
        return m


class TabularLIMEModel(Model, _LIMEParams):
    feature_stds = ComplexParam("(d,) train-set feature stds (sampling scale)")

    def transform(self, df: DataFrame) -> DataFrame:
        ic = self.get_or_fail("input_col")
        n_samp = self.get("n_samples")
        lam = self.get("regularization")
        stds = jnp.asarray(self.get_or_fail("feature_stds"))

        rows = np.asarray(df[ic], np.float32)
        if len(rows) == 0:
            return df.with_column(self.get("output_col"), np.empty(0, dtype=object))
        key = jax.random.PRNGKey(self.get("seed"))
        # all rows' perturbations in one device program, ONE inner-model call
        # over the flattened (R*S, d) sample matrix, one vmapped lasso solve
        samples, states = _make_tabular_samples(key, jnp.asarray(rows), stds, n_samp)
        flat = np.asarray(samples).reshape(len(rows) * n_samp, rows.shape[1])
        preds = self._predict_samples(DataFrame.from_dict({ic: flat}))
        preds = jnp.asarray(preds).reshape(len(rows), n_samp)
        coefs = np.asarray(batched_lasso(states, preds, lam, 300))
        out = np.empty(len(rows), dtype=object)
        for i in range(len(rows)):
            out[i] = coefs[i]
        return df.with_column(self.get("output_col"), out)


class ImageLIME(Transformer, _LIMEParams):
    """Explain an image model by superpixel on/off lasso
    (lime/ImageLIME in the reference). Emits the per-superpixel
    coefficient vector plus the label map used."""

    cell_size = Param("approximate superpixel diameter", default=16.0, type_=float)
    compactness = Param("SLIC compactness", default=10.0, type_=float)
    sampling_fraction = Param("P(superpixel stays on) per sample", default=0.7, type_=float)
    superpixel_col = Param("output column for the label map", default="superpixels")

    def __init__(self, **kw: Any):
        super().__init__(**kw)
        if "output_col" not in self._paramMap:
            self.set(output_col="weights")

    def transform(self, df: DataFrame) -> DataFrame:
        ic = self.get_or_fail("input_col")
        n_samp = self.get("n_samples")
        frac = self.get("sampling_fraction")
        lam = self.get("regularization")
        cell = self.get("cell_size")

        images = df[ic]
        weights_out = np.empty(len(images), dtype=object)
        labels_out = np.empty(len(images), dtype=object)
        key = jax.random.PRNGKey(self.get("seed"))

        for i, img in enumerate(images):
            img = np.asarray(img, np.float32)
            n_seg = max(2, int((img.shape[0] * img.shape[1]) / (cell * cell)))
            labels = slic(jnp.asarray(img), n_seg, self.get("compactness"))
            k = int(np.asarray(labels).max()) + 1
            key, sub = jax.random.split(key)
            states = jax.random.bernoulli(sub, frac, (n_samp, k)).astype(jnp.float32)
            censored = _censor_images(jnp.asarray(img), labels, states)
            preds = self._predict_samples(DataFrame.from_dict({ic: np.asarray(censored)}))
            coefs = lasso(states, jnp.asarray(preds), lam)
            weights_out[i] = np.asarray(coefs)
            labels_out[i] = np.asarray(labels)

        out = df.with_column(self.get("output_col"), weights_out)
        return out.with_column(self.get("superpixel_col"), labels_out)
