"""Search integrations: Bing-style image search + Azure-Search-style sink
(cognitive/BingImageSearch.scala, AzureSearch.scala analogues)."""

from __future__ import annotations

import concurrent.futures as _futures
import json
import urllib.parse
from typing import Any, List, Optional, Sequence

from mmlspark_tpu.cognitive import schemas as S
from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.clients import AdvancedHandler
from mmlspark_tpu.io.http_schema import HTTPRequestData
from mmlspark_tpu.io.parsers import _to_jsonable


class BingImageSearch(CognitiveServiceBase):
    """Query column -> image-search results (GET /images/search?q=...)."""

    query = ServiceParam("search query (value or column)")
    count = ServiceParam("results per query", default={"value": 10})
    offset = ServiceParam("result offset", default={"value": 0})
    image_type = ServiceParam("imageType filter")

    def _build_request(self, vals: dict) -> Optional[dict]:
        q = vals.get("query")
        if q is None:
            return None
        parts = [
            "q=" + urllib.parse.quote(str(q)),
            f"count={int(vals.get('count') or 10)}",
            f"offset={int(vals.get('offset') or 0)}",
        ]
        if vals.get("image_type"):
            parts.append("imageType=" + vals["image_type"])
        url = self.get_or_fail("url").rstrip("/") + "/images/search?" + "&".join(parts)
        headers = {}
        key = self._resolve("subscription_key", vals)
        if key:
            headers["Ocp-Apim-Subscription-Key"] = key
        return HTTPRequestData(url, "GET", headers)

    _response_schema = List[S.BingImage]

    def _project_response(self, obj: Any) -> Any:
        from typing import List as _L

        return S.from_json(_L[S.BingImage], (obj or {}).get("value"))

    @staticmethod
    def downloadFromUrls(
        df: DataFrame, url_col: str, bytes_col: str = "bytes",
        concurrency: int = 8, timeout: float = 30.0,
    ) -> DataFrame:
        """Fetch each URL into a bytes column (the reference's
        BingImageSearch.downloadFromUrls helper)."""
        from mmlspark_tpu.io.clients import send_request

        def fn(p: dict) -> dict:
            import numpy as np

            urls = list(p[url_col])
            out = np.empty(len(urls), dtype=object)
            with _futures.ThreadPoolExecutor(max_workers=concurrency) as pool:
                resps = pool.map(
                    lambda u: send_request(
                        {"url": u, "method": "GET", "headers": {}}, timeout=timeout
                    ) if u else None,
                    urls,
                )
                for i, r in enumerate(resps):
                    out[i] = r["entity"] if r and r["status_code"] // 100 == 2 else None
            q = dict(p)
            q[bytes_col] = out
            return q

        return df.map_partitions(fn)


class AzureSearchWriter:
    """Batch-upload DataFrame rows as documents to a search index
    (AzureSearch.scala AddDocuments analogue): POST
    ``{"value": [{"@search.action": ..., **doc}, ...]}`` to
    ``{url}/indexes/{index}/docs/index``."""

    @staticmethod
    def write(
        df: DataFrame,
        url: str,
        index_name: str,
        key: Optional[str] = None,
        action: str = "upload",
        action_col: Optional[str] = None,
        batch_size: int = 100,
        api_version: str = "2019-05-06",
        timeout: float = 30.0,
    ) -> list:
        rows = [dict(r) for r in df.collect()]
        endpoint = (
            url.rstrip("/") + f"/indexes/{index_name}/docs/index"
            f"?api-version={api_version}"
        )
        headers = {"Content-Type": "application/json"}
        if key:
            headers["api-key"] = key
        handler = AdvancedHandler(timeout=timeout)
        batches = [rows[i: i + batch_size] for i in range(0, len(rows), batch_size)]
        resps = []
        for batch in batches:
            docs = []
            for r in batch:
                doc = {k: _to_jsonable(v) for k, v in r.items() if k != action_col}
                doc["@search.action"] = (
                    str(r[action_col]) if action_col else action
                )
                docs.append(doc)
            resp = handler(
                HTTPRequestData(endpoint, "POST", headers, json.dumps({"value": docs}))
            )
            if resp["status_code"] // 100 != 2:
                raise RuntimeError(
                    f"AzureSearchWriter: batch failed "
                    f"{resp['status_code']} {resp['reason']}"
                )
            resps.append(resp)
        return resps
