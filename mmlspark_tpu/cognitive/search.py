"""Search integrations: Bing-style image search + Azure-Search-style sink
(cognitive/BingImageSearch.scala, AzureSearch.scala analogues)."""

from __future__ import annotations

import concurrent.futures as _futures
import json
import urllib.parse
from typing import Any, List, Optional, Sequence

from mmlspark_tpu.cognitive import schemas as S
from mmlspark_tpu.cognitive.base import CognitiveServiceBase, ServiceParam
from mmlspark_tpu.core.dataframe import DataFrame
from mmlspark_tpu.io.clients import AdvancedHandler
from mmlspark_tpu.io.http_schema import HTTPRequestData
from mmlspark_tpu.io.parsers import _to_jsonable


class BingImageSearch(CognitiveServiceBase):
    """Query column -> image-search results (GET /images/search?q=...)."""

    query = ServiceParam("search query (value or column)")
    count = ServiceParam("results per query", default={"value": 10})
    offset = ServiceParam("result offset", default={"value": 0})
    image_type = ServiceParam("imageType filter")

    def _build_request(self, vals: dict) -> Optional[dict]:
        q = vals.get("query")
        if q is None:
            return None
        parts = [
            "q=" + urllib.parse.quote(str(q)),
            f"count={int(vals.get('count') or 10)}",
            f"offset={int(vals.get('offset') or 0)}",
        ]
        if vals.get("image_type"):
            parts.append("imageType=" + vals["image_type"])
        url = self.get_or_fail("url").rstrip("/") + "/images/search?" + "&".join(parts)
        headers = {}
        key = self._resolve("subscription_key", vals)
        if key:
            headers["Ocp-Apim-Subscription-Key"] = key
        return HTTPRequestData(url, "GET", headers)

    _response_schema = List[S.BingImage]

    def _project_response(self, obj: Any) -> Any:
        from typing import List as _L

        return S.from_json(_L[S.BingImage], (obj or {}).get("value"))

    @staticmethod
    def downloadFromUrls(
        df: DataFrame, url_col: str, bytes_col: str = "bytes",
        concurrency: int = 8, timeout: float = 30.0,
    ) -> DataFrame:
        """Fetch each URL into a bytes column (the reference's
        BingImageSearch.downloadFromUrls helper)."""
        from mmlspark_tpu.io.clients import send_request

        def fn(p: dict) -> dict:
            import numpy as np

            urls = list(p[url_col])
            out = np.empty(len(urls), dtype=object)
            with _futures.ThreadPoolExecutor(max_workers=concurrency) as pool:
                resps = pool.map(
                    lambda u: send_request(
                        {"url": u, "method": "GET", "headers": {}}, timeout=timeout
                    ) if u else None,
                    urls,
                )
                for i, r in enumerate(resps):
                    out[i] = r["entity"] if r and r["status_code"] // 100 == 2 else None
            q = dict(p)
            q[bytes_col] = out
            return q

        return df.map_partitions(fn)


class AzureSearchWriter:
    """Batch-upload DataFrame rows as documents to a search index
    (AzureSearch.scala AddDocuments analogue): POST
    ``{"value": [{"@search.action": ..., **doc}, ...]}`` to
    ``{url}/indexes/{index}/docs/index``."""

    @staticmethod
    def write(
        df: DataFrame,
        url: str,
        index_name: str,
        key: Optional[str] = None,
        action: str = "upload",
        action_col: Optional[str] = None,
        batch_size: int = 100,
        api_version: str = "2019-05-06",
        timeout: float = 30.0,
    ) -> list:
        rows = [dict(r) for r in df.collect()]
        endpoint = (
            url.rstrip("/") + f"/indexes/{index_name}/docs/index"
            f"?api-version={api_version}"
        )
        headers = {"Content-Type": "application/json"}
        if key:
            headers["api-key"] = key
        handler = AdvancedHandler(timeout=timeout)
        batches = [rows[i: i + batch_size] for i in range(0, len(rows), batch_size)]
        resps = []
        for batch in batches:
            docs = []
            for r in batch:
                doc = {k: _to_jsonable(v) for k, v in r.items() if k != action_col}
                doc["@search.action"] = (
                    str(r[action_col]) if action_col else action
                )
                docs.append(doc)
            resp = handler(
                HTTPRequestData(endpoint, "POST", headers, json.dumps({"value": docs}))
            )
            if resp["status_code"] // 100 != 2:
                raise RuntimeError(
                    f"AzureSearchWriter: batch failed "
                    f"{resp['status_code']} {resp['reason']}"
                )
            resps.append(resp)
        return resps


# -- index management (AzureSearchAPI.scala:16-150) ---------------------------

EDM_TYPES = (
    "Edm.String", "Collection(Edm.String)", "Edm.Boolean", "Edm.Int32",
    "Edm.Int64", "Edm.Double", "Edm.DateTimeOffset", "Edm.GeographyPoint",
    "Edm.ComplexType",
)


class SearchIndex:
    """Index lifecycle for the search sink (SearchIndex object in
    AzureSearchAPI.scala: ``getExisting`` lists index names,
    ``createIfNoneExists`` validates the index JSON field by field and
    creates the index only when absent). ``url`` is the service endpoint
    (the reference builds it from a service name; local mocks pass a full
    URL)."""

    DEFAULT_API_VERSION = "2019-05-06"

    @staticmethod
    def validate_index(index: dict) -> dict:
        """Field-by-field validation (validIndexJson/validIndexField):
        non-empty names, known EDM types, exactly one Edm.String key
        field, and the searchable/sortable/facetable type constraints."""
        if not index.get("name"):
            raise ValueError("index needs a non-empty 'name'")
        fields = index.get("fields") or []
        if not fields:
            raise ValueError("index needs at least one field")
        keys = 0
        for f in fields:
            name = f.get("name")
            if not name:
                raise ValueError("every field needs a non-empty 'name'")
            t = f.get("type")
            if t not in EDM_TYPES:
                raise ValueError(
                    f"field {name!r}: unknown EDM type {t!r} "
                    f"(expected one of {EDM_TYPES})"
                )
            if f.get("searchable") and t not in (
                "Edm.String", "Collection(Edm.String)"
            ):
                raise ValueError(
                    f"field {name!r}: only Edm.String and "
                    "Collection(Edm.String) fields can be searchable"
                )
            if f.get("sortable") and t == "Collection(Edm.String)":
                raise ValueError(
                    f"field {name!r}: Collection(Edm.String) fields "
                    "cannot be sortable"
                )
            if f.get("facetable") and t == "Edm.GeographyPoint":
                raise ValueError(
                    f"field {name!r}: Edm.GeographyPoint fields "
                    "cannot be facetable"
                )
            if f.get("key"):
                keys += 1
                if t != "Edm.String":
                    raise ValueError(
                        f"field {name!r}: the key field must be Edm.String"
                    )
        if keys != 1:
            raise ValueError(f"index needs exactly one key field, got {keys}")
        return index

    @classmethod
    def get_existing(
        cls, url: str, key: Optional[str] = None,
        api_version: str = DEFAULT_API_VERSION, timeout: float = 30.0,
    ) -> list:
        headers = {"api-key": key} if key else {}
        # same 429/5xx retry policy as the create POST below
        resp = AdvancedHandler(timeout=timeout)(
            HTTPRequestData(
                url.rstrip("/")
                + f"/indexes?api-version={api_version}&$select=name",
                "GET", headers,
            )
        )
        if resp["status_code"] // 100 != 2:
            raise RuntimeError(
                f"SearchIndex.get_existing: {resp['status_code']} {resp['reason']}"
            )
        body = json.loads(resp["entity"] or b"{}")
        return [i.get("name") for i in body.get("value") or []]

    @classmethod
    def create_if_none_exists(
        cls, url: str, index: Any, key: Optional[str] = None,
        api_version: str = DEFAULT_API_VERSION, timeout: float = 30.0,
    ) -> bool:
        """Create the (validated) index when absent; returns True when a
        create happened (createIfNoneExists asserts the 201 the same way)."""
        if isinstance(index, str):
            index = json.loads(index)
        cls.validate_index(index)
        if index["name"] in cls.get_existing(url, key, api_version, timeout):
            return False
        headers = {"Content-Type": "application/json"}
        if key:
            headers["api-key"] = key
        resp = AdvancedHandler(timeout=timeout)(
            HTTPRequestData(
                url.rstrip("/") + f"/indexes?api-version={api_version}",
                "POST", headers, json.dumps(index),
            )
        )
        if resp["status_code"] != 201:
            raise RuntimeError(
                f"SearchIndex.create_if_none_exists: "
                f"{resp['status_code']} {resp['reason']}"
            )
        return True
